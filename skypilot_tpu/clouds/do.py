"""DigitalOcean: droplets + GPU droplets for cross-cloud cost ranking.

Parity: ``sky/clouds/do.py`` — region-only placement, no spot market,
stop/resume supported (powered-off droplets keep billing storage, like
stopped EC2). Lifecycle: ``provision/do`` (REST via curl + shared fake).
"""
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register(name='do', aliases=['digitalocean'])
class DO(simple_vm_cloud.SimpleVmCloud):
    """DigitalOcean."""

    _REPR = 'DO'
    _CLOUD_KEY = 'do'
    _HAS_SPOT = False
    _EGRESS_PER_GB = 0.01  # $0.01/GB beyond pooled allowance
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.do import do_api
        if do_api.api_token() is None:
            return False, ('DigitalOcean token not found. Set '
                           '$DIGITALOCEAN_TOKEN or run `doctl auth init`.')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.do import do_api
        token = do_api.api_token()
        return [f'do-token-{token[:8]}'] if token else None
