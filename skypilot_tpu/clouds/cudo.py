"""Cudo Compute: sustainable-datacenter GPU cloud.

Parity: ``sky/clouds/cudo.py`` — datacenters as regions, no spot market,
stop/resume supported. Lifecycle: ``provision/cudo`` (REST via curl +
shared fake).
"""
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register()
class Cudo(simple_vm_cloud.SimpleVmCloud):
    """Cudo Compute."""

    _REPR = 'Cudo'
    _CLOUD_KEY = 'cudo'
    _HAS_SPOT = False
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.cudo import cudo_api
        if cudo_api.api_key() is None:
            return False, ('Cudo API key not found. Set $CUDO_API_KEY or '
                           'run `cudoctl init` (~/.config/cudo/cudo.yml).')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.cudo import cudo_api
        key = cudo_api.api_key()
        return [f'cudo-key-{key[:8]}'] if key else None
