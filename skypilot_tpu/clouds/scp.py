"""Samsung Cloud Platform: Korean-region VMs + GPU servers.

Parity: ``sky/clouds/scp.py`` — service zones as regions, no spot
market, stop/resume supported. Lifecycle: ``provision/scp`` (open API
via curl + shared fake).
"""
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register()
class SCP(simple_vm_cloud.SimpleVmCloud):
    """Samsung Cloud Platform."""

    _REPR = 'SCP'
    _CLOUD_KEY = 'scp'
    _HAS_SPOT = False
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.scp import scp_api
        if scp_api.access_key() is None:
            return False, ('SCP access key not found. Set '
                           '$SCP_ACCESS_KEY or write it to '
                           '~/.scp/scp_credential.')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.scp import scp_api
        key = scp_api.access_key()
        return [f'scp-key-{key[:8]}'] if key else None
