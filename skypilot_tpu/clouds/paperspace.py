"""Paperspace (DigitalOcean Gradient): GPU machines.

Parity: ``sky/clouds/paperspace.py`` — region-only placement, no spot
market, stop/resume supported. Lifecycle: ``provision/paperspace``
(REST via curl + shared fake).
"""
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register()
class Paperspace(simple_vm_cloud.SimpleVmCloud):
    """Paperspace."""

    _REPR = 'Paperspace'
    _CLOUD_KEY = 'paperspace'
    _HAS_SPOT = False
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.paperspace import paperspace_api
        if paperspace_api.api_key() is None:
            return False, ('Paperspace API key not found. Set '
                           '$PAPERSPACE_API_KEY or log in with the '
                           'pspace CLI (~/.paperspace/config.json).')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.paperspace import paperspace_api
        key = paperspace_api.api_key()
        return [f'paperspace-key-{key[:8]}'] if key else None
