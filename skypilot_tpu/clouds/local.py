"""Local cloud: provisions "instances" as processes on this machine.

This plays the role the reference's Kubernetes-kind path plays for testing
(``sky local up``): a zero-credential backend the whole stack — optimizer,
provisioner, backend, skylet, jobs, serve — can run against end-to-end in CI.
Each "node" is a directory under ``~/.skytpu/local_cluster/<name>/<rank>`` and
commands run through the local CommandRunner (no SSH).
"""
import os
from typing import Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

_REGION = cloud.Region('local')
_REGION.set_zones([cloud.Zone('local-a')])


@CLOUD_REGISTRY.register()
class Local(cloud.Cloud):
    """The machine we are running on, as a cloud."""

    _REPR = 'Local'

    @classmethod
    def unsupported_features(
            cls, resources=None) -> Dict[cloud.CloudImplementationFeatures, str]:
        return {
            cloud.CloudImplementationFeatures.SPOT_INSTANCE:
                'Local cloud has no spot market.',
            cloud.CloudImplementationFeatures.IMAGE_ID:
                'Local cloud has no machine images.',
            # `ports:` IS supported — exposure is a no-op (loopback is
            # already reachable) but the declaration matters: the API
            # server's ws-proxy only tunnels declared ports, and smoke
            # scenarios declare ports on Local like any cloud.
        }

    def regions_with_offering(self, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        if use_spot or (region not in (None, 'local')):
            return []
        r = cloud.Region('local')
        z = cloud.Zone('local-a')
        z.region = 'local'
        r.zones = [z]
        return [r]

    def zones_provision_loop(self, *, region, num_nodes, instance_type,
                             accelerators=None, use_spot=False
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        for r in self.regions_with_offering(instance_type, accelerators,
                                            use_spot, region, None):
            yield r.zones

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return 0.0

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0

    def instance_type_exists(self, instance_type: str) -> bool:
        return instance_type.startswith('local')

    @classmethod
    def get_default_instance_type(cls, cpus=None, memory=None,
                                  disk_tier=None) -> Optional[str]:
        return 'local'

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type) -> Tuple[Optional[float], Optional[float]]:
        return float(os.cpu_count() or 1), None

    @classmethod
    def get_accelerators_from_instance_type(cls, instance_type):
        return None

    def get_feasible_launchable_resources(self, resources, num_nodes):
        if resources.accelerators is not None or resources.use_spot:
            return [], []
        if resources.region not in (None, 'local'):
            return [], []
        return [resources.copy(cloud=self, instance_type='local')], []

    def make_deploy_resources_variables(self, resources,
                                        cluster_name_on_cloud, region, zones,
                                        num_nodes) -> Dict[str, object]:
        return {
            'instance_type': 'local',
            'region': 'local',
            'zones': 'local-a',
            'num_nodes': num_nodes,
        }

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.utils import common_utils
        return [common_utils.get_user_hash()]
