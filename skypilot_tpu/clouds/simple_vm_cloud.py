"""Shared base for catalog-driven VM clouds.

Every cloud whose surface is "a CSV of priced SKUs + a provisioner"
(Lambda, RunPod, DigitalOcean, Fluidstack, Vast — and structurally AWS/
Azure, which keep their own classes for zone semantics and egress tiers)
implements the same nine methods against ``skypilot_tpu.catalog``. This
base parameterizes them by class attributes; subclasses add credentials,
feature gates, and any cloud-specific deploy variables.

Parity note: the reference repeats this surface per cloud under
``sky/clouds/*.py`` (~12k LoC); here it is one base + thin subclasses.
"""
from typing import Dict, Iterator, List, Optional

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import cloud


class SimpleVmCloud(cloud.Cloud):
    """Catalog-driven cloud with region-only (or pseudo-zone) placement."""

    # Subclasses pin these.
    _CLOUD_KEY = ''  # catalog csv key ('lambda' → lambda_vms.csv)
    _HAS_SPOT = True  # False → spot requests are infeasible here
    _EGRESS_PER_GB = 0.0  # flat internet egress $/GB (0 = unmetered)

    @classmethod
    def unsupported_features(
        cls,
        resources=None
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        del resources
        feats = {
            cloud.CloudImplementationFeatures.CLONE_DISK_FROM_CLUSTER:
                f'Disk cloning is not supported on {cls._REPR}.',
        }
        if not cls._HAS_SPOT:
            feats[cloud.CloudImplementationFeatures.SPOT_INSTANCE] = \
                f'{cls._REPR} has no spot market.'
        return feats

    # ----------------------------------------------------------- regions

    def regions_with_offering(self, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        del accelerators
        if instance_type is None:
            return []
        if use_spot and not self._HAS_SPOT:
            return []
        pairs = catalog.vm_regions_zones(instance_type, region, zone,
                                         cloud=self._CLOUD_KEY)
        return cloud.regions_from_catalog_pairs(pairs)

    def zones_provision_loop(self,
                             *,
                             region: str,
                             num_nodes: int,
                             instance_type: Optional[str],
                             accelerators=None,
                             use_spot: bool = False
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        # Region-only placement: each region's (pseudo-)zone set is one
        # failover try.
        del num_nodes
        for r in self.regions_with_offering(instance_type, accelerators,
                                            use_spot, region, None):
            yield r.zones

    # ----------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        del zone
        price = catalog.get_hourly_cost(instance_type, region, use_spot,
                                        cloud=self._CLOUD_KEY)
        if price is None:
            raise exceptions.ResourcesUnavailableError(
                f'No {self._REPR} pricing for {instance_type} in '
                f'{region}.')
        return price

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        # GPU cost is folded into the instance price.
        del accelerators, use_spot, region, zone
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return max(num_gigabytes, 0.0) * self._EGRESS_PER_GB

    # ----------------------------------------------------------- catalog

    def instance_type_exists(self, instance_type: str) -> bool:
        return catalog.instance_type_exists(instance_type,
                                            cloud=self._CLOUD_KEY)

    @classmethod
    def get_default_instance_type(cls,
                                  cpus=None,
                                  memory=None,
                                  disk_tier=None) -> Optional[str]:
        del disk_tier
        return catalog.get_default_instance_type(cpus, memory,
                                                 cloud=cls._CLOUD_KEY)

    @classmethod
    def get_vcpus_mem_from_instance_type(cls, instance_type):
        return catalog.get_vcpus_mem_from_instance_type(
            instance_type, cloud=cls._CLOUD_KEY)

    @classmethod
    def get_accelerators_from_instance_type(cls, instance_type):
        return catalog.get_accelerators_from_instance_type(
            instance_type, cloud=cls._CLOUD_KEY)

    def get_feasible_launchable_resources(self, resources, num_nodes):
        from skypilot_tpu import topology as topo_lib
        del num_nodes
        if resources.use_spot and not self._HAS_SPOT:
            return [], []
        if resources.instance_type is not None and \
                resources.accelerators is None:
            if not self.instance_type_exists(resources.instance_type):
                return [], []
            return [resources.copy(cloud=self)], []

        accs = resources.accelerators
        if accs is None:
            instance_type = self.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return [], []
            return [
                resources.copy(cloud=self, instance_type=instance_type)
            ], []

        acc_name, acc_count = next(iter(accs.items()))
        if topo_lib.is_tpu_accelerator(acc_name):
            return [], []  # TPUs live on GCP / GKE
        instance_types = catalog.get_instance_type_for_accelerator(
            acc_name,
            acc_count,
            cpus=resources.cpus,
            memory=resources.memory,
            region=resources.region,
            zone=resources.zone,
            cloud=self._CLOUD_KEY)
        if not instance_types:
            return [], catalog.fuzzy_accelerator_hints(
                acc_name, self._REPR)
        return [
            resources.copy(cloud=self, instance_type=instance_types[0])
        ], []

    # ----------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources,
                                        cluster_name_on_cloud, region, zones,
                                        num_nodes) -> Dict[str, object]:
        del cluster_name_on_cloud
        return {
            'instance_type': resources.instance_type,
            'region': region.name,
            'zones': ','.join(z.name for z in zones) if zones else None,
            'use_spot': resources.use_spot and self._HAS_SPOT,
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'num_nodes': num_nodes,
        }
