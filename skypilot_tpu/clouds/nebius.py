"""Nebius: European H100 GPU cloud (pairs with the Nebius object store).

Parity: ``sky/clouds/nebius.py`` — region-only placement, no spot
market, stop/resume supported. Lifecycle: ``provision/nebius`` (REST via
curl + shared fake).
"""
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register()
class Nebius(simple_vm_cloud.SimpleVmCloud):
    """Nebius AI Cloud."""

    _REPR = 'Nebius'
    _CLOUD_KEY = 'nebius'
    _HAS_SPOT = False
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.nebius import nebius_api
        if nebius_api.iam_token() is None:
            return False, ('Nebius IAM token not found. Set '
                           '$NEBIUS_IAM_TOKEN or write it to '
                           '~/.nebius/iam_token.')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.nebius import nebius_api
        token = nebius_api.iam_token()
        return [f'nebius-token-{token[:8]}'] if token else None
