"""Fluidstack: dedicated GPU servers for cross-cloud cost ranking.

Parity: ``sky/clouds/fluidstack.py`` — region-only placement, no spot
market, stop/resume supported. Lifecycle: ``provision/fluidstack``
(REST via curl + shared fake).
"""
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register()
class Fluidstack(simple_vm_cloud.SimpleVmCloud):
    """Fluidstack (GPU cloud)."""

    _REPR = 'Fluidstack'
    _CLOUD_KEY = 'fluidstack'
    _HAS_SPOT = False
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.fluidstack import fluidstack_api
        if fluidstack_api.api_key() is None:
            return False, ('Fluidstack API key not found. Set '
                           '$FLUIDSTACK_API_KEY or write it to '
                           '~/.fluidstack/api_key.')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.fluidstack import fluidstack_api
        key = fluidstack_api.api_key()
        return [f'fluidstack-key-{key[:8]}'] if key else None
