"""Lambda Cloud: single-tenant GPU boxes for cross-cloud cost ranking.

Parity: ``sky/clouds/lambda_cloud.py`` — a flat-priced GPU neocloud with
region-only placement (no zones), no spot market, and no stop/resume
(instances only run or terminate). Instance lifecycle is served by
``provision/lambda_cloud`` (REST API via curl + in-memory fake).
"""
import os
from typing import Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

_CLOUD = 'lambda'


@CLOUD_REGISTRY.register(name='lambda', aliases=['lambdacloud'])
class Lambda(cloud.Cloud):
    """Lambda Cloud (GPU cloud)."""

    _REPR = 'Lambda'
    # Lambda instance names cap at 64 chars; keep suffix headroom.
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def unsupported_features(
        cls,
        resources=None
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        del resources
        return {
            cloud.CloudImplementationFeatures.STOP:
                'Lambda instances cannot be stopped; only terminated.',
            cloud.CloudImplementationFeatures.AUTOSTOP:
                'Autostop requires stop support, which Lambda lacks.',
            cloud.CloudImplementationFeatures.SPOT_INSTANCE:
                'Lambda has no spot market.',
            cloud.CloudImplementationFeatures.CLONE_DISK_FROM_CLUSTER:
                'Disk cloning is not supported on Lambda.',
        }

    # ----------------------------------------------------------- regions

    def regions_with_offering(self, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        del accelerators
        if use_spot or instance_type is None:
            return []
        pairs = catalog.vm_regions_zones(instance_type, region, zone,
                                         cloud=_CLOUD)
        return cloud.regions_from_catalog_pairs(pairs)

    def zones_provision_loop(self,
                             *,
                             region: str,
                             num_nodes: int,
                             instance_type: Optional[str],
                             accelerators=None,
                             use_spot: bool = False
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        # Region-only placement: yield each region's pseudo-zone (the
        # region name itself) so the failover walk is one try per region.
        del num_nodes
        for r in self.regions_with_offering(instance_type, accelerators,
                                            use_spot, region, None):
            yield r.zones

    # ----------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        del zone
        price = catalog.get_hourly_cost(instance_type, region, use_spot,
                                        cloud=_CLOUD)
        if price is None:
            raise exceptions.ResourcesUnavailableError(
                f'No Lambda pricing for {instance_type} in {region}.')
        return price

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        # GPU cost is folded into the instance price.
        del accelerators, use_spot, region, zone
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Lambda does not meter egress.
        del num_gigabytes
        return 0.0

    # ----------------------------------------------------------- catalog

    def instance_type_exists(self, instance_type: str) -> bool:
        return catalog.instance_type_exists(instance_type, cloud=_CLOUD)

    @classmethod
    def get_default_instance_type(cls,
                                  cpus=None,
                                  memory=None,
                                  disk_tier=None) -> Optional[str]:
        del disk_tier
        return catalog.get_default_instance_type(cpus, memory, cloud=_CLOUD)

    @classmethod
    def get_vcpus_mem_from_instance_type(cls, instance_type):
        return catalog.get_vcpus_mem_from_instance_type(instance_type,
                                                        cloud=_CLOUD)

    @classmethod
    def get_accelerators_from_instance_type(cls, instance_type):
        return catalog.get_accelerators_from_instance_type(instance_type,
                                                           cloud=_CLOUD)

    def get_feasible_launchable_resources(self, resources, num_nodes):
        from skypilot_tpu import topology as topo_lib
        del num_nodes
        if resources.use_spot:
            return [], []  # no spot market
        if resources.instance_type is not None and \
                resources.accelerators is None:
            if not self.instance_type_exists(resources.instance_type):
                return [], []
            return [resources.copy(cloud=self)], []

        accs = resources.accelerators
        if accs is None:
            instance_type = self.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return [], []
            return [
                resources.copy(cloud=self, instance_type=instance_type)
            ], []

        acc_name, acc_count = next(iter(accs.items()))
        if topo_lib.is_tpu_accelerator(acc_name):
            return [], []  # TPUs live on GCP / GKE
        instance_types = catalog.get_instance_type_for_accelerator(
            acc_name,
            acc_count,
            cpus=resources.cpus,
            memory=resources.memory,
            region=resources.region,
            zone=resources.zone,
            cloud=_CLOUD)
        if not instance_types:
            return [], catalog.fuzzy_accelerator_hints(acc_name, 'Lambda')
        return [
            resources.copy(cloud=self, instance_type=instance_types[0])
        ], []

    # ----------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources,
                                        cluster_name_on_cloud, region, zones,
                                        num_nodes) -> Dict[str, object]:
        del cluster_name_on_cloud
        return {
            'instance_type': resources.instance_type,
            'region': region.name,
            'zones': ','.join(z.name for z in zones) if zones else None,
            'use_spot': False,
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'num_nodes': num_nodes,
        }

    # ----------------------------------------------------------- identity

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if cls._api_key() is None:
            return False, ('Lambda API key not found. Put it in '
                           '~/.lambda_cloud/lambda_keys (api_key = ...) '
                           'or set LAMBDA_API_KEY.')
        return True, None

    @staticmethod
    def _api_key() -> Optional[str]:
        key = os.environ.get('LAMBDA_API_KEY')
        if key:
            return key
        path = os.path.expanduser('~/.lambda_cloud/lambda_keys')
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                for line in f:
                    if line.strip().startswith('api_key') and '=' in line:
                        return line.split('=', 1)[1].strip()
        return None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        key = cls._api_key()
        # The API has no whoami; the key prefix identifies the account.
        return [f'lambda-key-{key[:8]}'] if key else None
