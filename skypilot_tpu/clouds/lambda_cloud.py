"""Lambda Cloud: single-tenant GPU boxes for cross-cloud cost ranking.

Parity: ``sky/clouds/lambda_cloud.py`` — a flat-priced GPU neocloud with
region-only placement (no zones), no spot market, and no stop/resume
(instances only run or terminate). Instance lifecycle is served by
``provision/lambda_cloud`` (REST API via curl + in-memory fake).
"""
import os
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register(name='lambda', aliases=['lambdacloud'])
class Lambda(simple_vm_cloud.SimpleVmCloud):
    """Lambda Cloud (GPU cloud)."""

    _REPR = 'Lambda'
    _CLOUD_KEY = 'lambda'
    _HAS_SPOT = False
    # Lambda instance names cap at 64 chars; keep suffix headroom.
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def unsupported_features(
        cls,
        resources=None
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        feats = super().unsupported_features(resources)
        feats.update({
            cloud.CloudImplementationFeatures.STOP:
                'Lambda instances cannot be stopped; only terminated.',
            cloud.CloudImplementationFeatures.AUTOSTOP:
                'Autostop requires stop support, which Lambda lacks.',
        })
        return feats

    # ----------------------------------------------------------- identity

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if cls._api_key() is None:
            return False, ('Lambda API key not found. Put it in '
                           '~/.lambda_cloud/lambda_keys (api_key = ...) '
                           'or set LAMBDA_API_KEY.')
        return True, None

    @staticmethod
    def _api_key() -> Optional[str]:
        key = os.environ.get('LAMBDA_API_KEY')
        if key:
            return key
        path = os.path.expanduser('~/.lambda_cloud/lambda_keys')
        if os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                for line in f:
                    if line.strip().startswith('api_key') and '=' in line:
                        return line.split('=', 1)[1].strip()
        return None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        key = cls._api_key()
        # The API has no whoami; the key prefix identifies the account.
        return [f'lambda-key-{key[:8]}'] if key else None
