"""Azure cloud: GPU/CPU instances for cross-cloud cost ranking.

Parity: ``sky/clouds/azure.py`` — catalog / feasibility / pricing
surface plus credential checks so the optimizer can rank Azure GPU SKUs
(ND A100/H100 series) against TPU slices; instance lifecycle is served
by ``provision/azure`` (az CLI + in-memory fake), and `sky check` gates
the cloud off without az credentials.
"""
import subprocess
from typing import Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

_CLOUD = 'azure'


@CLOUD_REGISTRY.register()
class Azure(cloud.Cloud):
    """Microsoft Azure."""

    _REPR = 'Azure'
    # Azure resource-group derived names: keep headroom under 64.
    _MAX_CLUSTER_NAME_LEN_LIMIT = 42

    @classmethod
    def unsupported_features(
        cls,
        resources=None
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        del resources
        return {
            cloud.CloudImplementationFeatures.CLONE_DISK_FROM_CLUSTER:
                'Disk cloning is not supported yet on Azure.',
        }

    # ----------------------------------------------------------- regions

    def regions_with_offering(self, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        del accelerators, use_spot
        if instance_type is None:
            return []
        pairs = catalog.vm_regions_zones(instance_type, region, zone,
                                         cloud=_CLOUD)
        regions: Dict[str, cloud.Region] = {}
        for r, z in pairs:
            regions.setdefault(r, cloud.Region(r))
            zone_obj = cloud.Zone(z)
            zone_obj.region = r
            regions[r].zones.append(zone_obj)
        return list(regions.values())

    def zones_provision_loop(self,
                             *,
                             region: str,
                             num_nodes: int,
                             instance_type: Optional[str],
                             accelerators=None,
                             use_spot: bool = False
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        # Azure provisions per-region (zones are a placement hint); yield
        # the region's zone set at once (parity: azure.py region loop).
        del num_nodes
        for r in self.regions_with_offering(instance_type, accelerators,
                                            use_spot, region, None):
            yield r.zones

    # ----------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        del zone
        price = catalog.get_hourly_cost(instance_type, region, use_spot,
                                        cloud=_CLOUD)
        if price is None:
            raise exceptions.ResourcesUnavailableError(
                f'No Azure pricing for {instance_type} in {region}.')
        return price

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        # GPU cost is folded into the hosting instance price.
        del accelerators, use_spot, region, zone
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Parity: sky/clouds/azure.py egress tiers (internet egress).
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 10 * 1024:
            return num_gigabytes * 0.087
        cost = 10 * 1024 * 0.087
        if num_gigabytes <= 50 * 1024:
            return cost + (num_gigabytes - 10 * 1024) * 0.083
        return cost + 40 * 1024 * 0.083 + (num_gigabytes - 50 * 1024) * 0.07

    # ----------------------------------------------------------- catalog

    def instance_type_exists(self, instance_type: str) -> bool:
        return catalog.instance_type_exists(instance_type, cloud=_CLOUD)

    @classmethod
    def get_default_instance_type(cls,
                                  cpus=None,
                                  memory=None,
                                  disk_tier=None) -> Optional[str]:
        del disk_tier
        return catalog.get_default_instance_type(cpus, memory, cloud=_CLOUD)

    @classmethod
    def get_vcpus_mem_from_instance_type(cls, instance_type):
        return catalog.get_vcpus_mem_from_instance_type(instance_type,
                                                        cloud=_CLOUD)

    @classmethod
    def get_accelerators_from_instance_type(cls, instance_type):
        return catalog.get_accelerators_from_instance_type(instance_type,
                                                           cloud=_CLOUD)

    def get_feasible_launchable_resources(self, resources, num_nodes):
        from skypilot_tpu import topology as topo_lib
        del num_nodes
        if resources.instance_type is not None and \
                resources.accelerators is None:
            if not self.instance_type_exists(resources.instance_type):
                return [], []
            return [resources.copy(cloud=self)], []

        accs = resources.accelerators
        if accs is None:
            instance_type = self.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return [], []
            return [
                resources.copy(cloud=self, instance_type=instance_type)
            ], []

        acc_name, acc_count = next(iter(accs.items()))
        if topo_lib.is_tpu_accelerator(acc_name):
            return [], []  # TPUs live on GCP / GKE
        instance_types = catalog.get_instance_type_for_accelerator(
            acc_name,
            acc_count,
            cpus=resources.cpus,
            memory=resources.memory,
            region=resources.region,
            zone=resources.zone,
            cloud=_CLOUD)
        if not instance_types:
            return [], catalog.fuzzy_accelerator_hints(acc_name, 'Azure')
        return [
            resources.copy(cloud=self, instance_type=instance_types[0])
        ], []

    # ----------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources,
                                        cluster_name_on_cloud, region, zones,
                                        num_nodes) -> Dict[str, object]:
        del cluster_name_on_cloud
        return {
            'instance_type': resources.instance_type,
            'region': region.name,
            'zones': ','.join(z.name for z in zones) if zones else None,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'num_nodes': num_nodes,
        }

    # ----------------------------------------------------------- identity

    @staticmethod
    def _az_query(field: str) -> Optional[str]:
        try:
            proc = subprocess.run(
                ['az', 'account', 'show', '--query', field, '-o', 'tsv'],
                capture_output=True,
                text=True,
                timeout=20,
                check=False)
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return None
        out = proc.stdout.strip()
        return out if proc.returncode == 0 and out else None

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if cls._az_query('id') is None:
            return False, ('Azure credentials not configured (or az CLI '
                           'missing). Run `az login`.')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        user = cls._az_query('user.name')
        return [user] if user else None
