"""Cloud registry (parity: ``sky/clouds/__init__.py``)."""
from skypilot_tpu.clouds.aws import AWS
from skypilot_tpu.clouds.azure import Azure
from skypilot_tpu.clouds.cloud import Cloud
from skypilot_tpu.clouds.cloud import CloudImplementationFeatures
from skypilot_tpu.clouds.cloud import Region
from skypilot_tpu.clouds.cloud import Zone
from skypilot_tpu.clouds.cudo import Cudo
from skypilot_tpu.clouds.do import DO
from skypilot_tpu.clouds.fluidstack import Fluidstack
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.ibm import IBM
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.lambda_cloud import Lambda
from skypilot_tpu.clouds.local import Local
from skypilot_tpu.clouds.nebius import Nebius
from skypilot_tpu.clouds.oci import OCI
from skypilot_tpu.clouds.paperspace import Paperspace
from skypilot_tpu.clouds.runpod import RunPod
from skypilot_tpu.clouds.scp import SCP
from skypilot_tpu.clouds.vast import Vast
from skypilot_tpu.clouds.vsphere import Vsphere

__all__ = [
    'AWS',
    'Azure',
    'Cloud',
    'CloudImplementationFeatures',
    'Cudo',
    'DO',
    'Fluidstack',
    'GCP',
    'IBM',
    'Kubernetes',
    'Lambda',
    'Local',
    'Nebius',
    'OCI',
    'Paperspace',
    'Region',
    'RunPod',
    'SCP',
    'Vast',
    'Vsphere',
    'Zone',
]
