"""Oracle Cloud Infrastructure: VMs + bare-metal GPU shapes.

Parity: ``sky/clouds/oci.py`` — availability domains modeled as each
region's pseudo-zone, spot = preemptible instances (terminate on
reclaim), stop/resume supported. Lifecycle: ``provision/oci`` (oci CLI
+ shared fake).
"""
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register(name='oci', aliases=['oracle'])
class OCI(simple_vm_cloud.SimpleVmCloud):
    """Oracle Cloud Infrastructure."""

    _REPR = 'OCI'
    _CLOUD_KEY = 'oci'
    _HAS_SPOT = True  # preemptible instances
    _EGRESS_PER_GB = 0.0085  # beyond the free 10TB/month tier
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        import os
        import subprocess
        if not os.path.exists(os.path.expanduser('~/.oci/config')):
            return False, ('OCI config not found: run `oci setup config` '
                           '(~/.oci/config).')
        try:
            proc = subprocess.run(['oci', 'iam', 'region', 'list'],
                                  capture_output=True,
                                  timeout=30,
                                  check=False)
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return False, 'oci CLI missing or unresponsive.'
        if proc.returncode != 0:
            return False, 'OCI credentials rejected (`oci iam` failed).'
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.oci import oci_api
        compartment = oci_api.compartment_id()
        return [f'oci-{compartment[-12:]}'] if compartment else None
