"""GCP cloud with TPU pod slices as the first-class target.

Parity: ``sky/clouds/gcp.py`` (TPU logic at :207-217,255,474-498,547-553,
614-665) — redesigned so that a TPU request resolves through
``skypilot_tpu.topology`` rather than string special-cases:

* ``instance_type`` for TPU slices is the sentinel ``'TPU-VM'`` (parity
  gcp.py:255); host vCPU/RAM come from the generation table.
* TPU pods cannot STOP (only delete) — reflected in unsupported_features
  (parity gcp.py:207-213).
* Deploy variables carry the resolved slice: ``accelerator_type`` (GCP API
  name like ``v5p-128``), ``topology``, ``runtime_version``, ``num_hosts``.
"""
import subprocess
from typing import Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu import skypilot_config
from skypilot_tpu import topology as topo_lib
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

TPU_VM_INSTANCE_TYPE = 'TPU-VM'

# Default TPU software (runtime) versions per generation.
# Parity: sky/resources.py:615-631 runtime_version defaulting matrix.
_DEFAULT_RUNTIME_VERSIONS = {
    'v2': 'tpu-vm-base',
    'v3': 'tpu-vm-base',
    'v4': 'tpu-vm-v4-base',
    'v5e': 'v2-alpha-tpuv5-lite',
    'v5p': 'v2-alpha-tpuv5',
    'v6e': 'v2-alpha-tpuv6e',
}


@CLOUD_REGISTRY.register()
class GCP(cloud.Cloud):
    """Google Cloud Platform."""

    _REPR = 'GCP'
    _MAX_CLUSTER_NAME_LEN_LIMIT = 35

    @classmethod
    def unsupported_features(
        cls,
        resources=None
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        feats: Dict[cloud.CloudImplementationFeatures, str] = {
            cloud.CloudImplementationFeatures.CLONE_DISK_FROM_CLUSTER:
                'Disk cloning is not supported yet on GCP.',
        }
        if resources is not None and resources.tpu_topology is not None:
            if resources.tpu_topology.is_pod:
                # Parity: sky/clouds/gcp.py:207-213 — multi-host TPU slices
                # cannot be stopped, only deleted.
                feats[cloud.CloudImplementationFeatures.STOP] = (
                    'Multi-host TPU slices do not support stopping; only '
                    'tearing down (delete).')
                feats[cloud.CloudImplementationFeatures.AUTOSTOP] = (
                    'Multi-host TPU slices support autodown, not autostop.')
        return feats

    # ----------------------------------------------------------- topology

    def regions_with_offering(self, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        if instance_type == TPU_VM_INSTANCE_TYPE:
            assert accelerators, 'TPU-VM requires a TPU accelerator'
            acc_name = next(iter(accelerators))
            gen = topo_lib.parse_generation(acc_name)
            pairs = catalog.tpu_regions_zones(gen.name, region, zone)
        elif instance_type is not None:
            pairs = catalog.vm_regions_zones(instance_type, region, zone)
        else:
            pairs = []
        return cloud.regions_from_catalog_pairs(pairs)

    def zones_provision_loop(self,
                             *,
                             region: str,
                             num_nodes: int,
                             instance_type: Optional[str],
                             accelerators=None,
                             use_spot: bool = False
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        # GCP provisions per-zone: yield one zone at a time (parity:
        # gcp.py zones_provision_loop yields singleton zone lists).
        del num_nodes
        for r in self.regions_with_offering(instance_type, accelerators,
                                            use_spot, region, None):
            for z in r.zones:
                yield [z]

    # ----------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        if instance_type == TPU_VM_INSTANCE_TYPE:
            # TPU slice cost is carried entirely by accelerators_to_hourly_cost
            # (chip price includes the host; parity gcp_catalog.py:243-254).
            return 0.0
        price = catalog.get_hourly_cost(instance_type, region, use_spot)
        if price is None:
            raise exceptions.ResourcesUnavailableError(
                f'No pricing for {instance_type} in {region}.')
        return price

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        acc_name, acc_count = next(iter(accelerators.items()))
        if topo_lib.is_tpu_accelerator(acc_name):
            gen = topo_lib.parse_generation(acc_name)
            if region is None:
                pairs = catalog.tpu_regions_zones(gen.name)
                if not pairs:
                    raise exceptions.ResourcesUnavailableError(
                        f'No region offers {acc_name}.')
                region = pairs[0][0]
            per_chip = catalog.tpu_price_per_chip_hour(gen.name, region,
                                                       use_spot)
            if per_chip is None:
                raise exceptions.ResourcesUnavailableError(
                    f'No TPU pricing for {acc_name} in {region}.')
            return per_chip * acc_count
        # GPUs on GCP are priced as part of the hosting a2/a3/g2 instance in
        # our catalog; no extra accelerator cost.
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Parity: sky/clouds/gcp.py egress tiers.
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 1024:
            return num_gigabytes * 0.12
        if num_gigabytes <= 10240:
            return 1024 * 0.12 + (num_gigabytes - 1024) * 0.11
        return 1024 * 0.12 + 9216 * 0.11 + (num_gigabytes - 10240) * 0.08

    # ----------------------------------------------------------- catalog

    def instance_type_exists(self, instance_type: str) -> bool:
        return (instance_type == TPU_VM_INSTANCE_TYPE or
                catalog.instance_type_exists(instance_type))

    @classmethod
    def get_default_instance_type(cls,
                                  cpus=None,
                                  memory=None,
                                  disk_tier=None) -> Optional[str]:
        del disk_tier
        return catalog.get_default_instance_type(cpus, memory)

    @classmethod
    def get_vcpus_mem_from_instance_type(cls, instance_type):
        if instance_type == TPU_VM_INSTANCE_TYPE:
            return None, None  # depends on generation; handled via topology
        return catalog.get_vcpus_mem_from_instance_type(instance_type)

    @classmethod
    def get_accelerators_from_instance_type(cls, instance_type):
        if instance_type == TPU_VM_INSTANCE_TYPE:
            return None  # accelerators are the request, not derived
        return catalog.get_accelerators_from_instance_type(instance_type)

    def get_feasible_launchable_resources(self, resources, num_nodes):
        """Resolve a partial request into launchable candidates.

        TPU path: any `tpu-*` accelerator ⇒ instance_type='TPU-VM', regions
        from the TPU catalog. GPU path: catalog SKU lookup. CPU path: default
        instance type. Parity: sky/clouds/gcp.py _get_feasible... +
        cloud.py:385.
        """
        from skypilot_tpu import resources as resources_lib
        del num_nodes
        if resources.instance_type is not None and resources.accelerators is None:
            if not self.instance_type_exists(resources.instance_type):
                return [], []
            return [resources.copy(cloud=self)], []

        accs = resources.accelerators
        if accs is None:
            instance_type = self.get_default_instance_type(
                resources.cpus, resources.memory)
            if instance_type is None:
                return [], []
            return [
                resources.copy(cloud=self, instance_type=instance_type)
            ], []

        acc_name, acc_count = next(iter(accs.items()))
        if topo_lib.is_tpu_accelerator(acc_name):
            try:
                topo = topo_lib.resolve_topology(
                    acc_name, acc_count,
                    (resources.accelerator_args or {}).get('topology'))
            except exceptions.InvalidSkyError:
                raise
            pairs = catalog.tpu_regions_zones(topo.generation.name,
                                              resources.region,
                                              resources.zone)
            if not pairs:
                return [], []
            return [
                resources.copy(
                    cloud=self,
                    instance_type=TPU_VM_INSTANCE_TYPE,
                    accelerators={topo.name: topo.num_chips},
                )
            ], []

        instance_types = catalog.get_instance_type_for_accelerator(
            acc_name,
            acc_count,
            cpus=resources.cpus,
            memory=resources.memory,
            region=resources.region,
            zone=resources.zone)
        if not instance_types:
            return [], catalog.fuzzy_accelerator_hints(acc_name, 'GCP')
        return [
            resources.copy(cloud=self, instance_type=instance_types[0])
        ], []

    # ----------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources,
                                        cluster_name_on_cloud, region, zones,
                                        num_nodes) -> Dict[str, object]:
        zone = zones[0].name if zones else None
        vars_: Dict[str, object] = {
            'instance_type': resources.instance_type,
            'region': region.name,
            'zones': zone,
            'use_spot': resources.use_spot,
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'labels': dict(resources.labels or {}),
            'num_nodes': num_nodes,
        }
        topo = resources.tpu_topology
        if topo is not None:
            args = resources.accelerator_args or {}
            vars_.update({
                'tpu_vm': True,
                'tpu_node_name': cluster_name_on_cloud,
                'accelerator_type': topo.gcp_accelerator_type,
                'topology': topo.topology_str,
                'runtime_version': args.get(
                    'runtime_version',
                    _DEFAULT_RUNTIME_VERSIONS[topo.generation.name]),
                'num_hosts': topo.num_hosts,
                'chips_per_host': topo.chips_per_host,
                # Queued-resources (DWS-style) capacity: per-Resources
                # accelerator_args win over ~/.skytpu/config.yaml's
                # gcp.use_queued_resources / gcp.provision_timeout.
                'use_queued_resources': bool(
                    args.get(
                        'queued_resources',
                        skypilot_config.get_nested(
                            ('gcp', 'use_queued_resources'), False))),
                'provision_timeout': int(
                    args.get(
                        'provision_timeout',
                        skypilot_config.get_nested(
                            ('gcp', 'provision_timeout'), 900))),
            })
        elif resources.accelerators:
            acc_name, acc_count = next(iter(resources.accelerators.items()))
            vars_.update({
                'gpu': acc_name,
                'gpu_count': int(acc_count),
            })
        return vars_

    # ----------------------------------------------------------- identity

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        try:
            proc = subprocess.run(
                ['gcloud', 'auth', 'list',
                 '--filter=status:ACTIVE', '--format=value(account)'],
                capture_output=True,
                text=True,
                timeout=20,
                check=False)
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return False, ('gcloud CLI not found or not responding. Install '
                           'the Google Cloud SDK and run `gcloud auth login`.')
        account = proc.stdout.strip()
        if proc.returncode != 0 or not account:
            return False, 'No active gcloud account. Run `gcloud auth login`.'
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        try:
            proc = subprocess.run(
                ['gcloud', 'auth', 'list',
                 '--filter=status:ACTIVE', '--format=value(account)'],
                capture_output=True,
                text=True,
                timeout=20,
                check=False)
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return None
        account = proc.stdout.strip()
        return [account] if account else None
