"""Kubernetes cloud: GKE TPU podslice nodepools as first-class targets.

Parity: ``sky/clouds/kubernetes.py`` — redesigned around cluster-advertised
capacity instead of a static catalog: feasibility is decided by what the
nodes actually carry (GKE TPU labels ``cloud.google.com/gke-tpu-accelerator``
/ ``gke-tpu-topology`` and the ``google.com/tpu`` resource; parity
``sky/provision/kubernetes/utils.py:96-102``). Each kubeconfig context is a
"region"; there are no zones. Cost is 0 — the user already pays for the
cluster — so the optimizer prefers Kubernetes whenever it is feasible
(parity: the reference ranks k8s by instance-type heuristics; zero-cost is
the honest model for bring-your-own-cluster).
"""
import os
import re
import shutil
import time
from typing import Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import topology as topo_lib
from skypilot_tpu.clouds import cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

# Generation → GKE accelerator label value (parity: utils.py:96-102
# GKE_TPU_ACCELERATOR_TO_GENERATION, inverted; single-host v5e uses the
# -device flavor).
_GEN_TO_GKE_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}
_GKE_V5E_SINGLE_HOST = 'tpu-v5-lite-device'

# GKE GPU nodepool label key.
GKE_GPU_LABEL = 'cloud.google.com/gke-accelerator'

_INSTANCE_TYPE_RE = re.compile(r'^cpu(\d+)-mem(\d+)$')
_DEFAULT_INSTANCE_TYPE = 'cpu4-mem16'

# Accelerator name → GKE GPU nodepool label value
# (cloud.google.com/gke-accelerator; parity: the reference's
# GKELabelFormatter).
_GPU_TO_GKE_LABEL = {
    'T4': 'nvidia-tesla-t4',
    'L4': 'nvidia-l4',
    'V100': 'nvidia-tesla-v100',
    'P100': 'nvidia-tesla-p100',
    'A100': 'nvidia-tesla-a100',
    'A100-80GB': 'nvidia-a100-80gb',
    'H100': 'nvidia-h100-80gb',
}


def gke_accelerator_for(topo: topo_lib.TpuSliceTopology) -> Optional[str]:
    """GKE nodepool accelerator label for a slice, or None if GKE cannot
    host this generation (v2/v3 are not offered on GKE)."""
    gen = topo.generation.name
    if gen == 'v5e' and topo.num_hosts == 1:
        return _GKE_V5E_SINGLE_HOST
    return _GEN_TO_GKE_ACCELERATOR.get(gen)


@CLOUD_REGISTRY.register()
class Kubernetes(cloud.Cloud):
    """A Kubernetes cluster (GKE for TPU workloads)."""

    _REPR = 'Kubernetes'
    # Pod names: RFC 1123 + room for '-{node}-{host}' suffixes.
    _MAX_CLUSTER_NAME_LEN_LIMIT = 40

    @classmethod
    def unsupported_features(
            cls,
            resources=None) -> Dict[cloud.CloudImplementationFeatures, str]:
        return {
            cloud.CloudImplementationFeatures.STOP:
                'Kubernetes pods cannot be stopped, only terminated.',
            cloud.CloudImplementationFeatures.AUTOSTOP:
                'Kubernetes supports autodown, not autostop.',
            cloud.CloudImplementationFeatures.SPOT_INSTANCE:
                'Spot scheduling is owned by the cluster autoscaler, not '
                'the framework.',
            cloud.CloudImplementationFeatures.IMAGE_ID:
                'Use docker images (image_id: docker:<image>) instead of '
                'machine images.',
            cloud.CloudImplementationFeatures.CLONE_DISK_FROM_CLUSTER:
                'Pods have no clonable boot disks.',
        }

    # ----------------------------------------------------------- contexts

    @classmethod
    def existing_allowed_contexts(cls) -> List[str]:
        """Kubeconfig contexts this build may target (parity:
        sky/clouds/kubernetes.py context discovery + allowed_contexts).

        With ``kubernetes.allowed_contexts`` configured, ALL listed
        contexts are offered, in list order (they are the failover
        chain); otherwise only the current context.
        """
        from skypilot_tpu import skypilot_config
        allowed = skypilot_config.get_nested(('kubernetes', 'allowed_contexts'),
                                             None)
        if allowed is not None:
            return list(allowed)
        if os.environ.get('SKYTPU_K8S_FAKE', '0') == '1':
            return [os.environ.get('SKYTPU_K8S_FAKE_CONTEXT', 'fake-gke')]
        from skypilot_tpu.provision.kubernetes import k8s_api
        current = k8s_api.KubectlTransport().current_context()
        return [current] if current else []

    def regions_with_offering(self, instance_type, accelerators, use_spot,
                              region, zone) -> List[cloud.Region]:
        del instance_type, zone
        if use_spot:
            return []
        regions = []
        for ctx in self.existing_allowed_contexts():
            if region is not None and region != ctx:
                continue
            regions.append(cloud.Region(ctx))
        return regions

    def zones_provision_loop(self, *, region, num_nodes, instance_type,
                             accelerators=None, use_spot=False
                             ) -> Iterator[Optional[List[cloud.Zone]]]:
        del num_nodes, instance_type, accelerators, use_spot
        # A context has no zones; one shot per context.
        yield None

    # ----------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return 0.0

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0

    # ----------------------------------------------------------- catalog

    def instance_type_exists(self, instance_type: str) -> bool:
        return bool(_INSTANCE_TYPE_RE.match(instance_type))

    @classmethod
    def get_default_instance_type(cls, cpus=None, memory=None,
                                  disk_tier=None) -> Optional[str]:
        del disk_tier
        c = int(float(str(cpus).rstrip('+'))) if cpus else 4
        m = int(float(str(memory).rstrip('+'))) if memory else 4 * c
        return f'cpu{c}-mem{m}'

    @classmethod
    def get_vcpus_mem_from_instance_type(
            cls, instance_type) -> Tuple[Optional[float], Optional[float]]:
        m = _INSTANCE_TYPE_RE.match(instance_type)
        if not m:
            return None, None
        return float(m.group(1)), float(m.group(2))

    @classmethod
    def get_accelerators_from_instance_type(cls, instance_type):
        return None

    # -------------------------------------------------------- feasibility

    # context → (fetched_at, nodes). Each optimizer pass calls feasibility
    # once per candidate; without a cache that is one kubectl subprocess
    # per candidate per context.
    _node_cache: Dict[str, Tuple[float, List[dict]]] = {}
    _NODE_CACHE_TTL = 10.0

    @classmethod
    def _cluster_nodes(cls, context: str) -> List[dict]:
        from skypilot_tpu.provision.kubernetes import k8s_api
        hit = cls._node_cache.get(context)
        if hit is not None and time.time() - hit[0] < cls._NODE_CACHE_TTL:
            return hit[1]
        try:
            nodes = k8s_api.make_client(context).list_nodes()
        except Exception:  # pylint: disable=broad-except
            # Transient API failure: serve the stale snapshot if we have
            # one, and never negatively-cache an empty list.
            return hit[1] if hit is not None else []
        cls._node_cache[context] = (time.time(), nodes)
        return nodes

    @classmethod
    def _tpu_offerings(cls, context: str) -> List[Tuple[str, str]]:
        """(gke_accelerator, topology) pairs the cluster's nodes advertise."""
        from skypilot_tpu.provision.kubernetes import k8s_api
        out = []
        for node in cls._cluster_nodes(context):
            labels = node.get('metadata', {}).get('labels', {})
            accel = labels.get(k8s_api.GKE_TPU_ACCELERATOR_LABEL)
            topo = labels.get(k8s_api.GKE_TPU_TOPOLOGY_LABEL)
            if accel and topo:
                out.append((accel, topo))
        return out

    def get_feasible_launchable_resources(self, resources, num_nodes):
        del num_nodes
        if resources.use_spot:
            return [], []
        allowed = self.existing_allowed_contexts()
        if resources.region is not None:
            if resources.region not in allowed:
                return [], []
            contexts = [resources.region]
        else:
            contexts = allowed
        if not contexts:
            return [], []

        accs = resources.accelerators
        if accs is None:
            instance_type = (resources.instance_type if resources.instance_type
                             and self.instance_type_exists(
                                 resources.instance_type) else
                             self.get_default_instance_type(
                                 resources.cpus, resources.memory))
            return [
                resources.copy(cloud=self, instance_type=instance_type)
            ], []

        # A user-pinned pod shape wins; otherwise derive from cpus/memory.
        pod_shape = (resources.instance_type
                     if resources.instance_type and
                     self.instance_type_exists(resources.instance_type)
                     else self.get_default_instance_type(
                         resources.cpus, resources.memory))

        acc_name, acc_count = next(iter(accs.items()))
        if not topo_lib.is_tpu_accelerator(acc_name):
            # GPU pods: feasible when a node advertises the matching GKE
            # GPU nodepool label with enough nvidia.com/gpu allocatable.
            wanted_label = _GPU_TO_GKE_LABEL.get(acc_name)

            def _advertised(ctx_list) -> List[str]:
                """Accelerator NAMES the cluster's GPU nodepools offer."""
                reverse = {v: k for k, v in _GPU_TO_GKE_LABEL.items()}
                return sorted({
                    reverse[lbl]
                    for ctx in ctx_list for node in self._cluster_nodes(ctx)
                    for lbl in [node.get('metadata', {}).get(
                        'labels', {}).get(GKE_GPU_LABEL)]
                    if lbl in reverse
                })

            if wanted_label is None:
                return [], _advertised(contexts)
            if acc_count != int(acc_count):
                # nvidia.com/gpu is an integer resource; truncating would
                # silently schedule a 0-GPU pod.
                return [], _advertised(contexts)
            from skypilot_tpu.provision.kubernetes import k8s_api
            for ctx in contexts:
                for node in self._cluster_nodes(ctx):
                    labels = node.get('metadata', {}).get('labels', {})
                    alloc = node.get('status', {}).get('allocatable', {})
                    if (labels.get(GKE_GPU_LABEL) == wanted_label and
                            float(alloc.get(k8s_api.GPU_RESOURCE_KEY,
                                            0)) >= acc_count):
                        return [
                            resources.copy(
                                cloud=self,
                                region=ctx if resources.region else None,
                                instance_type=pod_shape,
                            )
                        ], []
            return [], _advertised(contexts)
        topo = topo_lib.resolve_topology(
            acc_name, acc_count,
            (resources.accelerator_args or {}).get('topology'))
        wanted = gke_accelerator_for(topo)
        if wanted is None:
            return [], [f'{topo.name} is not offered on GKE']
        seen_offerings: List[Tuple[str, str]] = []
        for ctx in contexts:
            offerings = self._tpu_offerings(ctx)
            seen_offerings.extend(offerings)
            if (wanted, topo.topology_str) in offerings:
                return [
                    resources.copy(
                        cloud=self,
                        region=ctx if resources.region else None,
                        instance_type=pod_shape,
                        accelerators={topo.name: topo.num_chips},
                    )
                ], []
        hints = sorted({f'{a} ({t})' for a, t in seen_offerings})
        return [], hints

    # ----------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources,
                                        cluster_name_on_cloud, region, zones,
                                        num_nodes) -> Dict[str, object]:
        del zones
        cpus, mem = self.get_vcpus_mem_from_instance_type(
            resources.instance_type or _DEFAULT_INSTANCE_TYPE)
        image = resources.extract_docker_image()
        vars_: Dict[str, object] = {
            'instance_type': resources.instance_type,
            'region': region.name,   # kubeconfig context
            'num_nodes': num_nodes,
            'cpus': cpus,
            'memory': mem,
            'image': image,
        }
        topo = resources.tpu_topology
        if topo is not None:
            vars_.update({
                'tpu_accelerator': gke_accelerator_for(topo),
                'tpu_topology': topo.topology_str,
                'accelerator_type': topo.gcp_accelerator_type,
                'num_hosts': topo.num_hosts,
                'chips_per_host': topo.chips_per_host,
            })
        elif resources.accelerators:
            acc_name, acc_count = next(iter(resources.accelerators.items()))
            label = _GPU_TO_GKE_LABEL.get(acc_name)
            if label is None:
                # Feasibility gates this; fail fast if reached directly —
                # an empty selector value would pin the pod to nowhere.
                from skypilot_tpu import exceptions
                raise exceptions.InvalidSkyError(
                    f'{acc_name} has no GKE nodepool label (supported: '
                    f'{sorted(_GPU_TO_GKE_LABEL)}).')
            vars_.update({
                'gpu': acc_name,
                'gpu_count': int(acc_count),
                'node_selector': {GKE_GPU_LABEL: label},
            })
        return vars_

    # ----------------------------------------------------------- identity

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_K8S_FAKE', '0') == '1':
            return True, None
        if shutil.which('kubectl') is None:
            return False, ('kubectl not found. Install kubectl and '
                           'configure a kubeconfig context.')
        from skypilot_tpu.provision.kubernetes import k8s_api
        ctx = k8s_api.KubectlTransport().current_context()
        if not ctx:
            return False, ('No current kubeconfig context. Run `kubectl '
                           'config use-context <ctx>`.')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        ctxs = cls.existing_allowed_contexts()
        return ctxs or None
