"""vSphere: on-prem VMware clusters as a provisioning target.

Parity: ``sky/clouds/vsphere.py`` — one vCenter endpoint, "on-prem"
pseudo-region, $0 catalog prices (capacity is owned, not rented), no
spot, stop/resume supported. Lifecycle: ``provision/vsphere`` (govc CLI
clone-from-template + shared fake).
"""
import os
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register()
class Vsphere(simple_vm_cloud.SimpleVmCloud):
    """VMware vSphere (on-prem)."""

    _REPR = 'Vsphere'
    _CLOUD_KEY = 'vsphere'
    _HAS_SPOT = False
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu import skypilot_config
        if os.environ.get('GOVC_URL') or skypilot_config.get_nested(
                ('vsphere', 'url'), None):
            return True, None
        return False, ('vSphere endpoint not configured. Set $GOVC_URL '
                       '(+ $GOVC_USERNAME/$GOVC_PASSWORD) or vsphere.url '
                       'in ~/.skytpu/config.yaml.')

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        url = os.environ.get('GOVC_URL')
        user = os.environ.get('GOVC_USERNAME', 'vsphere-user')
        return [f'{user}@{url}'] if url else None
