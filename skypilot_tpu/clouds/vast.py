"""Vast.ai: GPU marketplace for cross-cloud cost ranking.

Parity: ``sky/clouds/vast.py`` — "regions" are geolocations (US/EU/...),
spot = interruptible bids, stop/resume supported. Lifecycle:
``provision/vast`` (offer search + rent via curl + shared fake).
"""
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.clouds import cloud
from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register(name='vast', aliases=['vastai'])
class Vast(simple_vm_cloud.SimpleVmCloud):
    """Vast.ai (GPU marketplace)."""

    _REPR = 'Vast'
    _CLOUD_KEY = 'vast'
    _HAS_SPOT = True
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def unsupported_features(
        cls,
        resources=None
    ) -> Dict[cloud.CloudImplementationFeatures, str]:
        feats = super().unsupported_features(resources)
        feats[cloud.CloudImplementationFeatures.OPEN_PORTS] = \
            'Vast.ai hosts expose only the mapped SSH port.'
        return feats

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.vast import vast_api
        if vast_api.api_key() is None:
            return False, ('Vast.ai API key not found. Set $VAST_API_KEY '
                           'or write it to ~/.vast_api_key.')
        return True, None

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        from skypilot_tpu.provision.vast import vast_api
        key = vast_api.api_key()
        return [f'vast-key-{key[:8]}'] if key else None
