"""IBM Cloud VPC: V100/L4 GPU instances (pairs with the IBM COS store).

Parity: ``sky/clouds/ibm.py`` — region-only placement, no spot market,
stop/resume supported. Lifecycle: ``provision/ibm`` (ibmcloud CLI +
shared fake).
"""
import os
from typing import List, Optional, Tuple

from skypilot_tpu.clouds import simple_vm_cloud
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


@CLOUD_REGISTRY.register()
class IBM(simple_vm_cloud.SimpleVmCloud):
    """IBM Cloud (VPC Gen2)."""

    _REPR = 'IBM'
    _CLOUD_KEY = 'ibm'
    _HAS_SPOT = False
    _EGRESS_PER_GB = 0.09
    _MAX_CLUSTER_NAME_LEN_LIMIT = 50

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('IBMCLOUD_API_KEY'):
            return True, None
        path = os.path.expanduser('~/.bluemix/config.json')
        if os.path.exists(path):
            return True, None
        return False, ('IBM Cloud credentials not found. Run '
                       '`ibmcloud login` or set $IBMCLOUD_API_KEY.')

    @classmethod
    def get_current_user_identity(cls) -> Optional[List[str]]:
        # None = identity unknown → ownership check skipped. Returning a
        # constant here would hard-mismatch against key-derived owners
        # when the same user switches between env-key and CLI sessions.
        key = os.environ.get('IBMCLOUD_API_KEY')
        return [f'ibm-key-{key[:8]}'] if key else None
