"""TPU slice topology: the first-class compute-target model.

This replaces the reference's GPU-count-centric accelerator model
(``sky/resources.py:575-640`` TPU special-casing, ``sky/clouds/gcp.py:207-217``)
with an explicit slice model: an accelerator request like ``tpu-v5p:128``
resolves to a :class:`TpuSliceTopology` — generation, chip count, ICI torus
shape, host fan-out — which drives:

* the optimizer (price = chips × $/chip-hr; feasibility = valid slice sizes),
* the provisioner (one TPU node, ``num_hosts`` SSH targets from
  ``networkEndpoints[]`` — parity: ``provision/gcp/instance_utils.py:1635``),
* the runtime (``jax.distributed`` coordinator + per-host ranks),
* the compute layer (default ``jax.sharding.Mesh`` axis sizes).

Counts are **chips** throughout (not TensorCores): ``tpu-v4:8`` is 8 chips
(2 hosts). The legacy GCP core-based names (``tpu-v2-8`` etc.) are accepted
and converted.
"""
import dataclasses
import math
import re
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions


@dataclasses.dataclass(frozen=True)
class TpuGenerationInfo:
    """Static per-generation hardware data (catalog-level facts)."""
    name: str                      # 'v5p'
    torus_dims: int                # 2 or 3 (ICI torus dimensionality)
    chips_per_host: int            # hosts in multi-host slices
    single_host_sizes: Tuple[int, ...]  # chip counts servable by one host
    max_chips: int
    hbm_gib_per_chip: float
    peak_bf16_tflops_per_chip: float
    ici_gbps_per_link: float       # unidirectional per-link ICI bandwidth
    host_vcpus: int                # parity: sky/clouds/gcp.py:474-498
    host_memory_gb: int
    # Core-count multiplier for legacy names (v2-8 = 8 cores = 4 chips).
    cores_per_chip: int = 2
    supports_stop: bool = False    # TPU pods can only be deleted, gcp.py:207

    def valid_chip_counts(self) -> List[int]:
        """Chip counts GCP actually sells for this generation.

        2D-torus generations scale by doubling; 3D-torus generations
        (v4/v5p) additionally sell cuboids with every dimension a multiple
        of 4 (e.g. v5p 4x4x12 = 192 chips, 16x16x24 = 6144 chips).
        """
        counts = set(self.single_host_sizes)
        if self.torus_dims == 2:
            c = self.chips_per_host
            while c <= self.max_chips:
                counts.add(c)
                c *= 2
        else:
            # Small sub-cube slices.
            for c in (4, 8, 16, 32):
                if c <= self.max_chips:
                    counts.add(c)
            # All 4-multiple cuboids a<=b<=c up to a per-dim cap of 32.
            dims = [d for d in range(4, 33, 4)]
            for a in dims:
                for b in dims:
                    if b < a:
                        continue
                    for c in dims:
                        if c < b:
                            continue
                        prod = a * b * c
                        if prod <= self.max_chips:
                            counts.add(prod)
        return sorted(x for x in counts if x <= self.max_chips)


# Generation table. Sources: public GCP TPU docs; perf figures are the
# published peak bf16 numbers used for cost/MFU modeling in the optimizer.
TPU_GENERATIONS: Dict[str, TpuGenerationInfo] = {
    'v2': TpuGenerationInfo('v2', 2, 4, (4,), 512, 8, 23, 100, 96, 334),
    'v3': TpuGenerationInfo('v3', 2, 4, (4,), 1024, 16, 61, 175, 96, 334),
    'v4': TpuGenerationInfo('v4', 3, 4, (4,), 4096, 32, 275, 200, 240, 400),
    'v5e': TpuGenerationInfo('v5e', 2, 4, (1, 4, 8), 256, 16, 197, 200, 224,
                             400, cores_per_chip=1),
    'v5p': TpuGenerationInfo('v5p', 3, 4, (4,), 6144, 95, 459, 400, 208, 448),
    'v6e': TpuGenerationInfo('v6e', 2, 4, (1, 4, 8), 256, 32, 918, 400, 180,
                             720, cores_per_chip=1),
}

_TPU_NAME_RE = re.compile(r'^tpu-?(v\d+[a-z]*)$', re.IGNORECASE)
# Legacy GCP catalog name: tpu-v2-8 (8 = TensorCores).
_TPU_LEGACY_RE = re.compile(r'^tpu-?(v\d+[a-z]*)-(\d+)$', re.IGNORECASE)


def is_tpu_accelerator(name: str) -> bool:
    return bool(_TPU_NAME_RE.match(name) or _TPU_LEGACY_RE.match(name))


def _squarish_factors(n: int, dims: int) -> Tuple[int, ...]:
    """Factor n into `dims` factors, as balanced as possible, ascending."""
    if dims == 1:
        return (n,)
    best: Optional[Tuple[int, ...]] = None
    best_score = math.inf

    def search(remaining: int, left: int, acc: List[int]):
        nonlocal best, best_score
        if left == 1:
            shape = tuple(sorted(acc + [remaining]))
            score = max(shape) / min(shape)
            if score < best_score:
                best, best_score = shape, score
            return
        f = 1
        while f * f <= remaining * 2:
            if remaining % f == 0:
                search(remaining // f, left - 1, acc + [f])
            f += 1

    search(n, dims, [])
    assert best is not None
    return best


@dataclasses.dataclass(frozen=True)
class TpuSliceTopology:
    """A concrete TPU slice: generation + chips + ICI shape + host fan-out."""
    generation: TpuGenerationInfo
    num_chips: int
    ici_shape: Tuple[int, ...]

    @property
    def name(self) -> str:
        """Canonical accelerator name, e.g. 'tpu-v5p'."""
        return f'tpu-{self.generation.name}'

    @property
    def gcp_accelerator_type(self) -> str:
        """GCP TPU API acceleratorType, e.g. 'v5p-128' (TensorCore count)."""
        if self.generation.cores_per_chip == 1:
            return f'{self.generation.name}-{self.num_chips}'
        return f'{self.generation.name}-' \
               f'{self.num_chips * self.generation.cores_per_chip}'

    @property
    def topology_str(self) -> str:
        """GCP API topology string, e.g. '4x4x8'."""
        return 'x'.join(str(d) for d in self.ici_shape)

    @property
    def num_hosts(self) -> int:
        if self.num_chips in self.generation.single_host_sizes:
            return 1
        return max(1, self.num_chips // self.generation.chips_per_host)

    @property
    def chips_per_host(self) -> int:
        return self.num_chips // self.num_hosts

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def is_pod(self) -> bool:
        return self.is_multi_host

    @property
    def hbm_gib(self) -> float:
        return self.num_chips * self.generation.hbm_gib_per_chip

    @property
    def peak_bf16_tflops(self) -> float:
        return self.num_chips * self.generation.peak_bf16_tflops_per_chip

    def default_mesh_shape(self,
                           data_parallel: Optional[int] = None
                           ) -> Dict[str, int]:
        """Default jax Mesh axis sizes for this slice.

        Model-parallel ('model') axis stays within a host's ICI-adjacent
        chips where possible; 'fsdp' spans hosts over ICI; 'data' is either
        the given DP degree or 1 (multislice DP over DCN is configured by the
        launcher, not the slice).
        """
        dp = data_parallel or 1
        chips = self.num_chips // dp
        model = min(self.chips_per_host, chips)
        fsdp = chips // model
        return {'data': dp, 'fsdp': fsdp, 'model': model}

    def __str__(self) -> str:
        return (f'{self.name}:{self.num_chips} '
                f'(topology {self.topology_str}, {self.num_hosts} host'
                f'{"s" if self.num_hosts != 1 else ""})')


_SMALL_3D_SHAPES = {4: (1, 2, 2), 8: (2, 2, 2), 16: (2, 2, 4), 32: (2, 4, 4)}


def _default_ici_shape(gen: TpuGenerationInfo, chips: int) -> Tuple[int, ...]:
    if chips == 1:
        return (1,) * gen.torus_dims
    if gen.torus_dims == 2:
        return _squarish_factors(chips, 2)
    # 3D torus (v4/v5p): small slices use fixed sub-cube shapes; larger
    # slices must have every dim a multiple of 4 — pick the most balanced
    # multiple-of-4 cuboid.
    if chips in _SMALL_3D_SHAPES:
        return _SMALL_3D_SHAPES[chips]
    best: Optional[Tuple[int, ...]] = None
    best_score = math.inf
    dims = [d for d in range(4, 33, 4)]
    for a in dims:
        for b in dims:
            if b < a:
                continue
            if a * b > chips:
                break
            c, rem = divmod(chips, a * b)
            if rem or c < b or c % 4:
                continue
            score = c / a
            if score < best_score:
                best, best_score = (a, b, c), score
    if best is None:
        # Shouldn't happen for counts from valid_chip_counts(); fall back.
        return _squarish_factors(chips, 3)
    return best


def resolve_topology(accelerator_name: str,
                     count: float,
                     topology: Optional[str] = None) -> TpuSliceTopology:
    """Parse ('tpu-v5p', 128) or legacy ('tpu-v5p-256', 1) into a topology.

    Raises InvalidSkyError for unknown generations or invalid chip counts.
    """
    name = accelerator_name.lower()
    m = _TPU_NAME_RE.match(name)
    chips = int(count)
    if m is None:
        lm = _TPU_LEGACY_RE.match(name)
        if lm is None:
            raise exceptions.InvalidSkyError(
                f'Not a TPU accelerator: {accelerator_name!r}')
        gen_name, cores = lm.group(1), int(lm.group(2))
        if gen_name not in TPU_GENERATIONS:
            raise exceptions.InvalidSkyError(
                f'Unknown TPU generation {gen_name!r}. Known: '
                f'{sorted(TPU_GENERATIONS)}')
        gen = TPU_GENERATIONS[gen_name]
        if int(count) != 1:
            raise exceptions.InvalidSkyError(
                f'Legacy TPU name {accelerator_name!r} must have count 1 '
                f'(got {count}); the size is embedded in the name.')
        chips = max(1, cores // gen.cores_per_chip)
    else:
        gen_name = m.group(1)
        if gen_name not in TPU_GENERATIONS:
            raise exceptions.InvalidSkyError(
                f'Unknown TPU generation {gen_name!r}. Known: '
                f'{sorted(TPU_GENERATIONS)}')
        gen = TPU_GENERATIONS[gen_name]
    if count != int(count):
        raise exceptions.InvalidSkyError(
            f'TPU chip count must be an integer, got {count}')

    if topology is not None:
        try:
            dims = tuple(int(d) for d in topology.lower().split('x'))
        except ValueError:
            raise exceptions.InvalidSkyError(
                f'Malformed topology {topology!r}: expected NxM or NxMxK '
                'integers.') from None
        if not dims or any(d < 1 for d in dims):
            raise exceptions.InvalidSkyError(
                f'Malformed topology {topology!r}: dims must be >= 1.')
        if math.prod(dims) != chips:
            raise exceptions.InvalidSkyError(
                f'Topology {topology} has {math.prod(dims)} chips, but '
                f'{chips} chips were requested.')
        if len(dims) != gen.torus_dims:
            raise exceptions.InvalidSkyError(
                f'TPU {gen.name} has a {gen.torus_dims}D torus; topology '
                f'{topology} has {len(dims)} dims.')
        # Preserve the user's shape verbatim — the GCP API accepts the
        # AcceleratorConfig topology exactly as given.
        shape = tuple(dims)
    else:
        valid = gen.valid_chip_counts()
        if chips not in valid:
            raise exceptions.InvalidSkyError(
                f'Invalid chip count {chips} for TPU {gen.name}. Valid '
                f'sizes: {valid}. (Counts are chips, not TensorCores.)')
        shape = _default_ici_shape(gen, chips)
    if chips > gen.max_chips:
        raise exceptions.InvalidSkyError(
            f'TPU {gen.name} supports at most {gen.max_chips} chips, '
            f'got {chips}.')
    return TpuSliceTopology(gen, chips, shape)


def parse_generation(accelerator_name: str) -> TpuGenerationInfo:
    """Accelerator name ('tpu-v5p' or legacy 'tpu-v5p-256') → generation.

    Cheaper than resolve_topology for callers that only need region/pricing
    lookups (which are per-generation, not per-slice).
    """
    name = accelerator_name.lower()
    m = _TPU_NAME_RE.match(name) or _TPU_LEGACY_RE.match(name)
    if m is None:
        raise exceptions.InvalidSkyError(
            f'Not a TPU accelerator: {accelerator_name!r}')
    gen_name = m.group(1)
    if gen_name not in TPU_GENERATIONS:
        raise exceptions.InvalidSkyError(
            f'Unknown TPU generation {gen_name!r}. Known: '
            f'{sorted(TPU_GENERATIONS)}')
    return TPU_GENERATIONS[gen_name]


def list_tpu_accelerators() -> List[str]:
    return [f'tpu-{g}' for g in TPU_GENERATIONS]
