"""sqlite registry of clusters / storage / enabled clouds / users.

Parity: ``sky/global_user_state.py:40,194,673``. Cluster handles are pickled
into the row like the reference (handle classes implement ``__setstate__``
for forward migration).
"""
import enum
import json
import os
import pickle
import sqlite3
import threading
import time
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu.backends import backend as backend_lib

_DB_PATH = '~/.skytpu/state.db'
_local = threading.local()


class ClusterStatus(enum.Enum):
    """Parity: sky/global_user_state.py ClusterStatus."""
    INIT = 'INIT'
    UP = 'UP'
    STOPPED = 'STOPPED'

    def colored_str(self) -> str:
        from skypilot_tpu.utils import ux_utils
        color = {
            ClusterStatus.INIT: ux_utils.YELLOW,
            ClusterStatus.UP: ux_utils.GREEN,
            ClusterStatus.STOPPED: ux_utils.DIM,
        }[self]
        return ux_utils.colored(self.value, color)


def _db() -> sqlite3.Connection:
    if getattr(_local, 'conn', None) is None:
        path = os.path.expanduser(_DB_PATH)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        conn = sqlite3.connect(path, timeout=10)
        conn.row_factory = sqlite3.Row
        _create_tables(conn)
        _local.conn = conn
        _local.path = path
    elif getattr(_local, 'path', None) != os.path.expanduser(_DB_PATH):
        # $HOME changed (tests isolate state): reopen.
        _local.conn.close()
        _local.conn = None
        return _db()
    return _local.conn


def _create_tables(conn: sqlite3.Connection) -> None:
    conn.executescript("""
        CREATE TABLE IF NOT EXISTS clusters (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT,
            autostop INTEGER DEFAULT -1,
            to_down INTEGER DEFAULT 0,
            owner TEXT DEFAULT NULL,
            metadata TEXT DEFAULT '{}',
            cluster_hash TEXT DEFAULT NULL,
            status_updated_at INTEGER DEFAULT NULL
        );
        CREATE TABLE IF NOT EXISTS cluster_history (
            cluster_hash TEXT PRIMARY KEY,
            name TEXT,
            num_nodes INTEGER,
            requested_resources BLOB,
            launched_resources BLOB,
            usage_intervals BLOB
        );
        CREATE TABLE IF NOT EXISTS storage (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT
        );
        CREATE TABLE IF NOT EXISTS enabled_clouds (
            cloud TEXT PRIMARY KEY
        );
        CREATE TABLE IF NOT EXISTS users (
            id TEXT PRIMARY KEY,
            name TEXT
        );
        CREATE TABLE IF NOT EXISTS config (
            key TEXT PRIMARY KEY,
            value TEXT
        );
    """)
    conn.commit()


# ------------------------------------------------------------------ clusters


def add_or_update_cluster(cluster_name: str,
                          cluster_handle: 'backend_lib.ResourceHandle',
                          requested_resources: Optional[set] = None,
                          is_launch: bool = True,
                          ready: bool = False) -> None:
    """Parity: global_user_state.add_or_update_cluster:194."""
    conn = _db()
    status = ClusterStatus.UP if ready else ClusterStatus.INIT
    now = int(time.time())
    handle_blob = pickle.dumps(cluster_handle)
    usage_intervals: List = []
    cluster_hash = _get_hash(cluster_name) or common_utils.get_usage_run_id()
    row = conn.execute('SELECT * FROM cluster_history WHERE cluster_hash=?',
                       (cluster_hash,)).fetchone()
    if row is not None:
        usage_intervals = pickle.loads(row['usage_intervals'])
    if is_launch and (not usage_intervals or
                      usage_intervals[-1][1] is not None):
        usage_intervals.append((now, None))
    launched_nodes = getattr(cluster_handle, 'launched_nodes', None)
    launched_resources = getattr(cluster_handle, 'launched_resources', None)
    conn.execute(
        """INSERT INTO clusters
           (name, launched_at, handle, last_use, status, autostop, to_down,
            owner, metadata, cluster_hash, status_updated_at)
           VALUES (?,?,?,?,?,
                   COALESCE((SELECT autostop FROM clusters WHERE name=?), -1),
                   COALESCE((SELECT to_down FROM clusters WHERE name=?), 0),
                   (SELECT owner FROM clusters WHERE name=?),
                   COALESCE((SELECT metadata FROM clusters WHERE name=?),
                            '{}'),
                   ?, ?)
           ON CONFLICT(name) DO UPDATE SET
             launched_at=excluded.launched_at, handle=excluded.handle,
             last_use=excluded.last_use, status=excluded.status,
             cluster_hash=excluded.cluster_hash,
             status_updated_at=excluded.status_updated_at""",
        (cluster_name, now, handle_blob, common_utils.get_pretty_entrypoint(),
         status.value, cluster_name, cluster_name, cluster_name, cluster_name,
         cluster_hash, now))
    conn.execute(
        """INSERT OR REPLACE INTO cluster_history
           (cluster_hash, name, num_nodes, requested_resources,
            launched_resources, usage_intervals) VALUES (?,?,?,?,?,?)""",
        (cluster_hash, cluster_name, launched_nodes,
         pickle.dumps(requested_resources), pickle.dumps(launched_resources),
         pickle.dumps(usage_intervals)))
    conn.commit()


def _get_hash(cluster_name: str) -> Optional[str]:
    row = _db().execute('SELECT cluster_hash FROM clusters WHERE name=?',
                        (cluster_name,)).fetchone()
    return row['cluster_hash'] if row else None


def update_cluster_status(cluster_name: str, status: ClusterStatus) -> None:
    conn = _db()
    conn.execute(
        'UPDATE clusters SET status=?, status_updated_at=? WHERE name=?',
        (status.value, int(time.time()), cluster_name))
    conn.commit()
    if status != ClusterStatus.UP:
        _close_usage_interval(cluster_name)


def update_last_use(cluster_name: str) -> None:
    conn = _db()
    conn.execute('UPDATE clusters SET last_use=? WHERE name=?',
                 (common_utils.get_pretty_entrypoint(), cluster_name))
    conn.commit()


def _close_usage_interval(cluster_name: str) -> None:
    conn = _db()
    h = _get_hash(cluster_name)
    if h is None:
        return
    row = conn.execute('SELECT * FROM cluster_history WHERE cluster_hash=?',
                       (h,)).fetchone()
    if row is None:
        return
    intervals = pickle.loads(row['usage_intervals'])
    if intervals and intervals[-1][1] is None:
        intervals[-1] = (intervals[-1][0], int(time.time()))
        conn.execute(
            'UPDATE cluster_history SET usage_intervals=? '
            'WHERE cluster_hash=?', (pickle.dumps(intervals), h))
        conn.commit()


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    """Parity: on down → delete row; on stop → STOPPED + clear cached IPs."""
    conn = _db()
    _close_usage_interval(cluster_name)
    if terminate:
        conn.execute('DELETE FROM clusters WHERE name=?', (cluster_name,))
    else:
        record = get_cluster_from_name(cluster_name)
        if record is not None:
            handle = record['handle']
            if hasattr(handle, 'stable_internal_external_ips'):
                handle.stable_internal_external_ips = None
            conn.execute(
                'UPDATE clusters SET status=?, handle=?, '
                'status_updated_at=? WHERE name=?',
                (ClusterStatus.STOPPED.value, pickle.dumps(handle),
                 int(time.time()), cluster_name))
    conn.commit()


def get_cluster_from_name(
        cluster_name: Optional[str]) -> Optional[Dict[str, Any]]:
    row = _db().execute('SELECT * FROM clusters WHERE name=?',
                        (cluster_name,)).fetchone()
    if row is None:
        return None
    return _row_to_record(row)


def _row_to_record(row: sqlite3.Row) -> Dict[str, Any]:
    return {
        'name': row['name'],
        'launched_at': row['launched_at'],
        'handle': pickle.loads(row['handle']),
        'last_use': row['last_use'],
        'status': ClusterStatus(row['status']),
        'autostop': row['autostop'],
        'to_down': bool(row['to_down']),
        'owner': json.loads(row['owner']) if row['owner'] else None,
        'metadata': json.loads(row['metadata']),
        'cluster_hash': row['cluster_hash'],
        'status_updated_at': row['status_updated_at'],
    }


def get_clusters() -> List[Dict[str, Any]]:
    rows = _db().execute(
        'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    return [_row_to_record(r) for r in rows]


def set_cluster_autostop_value(cluster_name: str, idle_minutes: int,
                               to_down: bool) -> None:
    conn = _db()
    conn.execute('UPDATE clusters SET autostop=?, to_down=? WHERE name=?',
                 (idle_minutes, int(to_down), cluster_name))
    conn.commit()


def set_owner_identity_for_cluster(cluster_name: str,
                                   owner_identity: Optional[List[str]]
                                   ) -> None:
    if owner_identity is None:
        return
    conn = _db()
    conn.execute('UPDATE clusters SET owner=? WHERE name=?',
                 (json.dumps(owner_identity), cluster_name))
    conn.commit()


def get_cluster_usage_intervals(cluster_hash: Optional[str]):
    if cluster_hash is None:
        return None
    row = _db().execute(
        'SELECT usage_intervals FROM cluster_history WHERE cluster_hash=?',
        (cluster_hash,)).fetchone()
    return pickle.loads(row['usage_intervals']) if row else None


def get_cluster_history() -> List[Dict[str, Any]]:
    rows = _db().execute('SELECT * FROM cluster_history').fetchall()
    out = []
    for row in rows:
        intervals = pickle.loads(row['usage_intervals'])
        duration = sum((end or int(time.time())) - start
                       for start, end in intervals)
        out.append({
            'name': row['name'],
            'num_nodes': row['num_nodes'],
            'launched_resources': pickle.loads(row['launched_resources']),
            'duration': duration,
            'usage_intervals': intervals,
        })
    return out


# ------------------------------------------------------------------ storage


def add_or_update_storage(storage_name: str, storage_handle: Any,
                          storage_status: str) -> None:
    conn = _db()
    conn.execute(
        'INSERT OR REPLACE INTO storage VALUES (?,?,?,?,?)',
        (storage_name, int(time.time()), pickle.dumps(storage_handle),
         common_utils.get_pretty_entrypoint(), storage_status))
    conn.commit()


def remove_storage(storage_name: str) -> None:
    conn = _db()
    conn.execute('DELETE FROM storage WHERE name=?', (storage_name,))
    conn.commit()


def get_storage_from_name(storage_name: str) -> Optional[Dict[str, Any]]:
    row = _db().execute('SELECT * FROM storage WHERE name=?',
                        (storage_name,)).fetchone()
    if row is None:
        return None
    return {
        'name': row['name'],
        'launched_at': row['launched_at'],
        'handle': pickle.loads(row['handle']),
        'last_use': row['last_use'],
        'status': row['status'],
    }


def get_storage() -> List[Dict[str, Any]]:
    rows = _db().execute('SELECT name FROM storage').fetchall()
    return [get_storage_from_name(r['name']) for r in rows]


# ------------------------------------------------------------ enabled clouds


def get_enabled_clouds() -> List[str]:
    rows = _db().execute('SELECT cloud FROM enabled_clouds').fetchall()
    return [r['cloud'] for r in rows]


def set_enabled_clouds(enabled_clouds: List[str]) -> None:
    conn = _db()
    conn.execute('DELETE FROM enabled_clouds')
    conn.executemany('INSERT INTO enabled_clouds VALUES (?)',
                     [(c,) for c in enabled_clouds])
    conn.commit()
