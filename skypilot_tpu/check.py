"""Credential probing per cloud (`sky check`). Parity: ``sky/check.py:25``."""
from typing import Iterable, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import sky_logging
from skypilot_tpu import skypilot_config
from skypilot_tpu.utils import ux_utils
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

logger = sky_logging.init_logger(__name__)


def check(quiet: bool = False,
          clouds: Optional[Iterable[str]] = None) -> List[str]:
    """Probe credentials for each cloud; persist the enabled set.

    Returns the list of enabled cloud names.
    """
    allowed = skypilot_config.get_nested(('allowed_clouds',), None)
    results: List[Tuple[str, bool, Optional[str]]] = []
    to_check = ([CLOUD_REGISTRY.from_str(c) for c in clouds]
                if clouds else list(CLOUD_REGISTRY.values()))
    for impl in to_check:
        name = str(impl)
        if allowed is not None and name.lower() not in [
                a.lower() for a in allowed
        ]:
            results.append((name, False, 'disabled by allowed_clouds config'))
            continue
        try:
            ok, reason = type(impl).check_credentials()
        except Exception as e:  # pylint: disable=broad-except
            ok, reason = False, str(e)
        results.append((name, ok, reason))

    enabled = [name for name, ok, _ in results if ok]
    if clouds is None:
        global_state.set_enabled_clouds(enabled)
    else:
        # Partial check: merge with the previously-enabled set.
        prev = set(global_state.get_enabled_clouds())
        for name, ok, _ in results:
            if ok:
                prev.add(name)
            else:
                prev.discard(name)
        global_state.set_enabled_clouds(sorted(prev))
        enabled = sorted(prev)

    if not quiet:
        print(ux_utils.bold('Checked credentials for clouds:'))
        for name, ok, reason in results:
            mark = ux_utils.colored('enabled', ux_utils.GREEN) if ok else \
                ux_utils.colored('disabled', ux_utils.RED)
            line = f'  {name}: {mark}'
            if not ok and reason:
                line += f'\n    {ux_utils.dim(reason)}'
            print(line)
    if not enabled:
        raise exceptions.NoCloudAccessError(
            'No cloud is enabled. Configure credentials (e.g. `gcloud auth '
            'login`) and rerun `sky check`.')
    return enabled


def get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud: bool = True) -> List:
    """Enabled Cloud objects from cache, probing once if the cache is empty.

    Parity: check.py:184 get_cached_enabled_clouds_or_refresh.
    """
    names = global_state.get_enabled_clouds()
    if not names:
        try:
            names = check(quiet=True)
        except exceptions.NoCloudAccessError:
            if raise_if_no_cloud:
                raise
            names = []
    return [CLOUD_REGISTRY.from_str(n) for n in names]
