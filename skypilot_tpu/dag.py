"""Task DAG (parity: ``sky/dag.py:11,84``)."""
import threading
from typing import List, Optional

import networkx as nx


class Dag:
    """Directed acyclic graph of Tasks. Use as a context manager::

        with Dag() as dag:
            task = Task(...)   # auto-added to dag
    """

    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.graph = nx.DiGraph()
        self.tasks: List = []

    def add(self, task) -> None:
        self.graph.add_node(task)
        self.tasks.append(task)

    def remove(self, task) -> None:
        self.tasks.remove(task)
        self.graph.remove_node(task)

    def add_edge(self, op1, op2) -> None:
        assert op1 in self.graph.nodes
        assert op2 in self.graph.nodes
        self.graph.add_edge(op1, op2)

    def __len__(self) -> int:
        return len(self.tasks)

    def __enter__(self) -> 'Dag':
        push_dag(self)
        return self

    def __exit__(self, *exc) -> None:
        pop_dag()

    def is_chain(self) -> bool:
        nodes = list(self.graph.nodes)
        out_degrees = [self.graph.out_degree(n) for n in nodes]
        in_degrees = [self.graph.in_degree(n) for n in nodes]
        return (len(nodes) <= 1 or
                (all(d <= 1 for d in out_degrees) and
                 all(d <= 1 for d in in_degrees) and
                 nx.is_directed_acyclic_graph(self.graph) and
                 nx.number_weakly_connected_components(self.graph) == 1))

    def get_sorted_tasks(self) -> List:
        return list(nx.topological_sort(self.graph))

    def __repr__(self) -> str:
        return f'Dag({self.name}, {len(self.tasks)} tasks)'


class _DagContext(threading.local):
    """Thread-local DAG stack (parity: sky/dag.py:84)."""

    def __init__(self):
        super().__init__()
        self._stack: List[Dag] = []

    def push(self, dag: Dag) -> None:
        self._stack.append(dag)

    def pop(self) -> Dag:
        return self._stack.pop()

    def current(self) -> Optional[Dag]:
        return self._stack[-1] if self._stack else None


_dag_context = _DagContext()
push_dag = _dag_context.push
pop_dag = _dag_context.pop
get_current_dag = _dag_context.current
