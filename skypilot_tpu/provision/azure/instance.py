"""Azure VM instance lifecycle (parity: ``sky/provision/azure/instance.py``).

A "cluster" of N nodes = N VMs in one resource group, tagged
``skytpu-cluster=<name>`` + ``skytpu-node=<i>``; one InstanceInfo per VM
(Azure GPU hosts are single-host nodes — multi-host fan-out is a TPU-slice
concept).
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.azure import az_api

logger = sky_logging.init_logger(__name__)

_CLUSTER_TAG = 'skytpu-cluster'
_NODE_TAG = 'skytpu-node'

# Azure powerState strings → the uniform status vocabulary.
_STATE_MAP = {
    'VM starting': 'pending',
    'VM running': 'running',
    'VM stopping': 'stopping',
    'VM stopped': 'stopped',
    'VM deallocating': 'stopping',
    'VM deallocated': 'stopped',
    'VM deleted': 'terminated',
}


def _resource_group(provider_config: Dict[str, Any],
                    cluster_name_on_cloud: str) -> str:
    return provider_config.get('resource_group',
                               f'skytpu-{cluster_name_on_cloud}')


def _client(provider_config: Dict[str, Any],
            cluster_name_on_cloud: str) -> Any:
    return az_api.make_client(
        provider_config['region'],
        _resource_group(provider_config, cluster_name_on_cloud))


def _node_index(vm: dict) -> int:
    return int(vm.get('tags', {}).get(_NODE_TAG, 0))


def _cluster_vms(client, cluster_name_on_cloud: str) -> List[dict]:
    return client.list_vms({_CLUSTER_TAG: cluster_name_on_cloud})


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = _client(config.provider_config, cluster_name_on_cloud)
    zone = config.provider_config.get('availability_zone')
    existing = _cluster_vms(client, cluster_name_on_cloud)
    by_index = {_node_index(v): v for v in existing}

    # One zone per cluster: adopting leftovers from another zone would
    # silently span zones while the record claims `zone`.
    for vm in existing:
        if zone and vm.get('zone') and vm['zone'] != zone:
            raise common.ProvisionerError(
                f'Cluster {cluster_name_on_cloud} has VMs in '
                f'{vm["zone"]} but {zone} was requested; run `down` '
                'first.')

    client.ensure_group()
    created: List[str] = []
    resumed: List[str] = []
    try:
        for i in range(config.count):
            vm = by_index.get(i)
            if vm is not None:
                state = _STATE_MAP.get(vm['powerState'], 'pending')
                if state == 'stopped':
                    if not config.resume_stopped_nodes:
                        raise common.ProvisionerError(
                            f'Node {i} of {cluster_name_on_cloud} is '
                            'deallocated and resume_stopped_nodes is '
                            'False; start the cluster instead.')
                    client.start_vms([vm['name']])
                    resumed.append(vm['name'])
                continue
            name = f'{cluster_name_on_cloud}-{i}'
            node_cfg = {
                'instance_type': config.node_config['instance_type'],
                'image_id': config.node_config.get('image_id'),
                'use_spot': config.node_config.get('use_spot', False),
                'ssh_user':
                    config.provider_config.get('ssh_user', 'azureuser'),
                'ssh_public_key':
                    config.authentication_config.get('ssh_public_key'),
                'tags': {
                    _CLUSTER_TAG: cluster_name_on_cloud,
                    _NODE_TAG: str(i),
                },
            }
            client.create_vm(name, zone, node_cfg)
            created.append(name)
    except az_api.AzureCapacityError:
        # Failover may move to another region/zone: partially-created VMs
        # would bill forever (mirrors the EC2 partial-create cleanup).
        if created:
            # Cleanup must never mask the capacity error: the failover
            # engine only fails over on CapacityError, so a cleanup
            # timeout/API error escaping here would abort provisioning
            # instead of moving to the next zone.
            try:
                if not existing and 'resource_group' not in \
                        config.provider_config:
                    # Fresh dedicated group: tear down NICs/IPs/disks
                    # too. Synchronous: a zone failover reuses the group
                    # name, and `az group create` conflicts with an
                    # in-flight async delete (the retry would then fail
                    # for a non-capacity reason).
                    client.delete_group(wait=True)
                else:
                    client.delete_vms(created)
            except Exception as cleanup_exc:  # pylint: disable=broad-except
                logger.warning(
                    f'Capacity rollback cleanup for '
                    f'{cluster_name_on_cloud} failed (continuing with '
                    f'failover): {cleanup_exc}')
        raise
    head = by_index.get(0)
    head_id = head['name'] if head is not None else (
        created[0] if created else None)
    assert head_id is not None
    return common.ProvisionRecord(provider_name='azure',
                                  region=region,
                                  zone=zone,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=resumed,
                                  created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    import time
    assert provider_config is not None
    client = _client(provider_config, cluster_name_on_cloud)
    deadline = time.time() + 600
    while True:
        vms = _cluster_vms(client, cluster_name_on_cloud)
        states = [_STATE_MAP.get(v['powerState'], 'pending') for v in vms]
        if vms and all(s == state for s in states):
            return
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for {cluster_name_on_cloud} to reach '
                f'{state}; current: {states}')
        time.sleep(5)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    client = _client(provider_config, cluster_name_on_cloud)
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    for vm in sorted(_cluster_vms(client, cluster_name_on_cloud),
                     key=_node_index):
        if head_id is None:  # sorted: node 0 first
            head_id = vm['name']
        instances[vm['name']] = [
            common.InstanceInfo(
                instance_id=vm['name'],
                internal_ip=vm.get('privateIp', ''),
                external_ip=vm.get('publicIp'),
                tags=dict(vm.get('tags', {})),
            )
        ]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name='azure',
        provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'azureuser'),
        ssh_private_key=provider_config.get('ssh_private_key'),
    )


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    assert provider_config is not None
    client = _client(provider_config, cluster_name_on_cloud)
    out: Dict[str, Optional[str]] = {}
    for vm in _cluster_vms(client, cluster_name_on_cloud):
        status = _STATE_MAP.get(vm['powerState'], 'pending')
        if non_terminated_only and status == 'terminated':
            continue
        out[vm['name']] = status
    return out


def _names(client, cluster_name_on_cloud: str,
           worker_only: bool) -> List[str]:
    return [
        vm['name'] for vm in _cluster_vms(client, cluster_name_on_cloud)
        if not (worker_only and _node_index(vm) == 0)
    ]


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config, cluster_name_on_cloud)
    client.stop_vms(_names(client, cluster_name_on_cloud, worker_only))


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config, cluster_name_on_cloud)
    whole_cluster = not worker_only
    dedicated_group = 'resource_group' not in provider_config
    if whole_cluster and dedicated_group:
        # Per-cluster group: delete it wholesale — a bare `az vm delete`
        # leaves NICs/public-IPs/OS disks billing forever.
        client.delete_group()
        return
    client.delete_vms(_names(client, cluster_name_on_cloud, worker_only))


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    """ONE named allow rule upserted on each VM's auto-created NSG
    (parity: the reference's Azure NSG rules for `ports:`). Upsert is
    by rule NAME at a fixed priority, so relaunches — including with a
    CHANGED port set — update in place instead of conflicting."""
    if not ports:
        return
    assert provider_config is not None
    client = _client(provider_config, cluster_name_on_cloud)
    vms = _cluster_vms(client, cluster_name_on_cloud)
    names = [v['name'] for v in vms]
    if not names:
        logger.warning(f'open_ports({cluster_name_on_cloud}): no VMs '
                       'found — nothing opened.')
        return
    client.upsert_nsg_rule(names, [str(p) for p in ports])
    logger.info(f'Opened ports {ports} for {cluster_name_on_cloud} '
                f'(NSG rules on {len(names)} VM(s)).')


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    """In the default per-cluster resource group, the rules die with
    the group. In a USER-CONFIGURED shared group, `az vm delete` leaves
    NICs/NSGs behind — delete the skytpu rule explicitly while the VMs
    still exist (teardown_cluster calls this before terminate)."""
    if not ports:
        return  # nothing was ever opened — skip the per-VM az calls
    assert provider_config is not None
    if 'resource_group' not in provider_config:
        return  # dedicated group: teardown removes the NSGs wholesale
    client = _client(provider_config, cluster_name_on_cloud)
    names = [v['name'] for v in
             _cluster_vms(client, cluster_name_on_cloud)]
    if names:
        client.delete_nsg_rule(names)
