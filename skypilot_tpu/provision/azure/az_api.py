"""Azure VM API client with a fake backend.

Parity: the reference drives the ``azure-mgmt-compute`` SDK from
``sky/provision/azure/instance.py``; this build shells out to the ``az``
CLI (``-o json``) with the same two-transport shape as
``provision/aws/ec2_api.py``:

* :class:`CliTransport` — real Azure via ``az vm ... -o json``.
* :class:`FakeAzureService` — in-memory VMs, used by tests and when
  ``SKYTPU_AZURE_FAKE=1``. Fault injection:
  ``SKYTPU_AZURE_FAKE_STOCKOUT='eastus-1,...'`` makes create in those
  zones raise ``ZonalAllocationFailed`` — exercising the failover engine.

Both transports normalize VMs to one dict shape::

    {'name', 'vmSize', 'powerState', 'location', 'zone',
     'privateIp', 'publicIp', 'tags': {...}}
"""
import json
import os
import subprocess
import threading
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# The single skytpu NSG rule name (`ports:` exposure) — shared by the
# real and fake transports (and tests) so they can never drift.
NSG_RULE_NAME = 'skytpu-ports'

_FAKE_STATE_ENV = 'SKYTPU_AZURE_FAKE_STATE'


class AzureApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class AzureCapacityError(AzureApiError, provision_common.CapacityError):
    """Capacity exhaustion. ``scope``: 'zone' for a zonal allocation
    failure, 'region' for SKU/quota exhaustion (sister zones in the same
    region fail identically)."""

    def __init__(self, message: str, scope: str = 'zone'):
        super().__init__(message)
        self.scope = scope


# Exact Azure error codes only (see ec2_api._CAPACITY_SCOPES rationale):
# ZonalAllocationFailed is zone-scoped; AllocationFailed /
# SkuNotAvailable / QuotaExceeded exhaust the whole region for that SKU.
_CAPACITY_SCOPES = {
    'zonalallocationfailed': 'zone',
    'allocationfailed': 'region',
    'skunotavailable': 'region',
    'quotaexceeded': 'region',
}


def _capacity_scope(message: str) -> Optional[str]:
    lowered = message.lower()
    # Order matters: 'allocationfailed' is a substring of
    # 'zonalallocationfailed'.
    if 'zonalallocationfailed' in lowered:
        return 'zone'
    for marker, scope in _CAPACITY_SCOPES.items():
        if marker in lowered:
            return scope
    # OperationNotAllowed covers both quota exhaustion and disallowed VM
    # state transitions — only the quota-text variant is capacity.
    if 'operationnotallowed' in lowered and 'quota' in lowered:
        return 'region'
    return None


class CliTransport:
    """Real Azure through the az CLI.

    A cluster's VMs live in one resource group
    (``provider_config['resource_group']``); tags carry cluster/node
    identity exactly like the EC2 path.
    """

    def __init__(self, region: str, resource_group: str):
        self.region = region
        self.resource_group = resource_group

    def _run(self, args: List[str]) -> Any:
        proc = subprocess.run(['az'] + args + ['-o', 'json'],
                              capture_output=True,
                              text=True,
                              timeout=600,
                              check=False)
        if proc.returncode != 0:
            msg = proc.stderr.strip()
            scope = _capacity_scope(msg)
            if scope is not None:
                raise AzureCapacityError(msg, scope=scope)
            raise AzureApiError(f'az {args[0]} {args[1]}: {msg}')
        return json.loads(proc.stdout) if proc.stdout.strip() else {}

    def ensure_group(self) -> None:
        # Idempotent: `az group create` on an existing group is a no-op
        # update. The per-cluster default group ('skytpu-<cluster>')
        # exists nowhere until this runs.
        self._run(['group', 'create', '--name', self.resource_group,
                   '--location', self.region])

    def create_vm(self, name: str, zone: Optional[str],
                  config: Dict[str, Any]) -> Dict[str, Any]:
        args = [
            'vm', 'create',
            '--resource-group', self.resource_group,
            '--name', name,
            '--location', self.region,
            '--size', config['instance_type'],
            '--image', config.get('image_id') or 'Ubuntu2204',
            # Must match the ssh_user the backend probes with
            # (backend_utils.make_provision_config): without this az
            # defaults the admin account to the local OS username.
            '--admin-username', config.get('ssh_user', 'azureuser'),
            '--tags',
        ] + [f'{k}={v}' for k, v in config.get('tags', {}).items()]
        if config.get('ssh_public_key'):
            args += ['--ssh-key-values', config['ssh_public_key']]
        else:
            args += ['--generate-ssh-keys']
        if zone:
            # Catalog zones are '<region>-<n>'; az wants the bare number.
            args += ['--zone', zone.rsplit('-', 1)[-1]]
        if config.get('use_spot'):
            args += ['--priority', 'Spot', '--eviction-policy',
                     'Deallocate']
        out = self._run(args)
        return {
            'name': name,
            'vmSize': config['instance_type'],
            'powerState': 'VM running',
            'location': self.region,
            'zone': zone,
            'privateIp': out.get('privateIpAddress', ''),
            'publicIp': out.get('publicIpAddress'),
            'tags': dict(config.get('tags', {})),
        }

    def list_vms(self, tag_filters: Dict[str, str]) -> List[Dict[str, Any]]:
        out = self._run(['vm', 'list', '--resource-group',
                         self.resource_group, '-d'])
        vms = []
        for vm in out:
            tags = vm.get('tags') or {}
            if any(tags.get(k) != v for k, v in tag_filters.items()):
                continue
            zones = vm.get('zones') or []
            vms.append({
                'name': vm['name'],
                'vmSize': vm.get('hardwareProfile', {}).get('vmSize', ''),
                'powerState': vm.get('powerState', 'VM running'),
                'location': vm.get('location', self.region),
                'zone': (f"{vm.get('location', self.region)}-{zones[0]}"
                         if zones else None),
                'privateIp': vm.get('privateIps', ''),
                'publicIp': vm.get('publicIps') or None,
                'tags': tags,
            })
        return vms

    def _vm_op(self, op: str, names: List[str]) -> None:
        for name in names:
            args = ['vm', op, '--resource-group', self.resource_group,
                    '--name', name]
            if op == 'delete':
                args.append('--yes')
            self._run(args)

    def stop_vms(self, names: List[str]) -> None:
        # Deallocate (not just power off) so compute billing stops —
        # the Azure analogue of a stopped EC2 instance.
        self._vm_op('deallocate', names)

    def start_vms(self, names: List[str]) -> None:
        self._vm_op('start', names)

    def delete_vms(self, names: List[str]) -> None:
        self._vm_op('delete', names)

    # ONE named rule per VM NSG, upserted by NAME at a FIXED priority:
    # `az network nsg rule create` is create_or_update, so a relaunch
    # with a CHANGED port set updates the same rule in place instead of
    # colliding on priority (az vm open-port names rules after the port
    # string — two different port sets at one priority would conflict).
    NSG_RULE_NAME = NSG_RULE_NAME
    NSG_RULE_PRIORITY = 900

    def upsert_nsg_rule(self, names: List[str],
                        ports: List[str]) -> None:
        """Allow the task's `ports:` on each VM's auto-created NSG
        (`<vm>NSG` — parity: the reference's Azure NSG handling)."""
        for name in names:
            self._run(['network', 'nsg', 'rule', 'create',
                       '--resource-group', self.resource_group,
                       '--nsg-name', f'{name}NSG',
                       '--name', self.NSG_RULE_NAME,
                       '--priority', str(self.NSG_RULE_PRIORITY),
                       '--access', 'Allow', '--direction', 'Inbound',
                       '--protocol', 'Tcp',
                       '--destination-port-ranges'] +
                      [str(p) for p in ports])

    def delete_nsg_rule(self, names: List[str]) -> None:
        for name in names:
            try:
                self._run(['network', 'nsg', 'rule', 'delete',
                           '--resource-group', self.resource_group,
                           '--nsg-name', f'{name}NSG',
                           '--name', self.NSG_RULE_NAME])
            except AzureApiError as e:
                logger.debug(f'delete nsg rule on {name}: {e}')

    def delete_group(self, wait: bool = False) -> None:
        # `az vm delete` leaves NICs/public-IPs/OS disks billing; the
        # per-cluster group teardown removes everything at once.
        # ``wait=True`` on the capacity-rollback path: a zone failover
        # recreates the SAME group name, and `az group create` can
        # collide with an in-flight async delete — the retry would then
        # fail for a non-capacity reason.
        args = ['group', 'delete', '--name', self.resource_group, '--yes']
        if not wait:
            args.append('--no-wait')
        self._run(args)


class FakeAzureService:
    """In-memory Azure: instant state transitions.

    State optionally persisted to ``SKYTPU_AZURE_FAKE_STATE`` (JSON file)
    so separate processes see the same cloud.
    """

    _lock = threading.Lock()
    _vms: Dict[str, Dict[str, Any]] = {}

    def __init__(self, region: str, resource_group: str):
        self.region = region
        self.resource_group = resource_group
        self._state_path = os.environ.get(_FAKE_STATE_ENV)

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding='utf-8') as f:
                return json.load(f)
        return FakeAzureService._vms

    def _save(self, vms: Dict[str, Dict[str, Any]]) -> None:
        if self._state_path:
            with open(self._state_path, 'w', encoding='utf-8') as f:
                json.dump(vms, f)
        else:
            FakeAzureService._vms = vms

    def create_vm(self, name: str, zone: Optional[str],
                  config: Dict[str, Any]) -> Dict[str, Any]:
        stockout = os.environ.get('SKYTPU_AZURE_FAKE_STOCKOUT',
                                  '').split(',')
        if zone and zone in stockout:
            raise AzureCapacityError(
                f'Allocation failed (ZonalAllocationFailed): the zone '
                f'{zone} does not have capacity for the requested VM '
                'size. (fake)')
        sku_out = os.environ.get('SKYTPU_AZURE_FAKE_SKU_OUT', '').split(',')
        if self.region in sku_out:
            raise AzureCapacityError(
                f'SkuNotAvailable: the requested size is not available '
                f'in region {self.region}. (fake)')
        with FakeAzureService._lock:
            vms = self._load()
            n = len(vms)
            key = f'{self.resource_group}/{name}'
            vm = {
                'name': name,
                'vmSize': config['instance_type'],
                'powerState': 'VM running',
                'location': self.region,
                'zone': zone,
                'privateIp': f'10.0.0.{n + 10}',
                'publicIp': f'20.0.0.{n + 10}',
                'tags': dict(config.get('tags', {})),
                '_rg': self.resource_group,
                '_id': uuid.uuid4().hex[:8],
            }
            vms[key] = vm
            self._save(vms)
            return dict(vm)

    def list_vms(self, tag_filters: Dict[str, str]) -> List[Dict[str, Any]]:
        out = []
        for vm in self._load().values():
            if vm.get('_rg') != self.resource_group:
                continue
            if vm['powerState'] == 'VM deleted':
                continue
            tags = vm.get('tags', {})
            if any(tags.get(k) != v for k, v in tag_filters.items()):
                continue
            out.append(dict(vm))
        return out

    def _set_state(self, names: List[str], state: str) -> None:
        with FakeAzureService._lock:
            vms = self._load()
            for name in names:
                key = f'{self.resource_group}/{name}'
                if key in vms:
                    vms[key]['powerState'] = state
            self._save(vms)

    def ensure_group(self) -> None:
        pass

    def stop_vms(self, names: List[str]) -> None:
        self._set_state(names, 'VM deallocated')

    def start_vms(self, names: List[str]) -> None:
        self._set_state(names, 'VM running')

    def delete_vms(self, names: List[str]) -> None:
        self._set_state(names, 'VM deleted')

    NSG_RULE_NAME = NSG_RULE_NAME

    def upsert_nsg_rule(self, names: List[str],
                        ports: List[str]) -> None:
        # Real-API fidelity: create_or_update REPLACES the named rule
        # (a changed port set swaps in, it does not merge).
        with FakeAzureService._lock:
            vms = self._load()
            for name in names:
                key = f'{self.resource_group}/{name}'
                if key in vms:
                    vms[key].setdefault('nsgRules', {})[
                        self.NSG_RULE_NAME] = [str(p) for p in ports]
            self._save(vms)

    def delete_nsg_rule(self, names: List[str]) -> None:
        with FakeAzureService._lock:
            vms = self._load()
            for name in names:
                key = f'{self.resource_group}/{name}'
                if key in vms:
                    vms[key].get('nsgRules', {}).pop(
                        self.NSG_RULE_NAME, None)
            self._save(vms)

    def delete_group(self, wait: bool = False) -> None:
        del wait  # the fake deletes synchronously either way
        with FakeAzureService._lock:
            vms = self._load()
            for vm in vms.values():
                if vm.get('_rg') == self.resource_group:
                    vm['powerState'] = 'VM deleted'
            self._save(vms)


def make_client(region: str, resource_group: str):
    if os.environ.get('SKYTPU_AZURE_FAKE', '0') == '1':
        return FakeAzureService(region, resource_group)
    return CliTransport(region, resource_group)
