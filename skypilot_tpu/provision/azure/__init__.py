"""Azure VM provisioner (parity: ``sky/provision/azure/``)."""
from skypilot_tpu.provision.azure.instance import cleanup_ports
from skypilot_tpu.provision.azure.instance import get_cluster_info
from skypilot_tpu.provision.azure.instance import open_ports
from skypilot_tpu.provision.azure.instance import query_instances
from skypilot_tpu.provision.azure.instance import run_instances
from skypilot_tpu.provision.azure.instance import stop_instances
from skypilot_tpu.provision.azure.instance import terminate_instances
from skypilot_tpu.provision.azure.instance import wait_instances
