"""SCP virtual-server lifecycle via the shared neocloud factory
(parity: ``sky/provision/scp/instance.py``)."""
from skypilot_tpu.provision import neocloud_common
from skypilot_tpu.provision.scp import scp_api

_impl = neocloud_common.make_lifecycle(
    provider_name='scp',
    make_client=scp_api.make_client,
    state_map=scp_api.STATE_MAP,
    capacity_error=scp_api.ScpCapacityError,
    default_ssh_user='root',
    supports_stop=True,
)

run_instances = _impl['run_instances']
wait_instances = _impl['wait_instances']
get_cluster_info = _impl['get_cluster_info']
query_instances = _impl['query_instances']
stop_instances = _impl['stop_instances']
terminate_instances = _impl['terminate_instances']
open_ports = _impl['open_ports']
cleanup_ports = _impl['cleanup_ports']
