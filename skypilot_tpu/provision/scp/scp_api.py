"""Samsung Cloud Platform API client (parity:
``sky/provision/scp/scp_utils.py``).

curl against the SCP open API (HMAC-signed in the reference; here a
Bearer access key from $SCP_ACCESS_KEY or ~/.scp/scp_credential), or
the shared fake when ``SKYTPU_SCP_FAKE=1``.
"""
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake
from skypilot_tpu.provision import rest_transport

_API_URL = 'https://openapi.samsungsdscloud.com/virtual-server/v3'

STATE_MAP = {
    'CREATING': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'TERMINATING': 'terminating',
    'TERMINATED': 'terminated',
    'running': 'running',
    'stopped': 'stopped',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('insufficient resources', 'out of capacity',
                     'quota')


class ScpApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class ScpCapacityError(ScpApiError, provision_common.CapacityError):
    """Service zone out of the requested server type."""


def access_key() -> Optional[str]:
    key = os.environ.get('SCP_ACCESS_KEY')
    if key:
        return key
    path = os.path.expanduser('~/.scp/scp_credential')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            for line in f:
                if line.strip().startswith('access_key') and '=' in line:
                    return line.split('=', 1)[1].strip() or None
    return None


class RestTransport:
    """Real SCP through curl + the open API."""

    def __init__(self, key: str):
        self.key = key

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        def classify(o: dict) -> None:
            if o.get('errorCode'):
                msg = str(o.get('message', o['errorCode']))
                if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                    raise ScpCapacityError(msg)
                raise ScpApiError(msg)

        return rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}',
            f'header = "Authorization: Bearer {self.key}"\n', body,
            api_error=ScpApiError, classify=classify)

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del use_spot  # no spot market (gated at the cloud level)
        body: Dict[str, Any] = {
            'virtualServerName': name,
            'serviceZoneId': region,
            'serverType': instance_type,
            'imageId': 'ubuntu-22.04',
            'initialScript': (
                'mkdir -p /root/.ssh && '
                f'echo "{public_key}" >> /root/.ssh/authorized_keys'
            ) if public_key else '',
        }
        out = self._run('POST', '/virtual-servers', body)
        server_id = out.get('resourceId') or out.get('virtualServerId')
        if not server_id:
            raise ScpApiError(f'Server create returned no id: {out!r}')
        return str(server_id)

    def list(self) -> List[Dict[str, Any]]:
        out = self._run('GET', '/virtual-servers')
        items = out.get('contents', []) if isinstance(out, dict) else out
        return [{
            'id': str(s.get('virtualServerId', s.get('resourceId'))),
            'name': s.get('virtualServerName', ''),
            'instance_type': s.get('serverType', ''),
            'region': s.get('serviceZoneId', ''),
            'status': s.get('virtualServerState', 'CREATING'),
            'ip': s.get('natIpAddress'),
            'private_ip': s.get('ipAddress', ''),
        } for s in items]

    def stop(self, iid: str) -> None:
        self._run('POST', f'/virtual-servers/{iid}/stop')

    def start(self, iid: str) -> None:
        self._run('POST', f'/virtual-servers/{iid}/start')

    def terminate(self, iid: str) -> None:
        self._run('DELETE', f'/virtual-servers/{iid}')


def make_client(region=None):
    del region  # zone id rides in each request
    if neocloud_fake.fake_enabled('SCP'):
        return neocloud_fake.FakeNeoClient(
            'SCP', lambda r: ScpCapacityError(
                f'Insufficient resources in {r}. (fake)'))
    key = access_key()
    if key is None:
        raise ScpApiError('No SCP access key configured.')
    return RestTransport(key)
