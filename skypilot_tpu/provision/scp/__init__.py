"""Samsung Cloud Platform provisioner (parity: ``sky/provision/scp/``)."""
from skypilot_tpu.provision.scp.instance import cleanup_ports
from skypilot_tpu.provision.scp.instance import get_cluster_info
from skypilot_tpu.provision.scp.instance import open_ports
from skypilot_tpu.provision.scp.instance import query_instances
from skypilot_tpu.provision.scp.instance import run_instances
from skypilot_tpu.provision.scp.instance import stop_instances
from skypilot_tpu.provision.scp.instance import terminate_instances
from skypilot_tpu.provision.scp.instance import wait_instances
