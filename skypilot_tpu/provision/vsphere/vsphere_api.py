"""vSphere API client (parity: ``sky/provision/vsphere/vsphere_utils.py``).

On-prem: VMs are cloned from a template via the ``govc`` CLI (the
reference drives pyvmomi; govc speaks the same vSphere API without a
vendored SDK), or the shared fake when ``SKYTPU_VSPHERE_FAKE=1``.
"govc env" credentials: $GOVC_URL / $GOVC_USERNAME / $GOVC_PASSWORD.

The catalog's instance types ('vm-8x32', 'vm-8x64-a100') map to clone
specs: vCPU x memory, with GPU rows assuming the template's host has
the device in passthrough.
"""
import json
import os
import subprocess
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake

logger = sky_logging.init_logger(__name__)

STATE_MAP = {
    'poweredOn': 'running',
    'poweredOff': 'stopped',
    'suspended': 'stopped',
    'running': 'running',
    'stopped': 'stopped',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('insufficient', 'not enough', 'no host is compatible')


class VsphereApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class VsphereCapacityError(VsphereApiError,
                           provision_common.CapacityError):
    """The cluster cannot place the VM (host resources exhausted)."""


def _config(key: str, env: str,
            default: Optional[str] = None) -> Optional[str]:
    from skypilot_tpu import skypilot_config
    return skypilot_config.get_nested(('vsphere', key),
                                      None) or os.environ.get(env, default)


def _parse_instance_type(instance_type: str) -> Dict[str, int]:
    # 'vm-8x32' / 'vm-8x64-a100' → vcpus, memory GiB.
    parts = instance_type.split('-')[1].split('x')
    return {'cpus': int(parts[0]), 'memory_gb': int(parts[1])}


class GovcTransport:
    """Real vSphere through the govc CLI.

    Credentials resolve from config OR env ($GOVC_URL etc.) and are
    exported into every govc subprocess — govc itself only reads env,
    so a config-file-only setup must not silently launch a credless
    CLI.
    """

    def __init__(self):
        self.url = _config('url', 'GOVC_URL')
        if not self.url:
            raise VsphereApiError(
                'vSphere needs $GOVC_URL (+ username/password) or '
                'vsphere.url in ~/.skytpu/config.yaml.')
        self.username = _config('username', 'GOVC_USERNAME')
        self.password = _config('password', 'GOVC_PASSWORD')
        self.guest_login = _config('guest_login', 'GOVC_GUEST_LOGIN')
        self.ssh_user = _config('ssh_user', 'SKYTPU_VSPHERE_SSH_USER',
                                'ubuntu')
        self.template = _config('template', 'SKYTPU_VSPHERE_TEMPLATE',
                                'skytpu-ubuntu2204-template')

    def _run(self, args: List[str]) -> str:
        env = dict(os.environ)
        env['GOVC_URL'] = self.url
        if self.username:
            env['GOVC_USERNAME'] = self.username
        if self.password:
            env['GOVC_PASSWORD'] = self.password
        if self.guest_login:
            env['GOVC_GUEST_LOGIN'] = self.guest_login
        proc = subprocess.run(['govc'] + args, capture_output=True,
                              text=True, timeout=600, check=False,
                              env=env)
        if proc.returncode != 0:
            msg = proc.stderr.strip()
            if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                raise VsphereCapacityError(msg)
            raise VsphereApiError(f'govc {args[0]}: {msg}')
        return proc.stdout

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del region, use_spot  # one vCenter; no spot on-prem
        spec = _parse_instance_type(instance_type)
        args = ['vm.clone', '-vm', self.template, '-on=true',
                '-c', str(spec['cpus']),
                '-m', str(spec['memory_gb'] * 1024), name]
        self._run(args)
        if public_key and self.guest_login:
            # Guest-ops key injection into the SSH user's home (the
            # guest login may be root — '~' would be the wrong user).
            # Requires VMware Tools in the template + GOVC_GUEST_LOGIN.
            home = f'/home/{self.ssh_user}'
            self._run(['guest.run', '-vm', name, '/bin/sh', '-c',
                       f'mkdir -p {home}/.ssh && '
                       f'echo "{public_key}" >> '
                       f'{home}/.ssh/authorized_keys && '
                       f'chown -R {self.ssh_user} {home}/.ssh'])
        elif public_key:
            logger.warning(
                'vsphere.guest_login/$GOVC_GUEST_LOGIN not set: skipping '
                'SSH key injection — the template must already trust the '
                'skytpu key.')
        return name  # VM name is the id in govc

    def list(self) -> List[Dict[str, Any]]:
        out = self._run(['find', '-type', 'm', '-json'])
        try:
            paths = [str(p) for p in (json.loads(out)
                                      if out.strip() else [])]
        except json.JSONDecodeError:
            paths = [line for line in out.splitlines() if line.strip()]
        if not paths:
            return []
        # ONE batched vm.info over full inventory paths: per-VM calls
        # would cost M subprocesses per 5s poll, and basename lookups
        # duplicate records when names collide across folders.
        info = self._run(['vm.info', '-json'] + paths)
        try:
            parsed = json.loads(info)
        except json.JSONDecodeError as e:
            # Silently returning [] would make wait_instances time out
            # and status refresh declare live VMs terminated.
            raise VsphereApiError(
                f'vm.info returned non-JSON output: {e}') from None
        # govc <0.29 capitalizes the key.
        vms = parsed.get('virtualMachines') or parsed.get(
            'VirtualMachines') or []
        items = []
        for vm in vms:
            name = vm.get('name', '')
            items.append({
                'id': name,
                'name': name,
                'instance_type': '',
                'region': 'on-prem',
                'status': vm.get('runtime',
                                 {}).get('powerState', 'poweredOff'),
                'ip': vm.get('guest', {}).get('ipAddress'),
                'private_ip': vm.get('guest', {}).get('ipAddress', ''),
            })
        return items

    def stop(self, iid: str) -> None:
        self._run(['vm.power', '-off', '-force', iid])

    def start(self, iid: str) -> None:
        self._run(['vm.power', '-on', iid])

    def terminate(self, iid: str) -> None:
        self._run(['vm.destroy', iid])


def make_client(region=None):
    del region  # one vCenter endpoint
    if neocloud_fake.fake_enabled('VSPHERE'):
        return neocloud_fake.FakeNeoClient(
            'VSPHERE', lambda r: VsphereCapacityError(
                f'No host is compatible with the virtual machine in '
                f'{r}. (fake)'))
    return GovcTransport()
