"""vSphere on-prem provisioner (parity: ``sky/provision/vsphere/``)."""
from skypilot_tpu.provision.vsphere.instance import cleanup_ports
from skypilot_tpu.provision.vsphere.instance import get_cluster_info
from skypilot_tpu.provision.vsphere.instance import open_ports
from skypilot_tpu.provision.vsphere.instance import query_instances
from skypilot_tpu.provision.vsphere.instance import run_instances
from skypilot_tpu.provision.vsphere.instance import stop_instances
from skypilot_tpu.provision.vsphere.instance import terminate_instances
from skypilot_tpu.provision.vsphere.instance import wait_instances
