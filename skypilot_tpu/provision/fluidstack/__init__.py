"""Fluidstack GPU provisioner (parity: ``sky/provision/fluidstack/``)."""
from skypilot_tpu.provision.fluidstack.instance import cleanup_ports
from skypilot_tpu.provision.fluidstack.instance import get_cluster_info
from skypilot_tpu.provision.fluidstack.instance import open_ports
from skypilot_tpu.provision.fluidstack.instance import query_instances
from skypilot_tpu.provision.fluidstack.instance import run_instances
from skypilot_tpu.provision.fluidstack.instance import stop_instances
from skypilot_tpu.provision.fluidstack.instance import terminate_instances
from skypilot_tpu.provision.fluidstack.instance import wait_instances
