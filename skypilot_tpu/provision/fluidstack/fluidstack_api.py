"""Fluidstack API client (parity: ``sky/provision/fluidstack/
fluidstack_utils.py``).

curl against ``https://platform.fluidstack.io`` (api-key header from
$FLUIDSTACK_API_KEY or ~/.fluidstack/api_key), or the shared fake when
``SKYTPU_FLUIDSTACK_FAKE=1``.
"""
import json
import os
import subprocess
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake

_API_URL = 'https://platform.fluidstack.io'

STATE_MAP = {
    'pending': 'pending',
    'provisioning': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'terminated': 'terminated',
    'unhealthy': 'running',
}

_CAPACITY_MARKERS = ('out of capacity', 'no capacity',
                     'insufficient capacity')


class FluidstackApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class FluidstackCapacityError(FluidstackApiError, provision_common.CapacityError):
    """Region out of the requested GPU configuration."""


def api_key() -> Optional[str]:
    key = os.environ.get('FLUIDSTACK_API_KEY')
    if key:
        return key
    path = os.path.expanduser('~/.fluidstack/api_key')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            return f.read().strip() or None
    return None


class RestTransport:
    """Real Fluidstack through curl + the REST API."""

    def __init__(self, key: str):
        self.key = key

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        args = ['curl', '-sS', '-K', '-', '-X', method,
                '-H', 'Content-Type: application/json',
                f'{_API_URL}{path}']
        if body is not None:
            args += ['-d', json.dumps(body)]
        secret_cfg = f'header = "api-key: {self.key}"\n'
        proc = subprocess.run(args, input=secret_cfg, capture_output=True,
                              text=True, timeout=120, check=False)
        if proc.returncode != 0:
            raise FluidstackApiError(
                f'fluidstack api {path}: {proc.stderr.strip()}')
        out = json.loads(proc.stdout) if proc.stdout.strip() else {}
        if isinstance(out, dict) and out.get('error'):
            msg = str(out.get('message', out['error']))
            if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                raise FluidstackCapacityError(msg)
            raise FluidstackApiError(msg)
        return out

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del use_spot  # no spot market (gated at the cloud level)
        body: Dict[str, Any] = {
            'name': name,
            'region': region,
            'gpu_type': instance_type.split('x_', 1)[-1],
            'gpu_count': int(instance_type.split('x_', 1)[0]),
            'operating_system_label': 'ubuntu_22_04_lts_nvidia',
        }
        if public_key:
            body['ssh_key'] = public_key
        out = self._run('POST', '/instances', body)
        return str(out['id'])

    def list(self) -> List[Dict[str, Any]]:
        out = self._run('GET', '/instances')
        items = out if isinstance(out, list) else out.get('instances', [])
        return [{
            'id': str(i['id']),
            'name': i.get('name', ''),
            'instance_type': i.get('gpu_type', ''),
            'region': i.get('region', ''),
            'status': i.get('status', 'pending'),
            'ip': i.get('ip_address'),
            'private_ip': i.get('private_ip_address', ''),
        } for i in items]

    def stop(self, iid: str) -> None:
        self._run('PUT', f'/instances/{iid}/stop')

    def start(self, iid: str) -> None:
        self._run('PUT', f'/instances/{iid}/start')

    def terminate(self, iid: str) -> None:
        self._run('DELETE', f'/instances/{iid}')


def make_client(region=None):
    del region  # global API
    if neocloud_fake.fake_enabled('FLUIDSTACK'):
        return neocloud_fake.FakeNeoClient(
            'FLUIDSTACK', lambda region: FluidstackCapacityError(
                f'Out of capacity for the requested configuration in '
                f'{region}. (fake)'))
    key = api_key()
    if key is None:
        raise FluidstackApiError('No Fluidstack API key configured.')
    return RestTransport(key)
