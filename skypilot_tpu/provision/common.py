"""Provisioner shared types (parity: ``sky/provision/common.py:39-233``)."""
import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ProvisionConfig:
    """Everything a cloud provisioner needs to create instances."""
    provider_config: Dict[str, Any]       # cloud-specific (project, zone, …)
    authentication_config: Dict[str, Any]  # ssh user/keys
    docker_config: Dict[str, Any]
    node_config: Dict[str, Any]            # deploy variables from Resources
    count: int                             # logical nodes
    tags: Dict[str, str]
    resume_stopped_nodes: bool


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances (parity: common.py:63)."""
    provider_name: str
    region: str
    zone: Optional[str]
    cluster_name: str
    head_instance_id: str
    resumed_instance_ids: List[str]
    created_instance_ids: List[str]

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.resumed_instance_ids or
                instance_id in self.created_instance_ids)


@dataclasses.dataclass
class InstanceInfo:
    """One SSH target. A multi-host TPU slice yields one InstanceInfo per

    worker host of the node (parity: instance_utils.py:1635-1656 fan-out)."""
    instance_id: str
    internal_ip: str
    external_ip: Optional[str]
    tags: Dict[str, str]
    ssh_port: int = 22

    def get_feasible_ip(self) -> str:
        return self.external_ip or self.internal_ip


@dataclasses.dataclass
class ClusterInfo:
    """Post-provision cluster metadata (parity: common.py:109)."""
    instances: Dict[str, List[InstanceInfo]]  # instance_id -> host infos
    head_instance_id: Optional[str]
    provider_name: str
    provider_config: Dict[str, Any]
    ssh_user: str = 'skytpu'
    ssh_private_key: Optional[str] = None
    # Extra metadata (e.g. TPU accelerator_type, topology).
    custom_metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def get_head_instance(self) -> Optional[InstanceInfo]:
        if self.head_instance_id is None:
            return None
        infos = self.instances.get(self.head_instance_id)
        return infos[0] if infos else None

    def ordered_host_infos(self) -> List[InstanceInfo]:
        """All hosts in rank order: head instance's hosts first, then the

        rest — rank == index == TPU worker id within a slice. The single
        source of truth for host ordering (runners, cluster_info.json,
        cached handles all derive from it)."""
        order = [self.head_instance_id] + sorted(
            i for i in self.instances if i != self.head_instance_id)
        out: List[InstanceInfo] = []
        for iid in order:
            if iid is None:
                continue
            out.extend(self.instances.get(iid, []))
        return out

    def ordered_host_meta(self) -> List[Dict[str, Any]]:
        """Rank-ordered transport dicts (the ``hosts`` entries of

        cluster_info.json, also cached on ClusterHandle)."""
        hosts: List[Dict[str, Any]] = []
        # Task container image (docker): stamped on every host so runners
        # and gang_run wrap execution in `docker exec`. Kubernetes pods
        # already run the image natively — no stamp there.
        docker_image = self.provider_config.get('docker_image')
        for rank, info in enumerate(self.ordered_host_infos()):
            if 'node_dir' in info.tags:
                # Directory-backed host: the local cloud's nodes and the
                # fake Kubernetes backend's pods.
                hosts.append({
                    'transport': 'local',
                    'rank': rank,
                    'node_dir': info.tags['node_dir'],
                    'internal_ip': info.tags['node_dir'],
                })
            elif 'pod_name' in info.tags:
                hosts.append({
                    'transport': 'kubernetes',
                    'rank': rank,
                    'pod_name': info.tags['pod_name'],
                    'namespace': info.tags.get('namespace', 'default'),
                    'context': info.tags.get('context'),
                    'access_mode': info.tags.get('access_mode',
                                                 'kubectl-exec'),
                    'internal_ip': info.internal_ip,
                    # For the portforward-ssh access mode (sshd in the
                    # pod): same credentials as the ssh transport.
                    'ssh_user': self.ssh_user,
                    'ssh_key': self.ssh_private_key or '~/.skytpu/sky-key',
                })
            else:
                hosts.append({
                    'transport': 'ssh',
                    'rank': rank,
                    'ip': info.get_feasible_ip(),
                    'internal_ip': info.internal_ip,
                    'ssh_port': info.ssh_port,
                    'ssh_user': self.ssh_user,
                    'ssh_key': self.ssh_private_key or '~/.skytpu/sky-key',
                })
            if docker_image and hosts[-1]['transport'] != 'kubernetes':
                hosts[-1]['docker_image'] = docker_image
            # Multislice: gang_run derives per-slice TPU worker ids and
            # MEGASCALE envs from this (absent → single slice).
            if 'slice_index' in info.tags:
                hosts[-1]['slice_id'] = int(info.tags['slice_index'])
        return hosts

    def ip_tuples(self) -> List[tuple]:
        """[(internal_ip, external_ip)], rank order."""
        return [(i.internal_ip, i.external_ip)
                for i in self.ordered_host_infos()]

    def get_ssh_ports(self) -> List[int]:
        return [i.ssh_port for i in self.ordered_host_infos()]

    def num_hosts(self) -> int:
        return sum(len(v) for v in self.instances.values())


class ProvisionerError(RuntimeError):
    """Base error; carries blocked-resource hints for the failover engine."""
    errors: List[Dict[str, Any]] = []


class CapacityError(Exception):
    """Mixin base for per-cloud capacity/stockout errors.

    ``scope`` tells the failover engine how much to blocklist: 'zone'
    (sister zones may still work) or 'region' (quota / zoneless clouds —
    retrying sister zones cannot help). Per-cloud API errors multiply
    inherit: ``class AwsCapacityError(Ec2ApiError, CapacityError)`` —
    so ``bulk_provision`` and ``FailoverCloudErrorHandler.classify``
    need only this one type, not an import per cloud.
    """
    scope: str = 'region'


class StopFailoverError(ProvisionerError):
    """Cluster is partially up and must not fail over elsewhere."""
