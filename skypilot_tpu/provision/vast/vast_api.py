"""Vast.ai API client (parity: ``sky/provision/vast/utils.py``).

Vast is a GPU marketplace: deploy = search offers for the GPU shape in
the asked geography, then create an instance on the cheapest match.
curl against ``https://console.vast.ai/api/v0`` (Bearer key from
$VAST_API_KEY or ~/.vast_api_key), or the shared fake when
``SKYTPU_VAST_FAKE=1``. ``use_spot`` maps to interruptible instances.
"""
import json
import os
import urllib.parse
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake
from skypilot_tpu.provision import rest_transport

_API_URL = 'https://console.vast.ai/api/v0'

STATE_MAP = {
    'created': 'pending',
    'loading': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'exited': 'stopped',
    'terminated': 'terminated',
}


class VastApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class VastCapacityError(VastApiError, provision_common.CapacityError):
    """No marketplace offers match the GPU shape in the geography."""


# Catalog accelerator tokens → Vast marketplace gpu_name strings (the
# marketplace uses spaced names like 'RTX 4090', 'A100 SXM4 80GB').
_GPU_NAME_MAP = {
    'RTX4090': 'RTX 4090',
    'A100-80GB': 'A100 SXM4 80GB',
    'H100': 'H100 SXM',
}


def _vast_gpu_name(catalog_token: str) -> str:
    return _GPU_NAME_MAP.get(catalog_token,
                             catalog_token.replace('-', ' '))


def api_key() -> Optional[str]:
    key = os.environ.get('VAST_API_KEY')
    if key:
        return key
    path = os.path.expanduser('~/.vast_api_key')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            return f.read().strip() or None
    return None


class RestTransport:
    """Real Vast.ai through curl + the REST API."""

    def __init__(self, key: str):
        self.key = key

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        def classify(o: dict) -> None:
            if o.get('success') is False:
                raise VastApiError(str(o.get('msg', o)))

        return rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}',
            f'header = "Authorization: Bearer {self.key}"\n', body,
            api_error=VastApiError, classify=classify)

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        # '1x_RTX4090' → Vast gpu_name + num_gpus 1; region is a
        # geolocation filter ('US', 'EU', ...).
        count_s, gpu = instance_type.split('x_', 1)
        query = {
            'gpu_name': {'eq': _vast_gpu_name(gpu)},
            'num_gpus': {'eq': int(count_s)},
            'geolocation': {'in': [region]},
            'rentable': {'eq': True},
            'order': [['dph_total', 'asc']],
        }
        # The q parameter is JSON (spaces, quotes, braces): it MUST be
        # percent-encoded or curl/the server rejects the URL.
        q = urllib.parse.quote(json.dumps(query))
        offers = self._run('GET', f'/bundles?q={q}').get('offers', [])
        if not offers:
            raise VastCapacityError(
                f'No rentable {instance_type} offers in {region}.')
        offer_id = offers[0]['id']
        body: Dict[str, Any] = {
            'client_id': 'me',
            'image': 'vastai/base-image:cuda-12.2',
            'label': name,
            'runtype': 'ssh',
            'target_state': 'running',
        }
        if use_spot:
            body['min_bid'] = offers[0].get('min_bid',
                                            offers[0]['dph_total'])
        if public_key:
            body['env'] = {'SSH_PUBLIC_KEY': public_key}
        out = self._run('PUT', f'/asks/{offer_id}/', body)
        return str(out.get('new_contract', out.get('id')))

    def list(self) -> List[Dict[str, Any]]:
        out = self._run('GET', '/instances')
        items = out.get('instances', [])
        return [{
            'id': str(i['id']),
            'name': i.get('label', ''),
            'instance_type': i.get('gpu_name', ''),
            'region': i.get('geolocation', ''),
            'status': i.get('actual_status', 'created'),
            'ip': i.get('public_ipaddr'),
            'private_ip': i.get('local_ipaddrs', ''),
        } for i in items]

    def stop(self, iid: str) -> None:
        self._run('PUT', f'/instances/{iid}/',
                  {'state': 'stopped'})

    def start(self, iid: str) -> None:
        self._run('PUT', f'/instances/{iid}/',
                  {'state': 'running'})

    def terminate(self, iid: str) -> None:
        self._run('DELETE', f'/instances/{iid}/')


def make_client(region=None):
    del region  # global API
    if neocloud_fake.fake_enabled('VAST'):
        return neocloud_fake.FakeNeoClient(
            'VAST', lambda region: VastCapacityError(
                f'No rentable offers in {region}. (fake)'))
    key = api_key()
    if key is None:
        raise VastApiError('No Vast.ai API key configured.')
    return RestTransport(key)
