"""Vast.ai marketplace provisioner (parity: ``sky/provision/vast/``)."""
from skypilot_tpu.provision.vast.instance import cleanup_ports
from skypilot_tpu.provision.vast.instance import get_cluster_info
from skypilot_tpu.provision.vast.instance import open_ports
from skypilot_tpu.provision.vast.instance import query_instances
from skypilot_tpu.provision.vast.instance import run_instances
from skypilot_tpu.provision.vast.instance import stop_instances
from skypilot_tpu.provision.vast.instance import terminate_instances
from skypilot_tpu.provision.vast.instance import wait_instances
