"""IBM VPC provisioner (parity: ``sky/provision/ibm/``)."""
from skypilot_tpu.provision.ibm.instance import cleanup_ports
from skypilot_tpu.provision.ibm.instance import get_cluster_info
from skypilot_tpu.provision.ibm.instance import open_ports
from skypilot_tpu.provision.ibm.instance import query_instances
from skypilot_tpu.provision.ibm.instance import run_instances
from skypilot_tpu.provision.ibm.instance import stop_instances
from skypilot_tpu.provision.ibm.instance import terminate_instances
from skypilot_tpu.provision.ibm.instance import wait_instances
