"""IBM VPC instance lifecycle via the shared neocloud factory
(parity: ``sky/provision/ibm/instance.py``)."""
from skypilot_tpu.provision import neocloud_common
from skypilot_tpu.provision.ibm import ibm_api

_impl = neocloud_common.make_lifecycle(
    provider_name='ibm',
    make_client=ibm_api.make_client,
    state_map=ibm_api.STATE_MAP,
    capacity_error=ibm_api.IbmCapacityError,
    default_ssh_user='ubuntu',
    supports_stop=True,
)

run_instances = _impl['run_instances']
wait_instances = _impl['wait_instances']
get_cluster_info = _impl['get_cluster_info']
query_instances = _impl['query_instances']
stop_instances = _impl['stop_instances']
terminate_instances = _impl['terminate_instances']
open_ports = _impl['open_ports']
cleanup_ports = _impl['cleanup_ports']
