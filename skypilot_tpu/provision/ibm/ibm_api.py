"""IBM VPC API client (parity: ``sky/provision/ibm/utils.py``).

Drives the ``ibmcloud is`` CLI (``--output JSON``; the reference uses
the ibm-vpc SDK), or the shared fake when ``SKYTPU_IBM_FAKE=1``.
"""
import json
import os
import subprocess
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake

STATE_MAP = {
    'pending': 'pending',
    'starting': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'deleting': 'terminating',
    'deleted': 'terminated',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('insufficient capacity', 'quota', 'over limit')


class IbmApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class IbmCapacityError(IbmApiError, provision_common.CapacityError):
    """VPC zone out of the requested profile."""


def _config(key: str, env: str,
            default: Optional[str] = None) -> Optional[str]:
    from skypilot_tpu import skypilot_config
    return skypilot_config.get_nested(('ibm', key),
                                      None) or os.environ.get(env, default)


class CliTransport:
    """Real IBM VPC through the ibmcloud CLI."""

    def __init__(self, region: Optional[str] = None):
        self.region = region or _config('region', 'IBM_REGION',
                                        'us-south')

    def _run(self, args: List[str]) -> Any:
        proc = subprocess.run(
            ['ibmcloud', 'is'] + args + ['--output', 'JSON'],
            capture_output=True, text=True, timeout=300, check=False)
        if proc.returncode != 0:
            msg = proc.stderr.strip()
            if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                raise IbmCapacityError(msg)
            raise IbmApiError(f'ibmcloud is {args[0]}: {msg}')
        return json.loads(proc.stdout) if proc.stdout.strip() else {}

    def _required(self, key: str, env: str) -> str:
        value = _config(key, env)
        if not value:
            raise IbmApiError(
                f'IBM VPC launches need ibm.{key} in '
                f'~/.skytpu/config.yaml or ${env}.')
        return value

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del use_spot  # no spot market (gated at the cloud level)
        # VPC attaches pre-REGISTERED keys only: ibm.key_id must point
        # at the imported skytpu key (make_provision_config fails fast
        # when it is unset, mirroring the AWS key_name gate) — passing
        # the raw public key here is not part of the VPC create API.
        del public_key
        args = [
            'instance-create', name,
            self._required('vpc_id', 'IBM_VPC_ID'),
            region,  # zone == pseudo-zone == region in our catalog
            instance_type,
            self._required('subnet_id', 'IBM_SUBNET_ID'),
            '--image', self._required('image_id', 'IBM_IMAGE_ID'),
            '--keys', self._required('key_id', 'IBM_KEY_ID'),
        ]
        out = self._run(args)
        instance_id = str(out['id'])
        # VPC instances have only 10.x addresses until a floating IP is
        # attached — without one, SSH bootstrap can never reach the host
        # and the launch dies as a 10-minute timeout with billing on.
        self._run(['floating-ip-reserve', f'{name}-fip', '--nic',
                   'primary', '--in', instance_id])
        return instance_id

    def list(self) -> List[Dict[str, Any]]:
        out = self._run(['instances'])
        items = out if isinstance(out, list) else out.get('instances', [])
        return [{
            'id': str(i['id']),
            'name': i.get('name', ''),
            'instance_type': i.get('profile', {}).get('name', ''),
            'region': i.get('zone', {}).get('name', self.region),
            'status': i.get('status', 'pending'),
            'ip': (i.get('primary_network_interface', {})
                   .get('floating_ip', {}).get('address')),
            'private_ip': (i.get('primary_network_interface', {})
                           .get('primary_ip', {}).get('address', '')),
        } for i in items]

    def stop(self, iid: str) -> None:
        self._run(['instance-stop', iid, '--force'])

    def start(self, iid: str) -> None:
        self._run(['instance-start', iid])

    def terminate(self, iid: str) -> None:
        self._run(['instance-delete', iid, '--force'])


def make_client(region=None):
    if neocloud_fake.fake_enabled('IBM'):
        return neocloud_fake.FakeNeoClient(
            'IBM', lambda r: IbmCapacityError(
                f'Insufficient capacity in {r}. (fake)'))
    return CliTransport(region)
