"""Provision orchestrator: create instances → wait SSH → runtime setup.

Parity: ``sky/provision/provisioner.py:101`` (bulk_provision), ``:353``
(wait_for_ssh), ``:643`` (post_provision_runtime_setup) +
``sky/provision/instance_setup.py`` — with the Ray bootstrap replaced by the
TPU-native gang runtime: sync the framework package to every host, write
``cluster_info.json`` (slice membership in TPU-worker order), start a skylet
per host.
"""
import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu.observability import journal
from skypilot_tpu.provision import common
from skypilot_tpu.skylet import constants
from skypilot_tpu.utils import command_runner as command_runner_lib
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)

_MAX_RETRY = 3


@dataclasses.dataclass
class ProvisionResult:
    record: common.ProvisionRecord
    cluster_info: common.ClusterInfo


def make_runners(
        cluster_info: common.ClusterInfo,
        wrap_docker: bool = True
) -> List[command_runner_lib.CommandRunner]:
    """One CommandRunner per host, rank order (head's hosts first)."""
    return runners_from_host_meta(cluster_info.ordered_host_meta(),
                                  wrap_docker=wrap_docker)


def runners_from_host_meta(
        hosts_meta: List[Dict[str, Any]],
        wrap_docker: bool = True
) -> List[command_runner_lib.CommandRunner]:
    """wrap_docker=False yields RAW host runners — needed before the task
    container exists (connection probes, container bootstrap)."""
    runners: List[command_runner_lib.CommandRunner] = []
    for host in hosts_meta:
        node_id = f'rank-{host["rank"]}'
        if host['transport'] == 'local':
            runners.append(
                command_runner_lib.LocalProcessRunner(
                    node_id, host['node_dir']))
        elif host['transport'] == 'kubernetes':
            if host.get('access_mode') == 'portforward-ssh':
                # SSH over kubectl port-forward (pod image runs sshd) —
                # the reference's 'portforward' networking mode.
                runners.append(
                    command_runner_lib.PortForwardSSHRunner(
                        node_id,
                        host['pod_name'],
                        ssh_user=host.get('ssh_user', 'skytpu'),
                        ssh_private_key=host.get(
                            'ssh_key', '~/.skytpu/sky-key'),
                        namespace=host.get('namespace', 'default'),
                        context=host.get('context'),
                        ssh_control_name=f'{host["pod_name"]}'))
            else:
                runners.append(
                    command_runner_lib.KubectlExecRunner(
                        node_id,
                        host['pod_name'],
                        namespace=host.get('namespace', 'default'),
                        context=host.get('context')))
        else:
            runners.append(
                command_runner_lib.SSHCommandRunner(
                    node_id,
                    host['ip'],
                    host['ssh_user'],
                    host['ssh_key'],
                    ssh_control_name=f'{host["ip"]}-{host["rank"]}',
                    port=host.get('ssh_port', 22)))
        if wrap_docker and host.get('docker_image'):
            from skypilot_tpu.provision import docker_utils
            runners[-1] = docker_utils.DockerRunner(runners[-1])
    return runners


@timeline.event
def bulk_provision(provider_name: str, region: str,
                   cluster_name_on_cloud: str,
                   config: common.ProvisionConfig) -> common.ProvisionRecord:
    """Create instances with bounded retries (parity: provisioner.py:101)."""
    last_exc: Optional[Exception] = None
    for attempt in range(_MAX_RETRY):
        try:
            record = provision.run_instances(provider_name, region,
                                             cluster_name_on_cloud, config)
            provision.wait_instances(provider_name, region,
                                     cluster_name_on_cloud,
                                     state='running',
                                     provider_config=config.provider_config)
            return record
        except Exception as e:  # pylint: disable=broad-except
            if isinstance(e, common.CapacityError):
                raise  # capacity errors go straight to the failover engine
            last_exc = e
            logger.warning(f'Provision attempt {attempt + 1} failed: {e}')
            time.sleep(2**attempt)
    assert last_exc is not None
    raise last_exc


@timeline.event
def wait_for_ssh(cluster_info: common.ClusterInfo,
                 timeout: float = 600.0,
                 cluster_name: Optional[str] = None) -> None:
    """Probe every host until reachable (parity: provisioner.py:353).

    Raw host runners: the task container (if any) does not exist yet."""
    runners = make_runners(cluster_info, wrap_docker=False)
    deadline = time.time() + timeout
    t0 = time.time()

    def _wait(runner) -> None:
        backoff = 1.0
        while True:
            if runner.check_connection():
                return
            if time.time() > deadline:
                raise common.ProvisionerError(
                    f'SSH to {runner.node_id} not ready after {timeout}s.')
            time.sleep(backoff)
            backoff = min(backoff * 1.5, 10.0)

    subprocess_utils.run_in_parallel(_wait, runners)
    journal.event(journal.EventKind.PROVISION_WAIT_SSH,
                  f'cluster:{cluster_name}' if cluster_name else '',
                  {'hosts': len(runners),
                   'seconds': round(time.time() - t0, 3)})


def _runtime_sync_source() -> str:
    """Path of the framework package to sync to hosts."""
    import skypilot_tpu
    return os.path.dirname(os.path.abspath(skypilot_tpu.__file__))


@timeline.event
def post_provision_runtime_setup(
        cluster_name: str, cluster_name_on_cloud: str,
        cluster_info: common.ClusterInfo,
        provider_config: Dict[str, Any]) -> None:
    """Install the gang runtime on every host (parity: provisioner.py:643 +

    instance_setup.py:202): sync package, write cluster_info.json, start a
    skylet per host. Head = rank 0 = TPU worker 0.
    """
    hosts_meta = cluster_info.ordered_host_meta()

    # Task container bootstrap (docker image): pull + start the idle
    # container on every RAW host first; all later steps run inside it.
    docker_image = provider_config.get('docker_image')
    if docker_image:
        from skypilot_tpu.provision import docker_utils
        raw_runners = runners_from_host_meta(hosts_meta, wrap_docker=False)

        def _bootstrap_one(runner) -> None:
            rc, _, err = runner.run(
                docker_utils.bootstrap_command(docker_image),
                require_outputs=True,
                timeout=600)
            subprocess_utils.handle_returncode(
                rc, 'docker bootstrap',
                f'Failed to start task container on {runner.node_id}', err)

        subprocess_utils.run_in_parallel(_bootstrap_one, raw_runners)

    runners = runners_from_host_meta(hosts_meta)

    info_payload = {
        'cluster_name': cluster_name,
        'cluster_name_on_cloud': cluster_name_on_cloud,
        'provider_name': cluster_info.provider_name,
        'provider_config': _jsonable(provider_config),
        'chips_per_host': cluster_info.custom_metadata.get('chips_per_host',
                                                           0),
        'accelerator_type':
            cluster_info.custom_metadata.get('accelerator_type'),
        'hosts': hosts_meta,
    }

    pkg_src = _runtime_sync_source()

    def _setup_one(args) -> None:
        runner, host_meta = args
        # 1) sync the framework package → ~/.skytpu/runtime/skypilot_tpu
        # (rsync goes to the HOST filesystem; a task container bind-mounts
        # it).
        runner.run('mkdir -p ~/.skytpu/runtime ~/sky_logs ~/.skytpu/jobs',
                   timeout=60)
        command_runner_lib.rsync_home(runner, pkg_src + '/',
                                      '~/.skytpu/runtime/skypilot_tpu/',
                                      up=True)
        # 2) cluster_info.json on each host
        payload = json.dumps(info_payload)
        runner.run(
            f'cat > ~/.skytpu/cluster_info.json << "SKYTPU_EOF"\n'
            f'{payload}\nSKYTPU_EOF',
            timeout=60)
        # 3) start skylet (idempotent; drop a pidfile)
        start_cmd = (
            'cd ~ && '
            'if [ -f ~/.skytpu/skylet.pid ] && '
            'kill -0 $(cat ~/.skytpu/skylet.pid) 2>/dev/null; then '
            'echo skylet already running; else '
            'PYTHONPATH=~/.skytpu/runtime:$PYTHONPATH '
            f'{constants.SKYLET_HOME_ENV}=$HOME '
            f'{constants.accel_strip_shell_prefix()}'
            'nohup python3 -m skypilot_tpu.skylet.skylet '
            '> ~/.skytpu/skylet.log 2>&1 < /dev/null & '
            'echo $! > ~/.skytpu/skylet.pid; fi',
            )[0]
        rc, out, err = runner.run(start_cmd, require_outputs=True,
                                  timeout=120)
        subprocess_utils.handle_returncode(
            rc, 'skylet start', f'Failed to start skylet on '
            f'{runner.node_id}', err)

    subprocess_utils.run_in_parallel(_setup_one,
                                     list(zip(runners, hosts_meta)))
    journal.event(journal.EventKind.PROVISION_RUNTIME_SETUP,
                  f'cluster:{cluster_name}', {'hosts': len(runners)})
    logger.debug(f'Runtime setup complete on {len(runners)} host(s).')


def _jsonable(d: Dict[str, Any]) -> Dict[str, Any]:
    return json.loads(json.dumps(d, default=str))


@timeline.event
def teardown_cluster(provider_name: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any],
                     terminate: bool,
                     ports: Optional[List[str]] = None) -> None:
    """Parity: provisioner.py:204 teardown_cluster.

    ``ports``: the cluster's opened `ports:` (from the handle) — clouds
    whose exposure lives on SHARED objects (AWS security-group rules)
    need the exact rules to revoke; per-cluster objects (k8s service,
    GCP firewall) ignore it and delete by name.
    """
    if terminate:
        try:
            # Port exposure dies with the cluster; best-effort — a
            # missing service/rule must not block instance teardown.
            provision.cleanup_ports(provider_name, cluster_name_on_cloud,
                                    list(ports or []),
                                    provider_config=provider_config)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'cleanup_ports({cluster_name_on_cloud}): {e}')
        provision.terminate_instances(provider_name, cluster_name_on_cloud,
                                      provider_config=provider_config)
    else:
        provision.stop_instances(provider_name, cluster_name_on_cloud,
                                 provider_config=provider_config)
