"""OCI compute provisioner (parity: ``sky/provision/oci/``)."""
from skypilot_tpu.provision.oci.instance import cleanup_ports
from skypilot_tpu.provision.oci.instance import get_cluster_info
from skypilot_tpu.provision.oci.instance import open_ports
from skypilot_tpu.provision.oci.instance import query_instances
from skypilot_tpu.provision.oci.instance import run_instances
from skypilot_tpu.provision.oci.instance import stop_instances
from skypilot_tpu.provision.oci.instance import terminate_instances
from skypilot_tpu.provision.oci.instance import wait_instances
