"""OCI compute API client (parity: ``sky/provision/oci/query_utils.py``).

Drives the ``oci`` CLI (``--output json``; the reference uses the oci
SDK), or the shared fake when ``SKYTPU_OCI_FAKE=1``. Instances carry
cluster membership in their display name (``<cluster>-<i>``) — the
factory's name scheme — plus a freeform tag. Spot = preemptible
instances (~50% off, terminated-on-reclaim).
"""
import json
import os
import subprocess
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake

STATE_MAP = {
    'PROVISIONING': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'TERMINATING': 'terminating',
    'TERMINATED': 'terminated',
    'running': 'running',
    'stopped': 'stopped',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('out of host capacity', 'outofhostcapacity',
                     'limitexceeded', 'quotaexceeded')


class OciApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class OciCapacityError(OciApiError, provision_common.CapacityError):
    """Out-of-host-capacity / service limits. OCI availability domains
    are modeled as pseudo-zones of the region; capacity errors blocklist
    the region (ADs share shape limits)."""


def compartment_id() -> Optional[str]:
    from skypilot_tpu import skypilot_config
    return skypilot_config.get_nested(
        ('oci', 'compartment_id'), None) or os.environ.get(
            'OCI_COMPARTMENT_ID')


class CliTransport:
    """Real OCI through the oci CLI, scoped to one region.

    The factory builds the client with the operation's region (from the
    failover walk or the cluster's provider_config), so list/terminate
    see the same region deploy used; config's oci.region / $OCI_REGION
    is only the fallback.
    """

    def __init__(self, region: Optional[str] = None):
        from skypilot_tpu import skypilot_config
        self.region = region or skypilot_config.get_nested(
            ('oci', 'region'), None) or os.environ.get(
                'OCI_REGION', 'us-ashburn-1')
        self.compartment = compartment_id()
        if not self.compartment:
            raise OciApiError(
                'OCI launches need oci.compartment_id in '
                '~/.skytpu/config.yaml or $OCI_COMPARTMENT_ID.')

    def _required(self, key: str, env: str) -> str:
        from skypilot_tpu import skypilot_config
        value = skypilot_config.get_nested(('oci', key),
                                           None) or os.environ.get(env)
        if not value:
            raise OciApiError(
                f'OCI launches need oci.{key} in ~/.skytpu/config.yaml '
                f'or ${env} (instance launch requires it).')
        return value

    def _run(self, args: List[str],
             region: Optional[str] = None) -> Any:
        proc = subprocess.run(
            ['oci', '--region', region or self.region, '--output',
             'json'] + args,
            capture_output=True, text=True, timeout=300, check=False)
        if proc.returncode != 0:
            msg = proc.stderr.strip()
            if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                raise OciCapacityError(msg)
            raise OciApiError(f'oci {args[0]}: {msg}')
        return json.loads(proc.stdout) if proc.stdout.strip() else {}

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        # `oci compute instance launch` REQUIRES an availability domain,
        # a subnet, and a real image OCID — all tenancy-specific; fail
        # with actionable config guidance instead of a CLI usage error.
        args = [
            'compute', 'instance', 'launch',
            '--compartment-id', self.compartment,
            '--availability-domain',
            self._required('availability_domain',
                           'OCI_AVAILABILITY_DOMAIN'),
            '--subnet-id', self._required('subnet_id', 'OCI_SUBNET_ID'),
            '--image-id', self._required('image_id', 'OCI_IMAGE_ID'),
            '--display-name', name,
            '--shape', instance_type,
            '--freeform-tags', json.dumps({'skytpu': name}),
        ]
        if use_spot:
            args += ['--preemptible-instance-config',
                     json.dumps({'preemptionAction': {
                         'type': 'TERMINATE',
                         'preserveBootVolume': False}})]
        if public_key:
            args += ['--metadata',
                     json.dumps({'ssh_authorized_keys': public_key})]
        out = self._run(args, region=region)
        return out['data']['id']

    def _vnic_ips(self, instance_id: str) -> Dict[str, Optional[str]]:
        # Instance listings carry no addresses on OCI; the primary vnic
        # does.
        out = self._run(['compute', 'instance', 'list-vnics',
                         '--instance-id', instance_id])
        for vnic in out.get('data', []):
            if vnic.get('is-primary', True):
                return {'ip': vnic.get('public-ip'),
                        'private_ip': vnic.get('private-ip', '')}
        return {'ip': None, 'private_ip': ''}

    def list(self) -> List[Dict[str, Any]]:
        out = self._run(['compute', 'instance', 'list',
                         '--compartment-id', self.compartment])
        items = []
        for inst in out.get('data', []):
            state = inst.get('lifecycle-state', 'PROVISIONING')
            ips = {'ip': None, 'private_ip': ''}
            if state in ('RUNNING', 'STARTING', 'STOPPING', 'STOPPED'):
                ips = self._vnic_ips(inst['id'])
            items.append({
                'id': inst['id'],
                'name': inst.get('display-name', ''),
                'instance_type': inst.get('shape', ''),
                'region': inst.get('region', self.region),
                'status': state,
                **ips,
            })
        return items

    def _action(self, iid: str, action: str) -> None:
        self._run(['compute', 'instance', 'action',
                   '--instance-id', iid, '--action', action])

    def stop(self, iid: str) -> None:
        self._action(iid, 'STOP')

    def start(self, iid: str) -> None:
        self._action(iid, 'START')

    def terminate(self, iid: str) -> None:
        self._run(['compute', 'instance', 'terminate',
                   '--instance-id', iid, '--force'])


def make_client(region=None):
    if neocloud_fake.fake_enabled('OCI'):
        return neocloud_fake.FakeNeoClient(
            'OCI', lambda r: OciCapacityError(
                f'Out of host capacity in {r}. (fake)'))
    return CliTransport(region)
