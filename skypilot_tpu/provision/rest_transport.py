"""Shared curl-based JSON REST helper for neocloud transports.

Secrets ride a curl config on stdin (``-K -``) — never argv, which is
world-readable via /proc/<pid>/cmdline.
"""
import json
import subprocess
from typing import Any, Optional, Type


def classified_curl_json(method: str, url: str, secret_config: str,
                         body: Optional[dict] = None,
                         api_error: Type[Exception] = RuntimeError,
                         classify=None, timeout: int = 120) -> Any:
    """:func:`curl_json` + error-body classification.

    ``classify(body_dict)`` is the transport's marker check: it raises
    the cloud's richer error (e.g. its CapacityError subclass — feeding
    the failover engine) when the body carries the cloud's error shape,
    and returns None otherwise. It runs on BOTH success bodies (APIs
    that answer 200 + error payload) and HTTP >= 400 bodies (APIs that
    answer 4xx + error payload); an HTTP error whose body classify
    doesn't recognize raises the generic ``api_error``.
    """
    try:
        out = curl_json(method, url, secret_config, body,
                        api_error=api_error, timeout=timeout)
    except api_error as exc:
        http_body = getattr(exc, 'http_body', None)
        if classify is not None and isinstance(http_body, dict):
            classify(http_body)  # may raise the richer error
        raise
    if classify is not None and isinstance(out, dict):
        classify(out)
    return out


def curl_json(method: str, url: str, secret_config: str,
              body: Optional[dict] = None,
              api_error: Type[Exception] = RuntimeError,
              timeout: int = 120) -> Any:
    """One JSON request; raises ``api_error`` on transport failure.

    ``secret_config`` is a curl config snippet, e.g.
    ``'header = "Authorization: Bearer <key>"\\n'``.
    """
    # '\n<status>' trailer on stdout: curl's -w write-out is the only
    # way to see the HTTP status without -i header parsing. An error
    # status whose JSON body happens to lack the per-cloud marker shape
    # (e.g. a 401 {"detail": ...}) must classify as an API error here,
    # not surface later as a KeyError in deploy/list.
    args = ['curl', '-sS', '-K', '-', '-X', method,
            '-H', 'Content-Type: application/json',
            '-w', '\n%{http_code}', url]
    if body is not None:
        args += ['-d', json.dumps(body)]
    proc = subprocess.run(args, input=secret_config, capture_output=True,
                          text=True, timeout=timeout, check=False)
    if proc.returncode != 0:
        raise api_error(f'{method} {url}: {proc.stderr.strip()}')
    payload, _, status_str = proc.stdout.rpartition('\n')
    try:
        status = int(status_str.strip() or '0')
    except ValueError:
        status, payload = 0, proc.stdout
    if status >= 400:
        # Clouds report capacity stockouts as 4xx JSON. The raised
        # api_error carries the parsed body (``http_body``/``http_status``
        # attributes) so the per-cloud transport can re-classify it into
        # its CapacityError taxonomy and keep the failover engine fed.
        try:
            parsed = json.loads(payload)
        except json.JSONDecodeError:
            parsed = None
        exc = api_error(
            f'{method} {url}: HTTP {status}: {payload.strip()[:500]}')
        exc.http_status = status
        exc.http_body = parsed
        raise exc
    if not payload.strip():
        return {}
    try:
        return json.loads(payload)
    except json.JSONDecodeError:
        # Gateways answer 5xx with HTML; that must classify as the
        # cloud's API error (feeding retry/rollback), not leak a raw
        # JSONDecodeError past neocloud_common's handling.
        raise api_error(
            f'{method} {url}: non-JSON response '
            f'{payload.strip()[:200]!r}') from None
