"""Shared curl-based JSON REST helper for neocloud transports.

Secrets ride a curl config on stdin (``-K -``) — never argv, which is
world-readable via /proc/<pid>/cmdline.
"""
import json
import subprocess
from typing import Any, Optional, Type


def curl_json(method: str, url: str, secret_config: str,
              body: Optional[dict] = None,
              api_error: Type[Exception] = RuntimeError,
              timeout: int = 120) -> Any:
    """One JSON request; raises ``api_error`` on transport failure.

    ``secret_config`` is a curl config snippet, e.g.
    ``'header = "Authorization: Bearer <key>"\\n'``.
    """
    args = ['curl', '-sS', '-K', '-', '-X', method,
            '-H', 'Content-Type: application/json', url]
    if body is not None:
        args += ['-d', json.dumps(body)]
    proc = subprocess.run(args, input=secret_config, capture_output=True,
                          text=True, timeout=timeout, check=False)
    if proc.returncode != 0:
        raise api_error(f'{method} {url}: {proc.stderr.strip()}')
    if not proc.stdout.strip():
        return {}
    try:
        return json.loads(proc.stdout)
    except json.JSONDecodeError:
        # Gateways answer 5xx with HTML; that must classify as the
        # cloud's API error (feeding retry/rollback), not leak a raw
        # JSONDecodeError past neocloud_common's handling.
        raise api_error(
            f'{method} {url}: non-JSON response '
            f'{proc.stdout.strip()[:200]!r}') from None
