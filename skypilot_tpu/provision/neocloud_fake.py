"""Shared in-memory fake for factory-built neocloud provisioners
(DigitalOcean, Fluidstack, Vast — see ``neocloud_common.make_lifecycle``).

Normalized statuses ('running'/'stopped'/'terminated') double as the
state-map keys, so one fake serves every cloud. Fault injection:
``SKYTPU_<KEY>_FAKE_STOCKOUT='<region>,...'`` makes deploy raise the
cloud's capacity error; ``SKYTPU_<KEY>_FAKE_STATE=<path>`` shares state
across processes.
"""
import json
import os
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

# Per-cloud in-process state: cloud key → {instance id → record}.
_STATES: Dict[str, Dict[str, Dict[str, Any]]] = {}
_LOCK = threading.Lock()


class FakeNeoClient:
    """deploy/list/stop/start/terminate against in-memory state."""

    def __init__(self, cloud_key: str,
                 capacity_error: Callable[[str], Exception],
                 ip_prefix: str = '203.0.113'):
        self.cloud_key = cloud_key.upper()
        self.capacity_error = capacity_error
        self.ip_prefix = ip_prefix
        self._state_env = f'SKYTPU_{self.cloud_key}_FAKE_STATE'
        self._stockout_env = f'SKYTPU_{self.cloud_key}_FAKE_STOCKOUT'

    def _load(self) -> Dict[str, Dict[str, Any]]:
        path = os.environ.get(self._state_env)
        if path and os.path.exists(path):
            with open(path, encoding='utf-8') as f:
                return json.load(f)
        return _STATES.setdefault(self.cloud_key, {})

    def _save(self, state: Dict[str, Dict[str, Any]]) -> None:
        path = os.environ.get(self._state_env)
        if path:
            with open(path, 'w', encoding='utf-8') as f:
                json.dump(state, f)
        else:
            _STATES[self.cloud_key] = state

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del public_key
        stockout = os.environ.get(self._stockout_env, '').split(',')
        if region in stockout:
            raise self.capacity_error(region)
        with _LOCK:
            state = self._load()
            iid = f'{self.cloud_key.lower()}-{uuid.uuid4().hex[:12]}'
            n = len(state)
            state[iid] = {
                'id': iid,
                'name': name,
                'instance_type': instance_type,
                'region': region,
                'status': 'running',
                'ip': f'{self.ip_prefix}.{n + 10}',
                'private_ip': f'10.100.0.{n + 10}',
                'use_spot': use_spot,
            }
            self._save(state)
            return iid

    def list(self) -> List[Dict[str, Any]]:
        return [dict(i) for i in self._load().values()
                if i['status'] != 'terminated']

    def _set(self, iid: str, status: str) -> None:
        with _LOCK:
            state = self._load()
            if iid in state:
                state[iid]['status'] = status
            self._save(state)

    def stop(self, iid: str) -> None:
        self._set(iid, 'stopped')

    def start(self, iid: str) -> None:
        self._set(iid, 'running')

    def terminate(self, iid: str) -> None:
        self._set(iid, 'terminated')


def reset(cloud_key: str) -> None:
    """Test helper: drop the in-process state for one cloud."""
    _STATES.pop(cloud_key.upper(), None)


def fake_enabled(cloud_key: str) -> bool:
    return os.environ.get(f'SKYTPU_{cloud_key.upper()}_FAKE', '0') == '1'
