"""Cudo Compute provisioner (parity: ``sky/provision/cudo/``)."""
from skypilot_tpu.provision.cudo.instance import cleanup_ports
from skypilot_tpu.provision.cudo.instance import get_cluster_info
from skypilot_tpu.provision.cudo.instance import open_ports
from skypilot_tpu.provision.cudo.instance import query_instances
from skypilot_tpu.provision.cudo.instance import run_instances
from skypilot_tpu.provision.cudo.instance import stop_instances
from skypilot_tpu.provision.cudo.instance import terminate_instances
from skypilot_tpu.provision.cudo.instance import wait_instances
