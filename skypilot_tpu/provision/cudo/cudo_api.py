"""Cudo Compute API client (parity: ``sky/provision/cudo/cudo_wrapper.py``).

curl against ``https://rest.compute.cudo.org/v1`` (Bearer key from
$CUDO_API_KEY or ~/.config/cudo/cudo.yml), or the shared fake when
``SKYTPU_CUDO_FAKE=1``.
"""
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake
from skypilot_tpu.provision import rest_transport

_API_URL = 'https://rest.compute.cudo.org/v1'

STATE_MAP = {
    'PENDING': 'pending',
    'BOOTING': 'pending',
    'ACTIVE': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'terminating',
    'DELETED': 'terminated',
    'running': 'running',
    'stopped': 'stopped',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('no hosts available', 'insufficient capacity',
                     'out of stock')


class CudoApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class CudoCapacityError(CudoApiError, provision_common.CapacityError):
    """Datacenter out of the requested machine configuration."""


def api_key() -> Optional[str]:
    key = os.environ.get('CUDO_API_KEY')
    if key:
        return key
    path = os.path.expanduser('~/.config/cudo/cudo.yml')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            for line in f:
                if line.strip().startswith('key:'):
                    return line.split(':', 1)[1].strip().strip('"') or None
    return None


def project_id() -> str:
    from skypilot_tpu import skypilot_config
    return skypilot_config.get_nested(
        ('cudo', 'project_id'), None) or os.environ.get(
            'CUDO_PROJECT_ID', 'default')


class RestTransport:
    """Real Cudo through curl + the REST API."""

    def __init__(self, key: str):
        self.key = key
        self.project = project_id()

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        def classify(o: dict) -> None:
            if o.get('code'):
                msg = str(o.get('message', o))
                if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                    raise CudoCapacityError(msg)
                raise CudoApiError(msg)

        return rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}',
            f'header = "Authorization: Bearer {self.key}"\n', body,
            api_error=CudoApiError, classify=classify)

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del use_spot  # no spot market (gated at the cloud level)
        body: Dict[str, Any] = {
            'vmId': name,
            'dataCenterId': region,
            'machineType': instance_type,
            'bootDiskImageId': 'ubuntu-2204-nvidia-535-docker-v20240214',
            'bootDisk': {'sizeGib': 100},
        }
        if public_key:
            body['sshKeySource'] = 'SSH_KEY_SOURCE_NONE'
            body['customSshKeys'] = [public_key]
        self._run('POST', f'/projects/{self.project}/vm', body)
        return name  # Cudo VM ids are caller-chosen

    def list(self) -> List[Dict[str, Any]]:
        out = self._run('GET', f'/projects/{self.project}/vms')
        return [{
            'id': str(vm['id']),
            'name': str(vm['id']),  # vmId doubles as the name
            'instance_type': vm.get('machineType', ''),
            'region': vm.get('dataCenterId', ''),
            'status': vm.get('vmState', vm.get('state', 'PENDING')),
            'ip': vm.get('publicIpAddress'),
            'private_ip': vm.get('internalIpAddress', ''),
        } for vm in out.get('vms', [])]

    def stop(self, iid: str) -> None:
        self._run('POST', f'/projects/{self.project}/vms/{iid}/stop')

    def start(self, iid: str) -> None:
        self._run('POST', f'/projects/{self.project}/vms/{iid}/start')

    def terminate(self, iid: str) -> None:
        self._run('POST', f'/projects/{self.project}/vms/{iid}/terminate')


def make_client(region=None):
    del region  # global API
    if neocloud_fake.fake_enabled('CUDO'):
        return neocloud_fake.FakeNeoClient(
            'CUDO', lambda region: CudoCapacityError(
                f'No hosts available in {region}. (fake)'))
    key = api_key()
    if key is None:
        raise CudoApiError('No Cudo API key configured.')
    return RestTransport(key)
