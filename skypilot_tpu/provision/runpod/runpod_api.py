"""RunPod API client with a fake backend.

Parity: the reference drives the ``runpod`` SDK from
``sky/provision/runpod/utils.py``; this build talks to the REST API
(``https://rest.runpod.io/v1``) via curl with the usual two-transport
shape:

* :class:`RestTransport` — real pods via curl.
* :class:`FakeRunPodService` — in-memory pods, used by tests and when
  ``SKYTPU_RUNPOD_FAKE=1``. Fault injection:
  ``SKYTPU_RUNPOD_FAKE_STOCKOUT='US-CA-1,...'`` makes deploy in those
  datacenters raise "no instances available".

Normalized pod dict::

    {'id', 'name', 'instance_type', 'region', 'status', 'ip',
     'private_ip', 'interruptible'}

Pod statuses: CREATED | RUNNING | EXITED | TERMINATED.
"""
import json
import os
import subprocess
import threading
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import rest_transport
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_FAKE_STATE_ENV = 'SKYTPU_RUNPOD_FAKE_STATE'
_API_URL = 'https://rest.runpod.io/v1'

_CAPACITY_MARKERS = ('no instances available',
                     'no longer any instances available',
                     'not enough free gpus')


class RunPodApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class RunPodCapacityError(RunPodApiError, provision_common.CapacityError):
    """Datacenter out of the requested GPU shape. RunPod has no zones:
    scope is always the datacenter ("region")."""


def _raise_for_error(message: str) -> None:
    lowered = message.lower()
    if any(m in lowered for m in _CAPACITY_MARKERS):
        raise RunPodCapacityError(message)
    raise RunPodApiError(message)


def build_pod_body(name: str, region: str, instance_type: str,
                   interruptible: bool,
                   public_key: Optional[str]) -> Dict[str, Any]:
    """Catalog instance type → REST deploy body.

    '2x_A100-80GB_SECURE' → gpuTypeIds + gpuCount; '1x_CPU_SECURE' → a
    CPU pod (computeType, no gpuTypeIds — the API rejects a GPU request
    for type 'CPU'). Split out so the shape is unit-testable without the
    real endpoint (the fake ignores bodies).
    """
    count_s, rest = instance_type.split('x_', 1)
    device_type = rest.rsplit('_', 1)[0]
    body: Dict[str, Any] = {
        'name': name,
        'dataCenterIds': [region],
        'interruptible': interruptible,
        'containerDiskInGb': 50,
    }
    if device_type == 'CPU':
        body['computeType'] = 'CPU'
        body['vcpuCount'] = 4 * int(count_s)
        body['imageName'] = 'runpod/base:0.6.2'
    else:
        body['gpuTypeIds'] = [device_type]
        body['gpuCount'] = int(count_s)
        body['imageName'] = 'runpod/base:0.6.2-cuda12.2.0'
    if public_key:
        body['env'] = {'PUBLIC_KEY': public_key}
    return body


class RestTransport:
    """Real RunPod through curl + the REST API."""

    def __init__(self, api_key: str):
        self.api_key = api_key

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        def classify(o: dict) -> None:
            if o.get('error'):
                _raise_for_error(str(o['error']))

        return rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}',
            f'header = "Authorization: Bearer {self.api_key}"\n', body,
            api_error=RunPodApiError, classify=classify)

    def deploy_pod(self, name: str, region: str, instance_type: str,
                   interruptible: bool,
                   public_key: Optional[str]) -> str:
        out = self._run(
            'POST', '/pods',
            build_pod_body(name, region, instance_type, interruptible,
                           public_key))
        return out['id']

    def list_pods(self) -> List[Dict[str, Any]]:
        out = self._run('GET', '/pods')
        pods = out if isinstance(out, list) else out.get('pods', [])
        result = []
        for pod in pods:
            result.append({
                'id': pod['id'],
                'name': pod.get('name', ''),
                'instance_type': '',
                'region': pod.get('dataCenterId', ''),
                'status': pod.get('desiredStatus',
                                  pod.get('status', 'CREATED')),
                'ip': pod.get('publicIp'),
                'private_ip': pod.get('privateIp', ''),
                'interruptible': pod.get('interruptible', False),
            })
        return result

    def stop_pod(self, pod_id: str) -> None:
        self._run('POST', f'/pods/{pod_id}/stop')

    def start_pod(self, pod_id: str) -> None:
        self._run('POST', f'/pods/{pod_id}/start')

    def terminate_pod(self, pod_id: str) -> None:
        self._run('DELETE', f'/pods/{pod_id}')


class FakeRunPodService:
    """In-memory RunPod: instant transitions, per-datacenter stockout."""

    _lock = threading.Lock()
    _pods: Dict[str, Dict[str, Any]] = {}

    def __init__(self, api_key: str = 'fake'):
        self.api_key = api_key
        self._state_path = os.environ.get(_FAKE_STATE_ENV)

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding='utf-8') as f:
                return json.load(f)
        return FakeRunPodService._pods

    def _save(self, pods: Dict[str, Dict[str, Any]]) -> None:
        if self._state_path:
            with open(self._state_path, 'w', encoding='utf-8') as f:
                json.dump(pods, f)
        else:
            FakeRunPodService._pods = pods

    def deploy_pod(self, name: str, region: str, instance_type: str,
                   interruptible: bool,
                   public_key: Optional[str]) -> str:
        del public_key
        stockout = os.environ.get('SKYTPU_RUNPOD_FAKE_STOCKOUT',
                                  '').split(',')
        if region in stockout:
            _raise_for_error(
                f'There are no longer any instances available with the '
                f'requested specifications in {region}. (fake)')
        with FakeRunPodService._lock:
            pods = self._load()
            pid = f'pod-{uuid.uuid4().hex[:12]}'
            n = len(pods)
            pods[pid] = {
                'id': pid,
                'name': name,
                'instance_type': instance_type,
                'region': region,
                'status': 'RUNNING',
                'ip': f'194.26.0.{n + 10}',
                'private_ip': f'10.65.0.{n + 10}',
                'interruptible': interruptible,
            }
            self._save(pods)
            return pid

    def list_pods(self) -> List[Dict[str, Any]]:
        return [dict(p) for p in self._load().values()
                if p['status'] != 'TERMINATED']

    def _set_state(self, pod_id: str, status: str) -> None:
        with FakeRunPodService._lock:
            pods = self._load()
            if pod_id in pods:
                pods[pod_id]['status'] = status
            self._save(pods)

    def stop_pod(self, pod_id: str) -> None:
        self._set_state(pod_id, 'EXITED')

    def start_pod(self, pod_id: str) -> None:
        self._set_state(pod_id, 'RUNNING')

    def terminate_pod(self, pod_id: str) -> None:
        self._set_state(pod_id, 'TERMINATED')


def make_client(api_key: Optional[str] = None):
    if os.environ.get('SKYTPU_RUNPOD_FAKE', '0') == '1':
        return FakeRunPodService()
    if api_key is None:
        from skypilot_tpu.clouds.runpod import RunPod
        api_key = RunPod._api_key()  # pylint: disable=protected-access
    if api_key is None:
        raise RunPodApiError('No RunPod API key configured.')
    return RestTransport(api_key)
