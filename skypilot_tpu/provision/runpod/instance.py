"""RunPod pod lifecycle (parity: ``sky/provision/runpod/instance.py``).

Pods have no tags: cluster membership is encoded in the pod NAME
(``<cluster>-<i>``), like the Lambda path. Stop/resume map to pod
stop/start (billing pauses, disk persists); spot = interruptible pods.
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.runpod import runpod_api

logger = sky_logging.init_logger(__name__)

_STATE_MAP = {
    'CREATED': 'pending',
    'RUNNING': 'running',
    'RESTARTING': 'pending',
    'EXITED': 'stopped',
    'TERMINATED': 'terminated',
}


def _client(provider_config: Dict[str, Any]) -> Any:
    del provider_config
    return runpod_api.make_client()


def _node_index(pod: dict, cluster_name_on_cloud: str) -> int:
    suffix = pod['name'][len(cluster_name_on_cloud) + 1:]
    try:
        return int(suffix)
    except ValueError:
        return 0


def _cluster_pods(client, cluster_name_on_cloud: str) -> List[dict]:
    return [
        pod for pod in client.list_pods()
        if pod['name'].startswith(f'{cluster_name_on_cloud}-')
    ]


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = _client(config.provider_config)
    existing = _cluster_pods(client, cluster_name_on_cloud)
    by_index = {_node_index(p, cluster_name_on_cloud): p for p in existing}

    created: List[str] = []
    resumed: List[str] = []
    try:
        for i in range(config.count):
            pod = by_index.get(i)
            if pod is not None:
                if _STATE_MAP.get(pod['status']) == 'stopped':
                    if not config.resume_stopped_nodes:
                        raise common.ProvisionerError(
                            f'Pod {i} of {cluster_name_on_cloud} is '
                            'stopped and resume_stopped_nodes is False; '
                            'start the cluster instead.')
                    client.start_pod(pod['id'])
                    resumed.append(pod['id'])
                continue
            pid = client.deploy_pod(
                name=f'{cluster_name_on_cloud}-{i}',
                region=region,
                instance_type=config.node_config['instance_type'],
                interruptible=config.node_config.get('use_spot', False),
                public_key=config.authentication_config.get(
                    'ssh_public_key'))
            created.append(pid)
    except runpod_api.RunPodCapacityError:
        # Partial pods bill until terminated; failover may leave this
        # datacenter for good.
        for pid in created:
            client.terminate_pod(pid)
        raise
    head = by_index.get(0)
    head_id = head['id'] if head is not None else (
        created[0] if created else None)
    assert head_id is not None
    return common.ProvisionRecord(provider_name='runpod',
                                  region=region,
                                  zone=None,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=resumed,
                                  created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    import time
    assert provider_config is not None
    client = _client(provider_config)
    deadline = time.time() + 600
    while True:
        pods = _cluster_pods(client, cluster_name_on_cloud)
        states = [_STATE_MAP.get(p['status'], 'pending') for p in pods]
        if pods and all(s == state for s in states):
            return
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for {cluster_name_on_cloud} to reach '
                f'{state}; current: {states}')
        time.sleep(5)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    client = _client(provider_config)
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    pods = _cluster_pods(client, cluster_name_on_cloud)
    for pod in sorted(pods,
                      key=lambda p: _node_index(p, cluster_name_on_cloud)):
        if head_id is None:  # sorted: node 0 first
            head_id = pod['id']
        instances[pod['id']] = [
            common.InstanceInfo(
                instance_id=pod['id'],
                internal_ip=pod.get('private_ip', ''),
                external_ip=pod.get('ip'),
                tags={'name': pod['name']},
            )
        ]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name='runpod',
        provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'root'),
        ssh_private_key=provider_config.get('ssh_private_key'),
    )


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    assert provider_config is not None
    client = _client(provider_config)
    out: Dict[str, Optional[str]] = {}
    for pod in _cluster_pods(client, cluster_name_on_cloud):
        status = _STATE_MAP.get(pod['status'], 'pending')
        if non_terminated_only and status == 'terminated':
            continue
        out[pod['id']] = status
    return out


def _pod_ids(client, cluster_name_on_cloud: str,
             worker_only: bool) -> List[str]:
    return [
        pod['id']
        for pod in _cluster_pods(client, cluster_name_on_cloud)
        if not (worker_only and
                _node_index(pod, cluster_name_on_cloud) == 0)
    ]


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    for pid in _pod_ids(client, cluster_name_on_cloud, worker_only):
        client.stop_pod(pid)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    for pid in _pod_ids(client, cluster_name_on_cloud, worker_only):
        client.terminate_pod(pid)


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    # Gated off at the cloud level (OPEN_PORTS unsupported); pods only
    # expose the proxy/SSH endpoints configured at deploy time.
    logger.debug(f'open_ports({cluster_name_on_cloud}, {ports})')


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.debug(f'cleanup_ports({cluster_name_on_cloud}, {ports})')
