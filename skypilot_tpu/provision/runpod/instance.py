"""RunPod pod lifecycle (parity: ``sky/provision/runpod/instance.py``).

Pods have no tags: cluster membership is encoded in the pod NAME
(``<cluster>-<i>`` — strict integer suffix, see
``provision/neocloud_common.py``). Stop/resume map to pod stop/start
(billing pauses, disk persists); spot = interruptible pods.
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision import neocloud_common
from skypilot_tpu.provision.runpod import runpod_api

logger = sky_logging.init_logger(__name__)

_STATE_MAP = {
    'CREATED': 'pending',
    'RUNNING': 'running',
    'RESTARTING': 'pending',
    'EXITED': 'stopped',
    'TERMINATED': 'terminated',
}


def _client(provider_config: Dict[str, Any]) -> Any:
    del provider_config
    return runpod_api.make_client()


def _cluster_pods(client, cluster_name_on_cloud: str) -> List[dict]:
    return neocloud_common.cluster_members(client.list_pods(),
                                           cluster_name_on_cloud)


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = _client(config.provider_config)
    existing = _cluster_pods(client, cluster_name_on_cloud)
    by_index = neocloud_common.members_by_index(existing,
                                                cluster_name_on_cloud)

    created: List[str] = []
    resumed: List[str] = []
    try:
        for i in range(config.count):
            pod = by_index.get(i)
            if pod is not None:
                if _STATE_MAP.get(pod['status']) == 'stopped':
                    if not config.resume_stopped_nodes:
                        raise common.ProvisionerError(
                            f'Pod {i} of {cluster_name_on_cloud} is '
                            'stopped and resume_stopped_nodes is False; '
                            'start the cluster instead.')
                    client.start_pod(pod['id'])
                    resumed.append(pod['id'])
                continue
            pid = client.deploy_pod(
                name=f'{cluster_name_on_cloud}-{i}',
                region=region,
                instance_type=config.node_config['instance_type'],
                interruptible=config.node_config.get('use_spot', False),
                public_key=config.authentication_config.get(
                    'ssh_public_key'))
            created.append(pid)
    except runpod_api.RunPodCapacityError:
        # Partial pods bill until rolled back; failover may leave this
        # datacenter for good. Pods resumed THIS attempt go back to
        # stopped (their prior state) rather than billing unattended.
        # Best-effort per pod: one rollback failure must not abort the
        # rest, nor mask the capacity error the failover engine needs.
        for pid in created:
            try:
                client.terminate_pod(pid)
            except Exception as cleanup_exc:  # pylint: disable=broad-except
                logger.warning(f'Rollback terminate of {pid} failed: '
                               f'{cleanup_exc}')
        for pid in resumed:
            try:
                client.stop_pod(pid)
            except Exception as cleanup_exc:  # pylint: disable=broad-except
                logger.warning(f'Rollback stop of {pid} failed: '
                               f'{cleanup_exc}')
        raise
    head = by_index.get(0)
    head_id = head['id'] if head is not None else (
        created[0] if created else None)
    assert head_id is not None
    return common.ProvisionRecord(provider_name='runpod',
                                  region=region,
                                  zone=None,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=resumed,
                                  created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    neocloud_common.wait_for_state(
        lambda: _cluster_pods(client, cluster_name_on_cloud), _STATE_MAP,
        cluster_name_on_cloud, state)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    client = _client(provider_config)
    return neocloud_common.build_cluster_info(
        _cluster_pods(client, cluster_name_on_cloud), 'runpod',
        provider_config, default_ssh_user='root')


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    assert provider_config is not None
    client = _client(provider_config)
    return neocloud_common.query_statuses(
        _cluster_pods(client, cluster_name_on_cloud), _STATE_MAP,
        non_terminated_only)


def _pod_ids(client, cluster_name_on_cloud: str,
             worker_only: bool) -> List[str]:
    return [
        pod['id']
        for pod in _cluster_pods(client, cluster_name_on_cloud)
        if not (worker_only and neocloud_common.parse_node_index(
            pod['name'], cluster_name_on_cloud) == 0)
    ]


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    for pid in _pod_ids(client, cluster_name_on_cloud, worker_only):
        client.stop_pod(pid)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    for pid in _pod_ids(client, cluster_name_on_cloud, worker_only):
        client.terminate_pod(pid)


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    # Gated off at the cloud level (OPEN_PORTS unsupported); pods only
    # expose the proxy/SSH endpoints configured at deploy time.
    logger.debug(f'open_ports({cluster_name_on_cloud}, {ports})')


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.debug(f'cleanup_ports({cluster_name_on_cloud}, {ports})')
