"""RunPod pod provisioner (parity: ``sky/provision/runpod/``)."""
from skypilot_tpu.provision.runpod.instance import cleanup_ports
from skypilot_tpu.provision.runpod.instance import get_cluster_info
from skypilot_tpu.provision.runpod.instance import open_ports
from skypilot_tpu.provision.runpod.instance import query_instances
from skypilot_tpu.provision.runpod.instance import run_instances
from skypilot_tpu.provision.runpod.instance import stop_instances
from skypilot_tpu.provision.runpod.instance import terminate_instances
from skypilot_tpu.provision.runpod.instance import wait_instances
