"""Docker task containers on cluster hosts.

Parity: ``sky/provision/docker_utils.py`` (DockerInitializer) — redesigned
for TPU VMs: the container runs ``--privileged --net=host`` so libtpu sees
the chips and ``jax.distributed`` rendezvous ports are unchanged, and the
host home + /tmp are bind-mounted so the runtime state (``~/.skytpu``),
synced packages, and pushed task scripts are shared between host and
container. Commands are then wrapped with ``docker exec`` instead of the
reference's sshd-in-container approach — no second SSH daemon to manage.
"""
import shlex

from skypilot_tpu.utils import command_runner as command_runner_lib

# Also hardcoded in skylet/gang_run.py:_docker_wrap — gang_run ships to
# hosts as a self-contained module and cannot import this one.
CONTAINER_NAME = 'skytpu-container'


def bootstrap_command(image: str) -> str:
    """Idempotent per-host container bootstrap, run on the RAW host.

    One container per host (each cloud host is one VM). A leftover
    container from an earlier launch is reused only if it runs the SAME
    image; otherwise it is replaced — a stale-image container must never
    serve a new launch.
    """
    img = shlex.quote(image)
    run_flags = (f'-d --name {CONTAINER_NAME} --privileged --net=host '
                 '-v "$HOME":"$HOME" -v /tmp:/tmp -e HOME="$HOME" '
                 '-w "$HOME"')
    return (
        f'cur="$(docker inspect -f "{{{{.Config.Image}}}}" {CONTAINER_NAME} '
        '2>/dev/null)"; '
        f'if [ "$cur" = {img} ]; then '
        f'docker start {CONTAINER_NAME} >/dev/null 2>&1 || true; '
        f'else docker rm -f {CONTAINER_NAME} >/dev/null 2>&1 || true; '
        f'docker run {run_flags} {img} sleep infinity; fi')


class DockerRunner(command_runner_lib.CommandRunner):
    """Wraps a host CommandRunner so run() executes inside the task
    container; rsync stays on the host (home/tmp are bind-mounted)."""

    def __init__(self, inner: command_runner_lib.CommandRunner):
        super().__init__(inner.node_id)
        self.inner = inner

    def run(self, cmd, *, require_outputs=False, log_path='/dev/null',
            stream_logs=False, env_vars=None, timeout=None, **kwargs):
        full = self._make_cmd(cmd, env_vars)
        wrapped = (f'docker exec {CONTAINER_NAME} /bin/bash -c '
                   f'{shlex.quote(full)}')
        return self.inner.run(wrapped,
                              require_outputs=require_outputs,
                              log_path=log_path,
                              stream_logs=stream_logs,
                              timeout=timeout,
                              **kwargs)

    def rsync(self, source, target, *, up: bool, log_path='/dev/null'):
        return self.inner.rsync(source, target, up=up, log_path=log_path)
