"""AWS EC2 provisioner (parity: ``sky/provision/aws/``) — aws-CLI based,
with an in-memory fake for credential-free tests."""
from skypilot_tpu.provision.aws.instance import cleanup_ports
from skypilot_tpu.provision.aws.instance import get_cluster_info
from skypilot_tpu.provision.aws.instance import open_ports
from skypilot_tpu.provision.aws.instance import query_instances
from skypilot_tpu.provision.aws.instance import run_instances
from skypilot_tpu.provision.aws.instance import stop_instances
from skypilot_tpu.provision.aws.instance import terminate_instances
from skypilot_tpu.provision.aws.instance import wait_instances

__all__ = [
    'cleanup_ports', 'get_cluster_info', 'open_ports', 'query_instances',
    'run_instances', 'stop_instances', 'terminate_instances',
    'wait_instances'
]
