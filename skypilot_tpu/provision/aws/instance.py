"""AWS EC2 instance lifecycle (parity: ``sky/provision/aws/instance.py``).

A "cluster" of N nodes = N EC2 instances tagged
``skytpu-cluster=<name>`` + ``skytpu-node=<i>``; one InstanceInfo per
instance (GPU hosts are single-host nodes — the multi-host fan-out is a
TPU-slice concept).
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.aws import ec2_api

logger = sky_logging.init_logger(__name__)

_CLUSTER_TAG = 'skytpu-cluster'
_NODE_TAG = 'skytpu-node'

_STATE_MAP = {
    'pending': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'shutting-down': 'terminating',
    'terminated': 'terminated',
}


def _client(provider_config: Dict[str, Any]) -> Any:
    return ec2_api.make_client(provider_config['region'])


def _cluster_filter(cluster_name_on_cloud: str,
                    non_terminated: bool = True) -> List[dict]:
    filters = [{'Name': f'tag:{_CLUSTER_TAG}',
                'Values': [cluster_name_on_cloud]}]
    if non_terminated:
        filters.append({
            'Name': 'instance-state-name',
            'Values': ['pending', 'running', 'stopping', 'stopped'],
        })
    return filters


def _node_index(inst: dict) -> int:
    tags = {t['Key']: t['Value'] for t in inst.get('Tags', [])}
    return int(tags.get(_NODE_TAG, 0))


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = _client(config.provider_config)
    zone = config.provider_config.get('availability_zone')
    existing = client.describe_instances(
        _cluster_filter(cluster_name_on_cloud))
    by_index = {_node_index(i): i for i in existing}

    # A cluster must live in ONE zone: adopting leftovers from another
    # zone would silently span AZs while the record claims `zone`.
    for inst in existing:
        inst_zone = inst.get('Placement', {}).get('AvailabilityZone')
        if zone and inst_zone and inst_zone != zone:
            raise common.ProvisionerError(
                f'Cluster {cluster_name_on_cloud} has instances in '
                f'{inst_zone} but {zone} was requested; run `down` first.')

    created: List[str] = []
    resumed: List[str] = []
    head_id: Optional[str] = None
    try:
        _create_nodes(client, zone, cluster_name_on_cloud, config,
                      by_index, created, resumed)
    except ec2_api.AwsCapacityError:
        # Failover moves on (possibly to another region whose client can
        # never see these): partially-created nodes would bill forever.
        if created:
            client.terminate_instances(created)
        raise
    for i in range(config.count):
        inst = by_index.get(i)
        if inst is not None and i == 0:
            head_id = inst['InstanceId']
    head_id = head_id or (created[0] if created else None)
    assert head_id is not None
    return common.ProvisionRecord(provider_name='aws',
                                  region=region,
                                  zone=zone,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=resumed,
                                  created_instance_ids=created)


def _create_nodes(client, zone, cluster_name_on_cloud, config, by_index,
                  created, resumed) -> None:
    for i in range(config.count):
        inst = by_index.get(i)
        if inst is not None:
            state = inst['State']['Name']
            if state == 'stopped':
                if not config.resume_stopped_nodes:
                    raise common.ProvisionerError(
                        f'Node {i} of {cluster_name_on_cloud} is stopped '
                        'and resume_stopped_nodes is False; start the '
                        'cluster instead.')
                client.start_instances([inst['InstanceId']])
                resumed.append(inst['InstanceId'])
            if i == 0:
                head_id = inst['InstanceId']
            continue
        node_cfg = {
            'instance_type': config.node_config['instance_type'],
            'image_id': config.node_config.get('image_id'),
            'use_spot': config.node_config.get('use_spot', False),
            'key_name': config.authentication_config.get('key_name'),
            'security_group_ids':
                config.node_config.get('security_group_ids'),
            'subnet_id': config.node_config.get('subnet_id'),
            'tags': {
                _CLUSTER_TAG: cluster_name_on_cloud,
                _NODE_TAG: str(i),
                'Name': f'{cluster_name_on_cloud}-{i}',
            },
        }
        insts = client.run_instances(zone, 1, node_cfg)
        created.append(insts[0]['InstanceId'])


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    import time
    assert provider_config is not None
    client = _client(provider_config)
    deadline = time.time() + 600
    while True:
        insts = client.describe_instances(
            _cluster_filter(cluster_name_on_cloud))
        states = [_STATE_MAP.get(i['State']['Name'], 'pending')
                  for i in insts]
        if insts and all(s == state for s in states):
            return
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for {cluster_name_on_cloud} to reach '
                f'{state}; current: {states}')
        time.sleep(5)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    client = _client(provider_config)
    insts = client.describe_instances(
        _cluster_filter(cluster_name_on_cloud))
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    for inst in sorted(insts, key=_node_index):
        iid = inst['InstanceId']
        if head_id is None:  # sorted: node 0 first
            head_id = iid
        instances[iid] = [
            common.InstanceInfo(
                instance_id=iid,
                internal_ip=inst.get('PrivateIpAddress', ''),
                external_ip=inst.get('PublicIpAddress'),
                tags={t['Key']: t['Value'] for t in inst.get('Tags', [])},
            )
        ]
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name='aws',
        provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'ubuntu'),
        ssh_private_key=provider_config.get('ssh_private_key'),
    )


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    assert provider_config is not None
    client = _client(provider_config)
    out: Dict[str, Optional[str]] = {}
    for inst in client.describe_instances(
            _cluster_filter(cluster_name_on_cloud,
                            non_terminated=non_terminated_only)):
        status = _STATE_MAP.get(inst['State']['Name'], 'pending')
        if non_terminated_only and status == 'terminated':
            continue
        out[inst['InstanceId']] = status
    return out


def _ids(client, cluster_name_on_cloud: str,
         worker_only: bool) -> List[str]:
    out = []
    for inst in client.describe_instances(
            _cluster_filter(cluster_name_on_cloud)):
        if worker_only and _node_index(inst) == 0:
            continue
        out.append(inst['InstanceId'])
    return out


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    client.stop_instances(_ids(client, cluster_name_on_cloud, worker_only))


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    client.terminate_instances(
        _ids(client, cluster_name_on_cloud, worker_only))


def _port_permissions(ports: List[str]) -> List[dict]:
    perms = []
    for p in sorted({str(p) for p in ports}):
        if '-' in p:
            lo, hi = p.split('-', 1)
            if int(hi) < int(lo):
                raise common.ProvisionerError(
                    f'Invalid port range {p!r}: end < start.')
        else:
            lo = hi = p
        perms.append({'IpProtocol': 'tcp',
                      'FromPort': int(lo),
                      'ToPort': int(hi),
                      'IpRanges': [{'CidrIp': '0.0.0.0/0'}]})
    return perms


def _configured_security_groups() -> Optional[List[str]]:
    from skypilot_tpu import skypilot_config
    return skypilot_config.get_nested(('aws', 'security_group_ids'),
                                      None)


def _cluster_security_groups(client, cluster_name_on_cloud: str
                             ) -> List[str]:
    group_ids = []
    for inst in client.describe_instances(
            _cluster_filter(cluster_name_on_cloud)):
        for sg in inst.get('SecurityGroups', []):
            gid = sg.get('GroupId')
            if gid and gid not in group_ids:
                group_ids.append(gid)
    return group_ids


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    """Authorize the task's `ports:` on the cluster's security groups
    (parity: the reference's SG ingress for `ports:`).

    Per-permission calls: a relaunch that ADDS a port must not lose the
    new rule to a batched Duplicate rejection of an old one.
    """
    if not ports:
        return
    assert provider_config is not None
    client = _client(provider_config)
    group_ids = _configured_security_groups() or \
        _cluster_security_groups(client, cluster_name_on_cloud)
    if not group_ids:
        logger.warning(
            f'open_ports({cluster_name_on_cloud}): no security groups '
            'found (no instances?) — nothing authorized.')
        return
    for gid in group_ids:
        for perm in _port_permissions(ports):
            try:
                client.authorize_ingress(gid, [perm])
            except ec2_api.Ec2ApiError as exc:
                # Idempotent relaunch: this rule already exists.
                if 'InvalidPermission.Duplicate' not in str(exc):
                    raise
    logger.info(f'Opened ports {ports} for {cluster_name_on_cloud} '
                f'(security-group ingress on {group_ids}).')


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    """Revoke the exact rules open_ports added — but ONLY on
    explicitly-configured security groups (``aws.security_group_ids``).

    On the implicit shared default-VPC SG, rules are left in place:
    another live cluster on the same SG may have opened (or be
    borrowing) the same port, and EC2 rules carry no per-cluster
    ownership, so revoking there can silently cut a neighbor's traffic.
    Configured SGs also need no instance discovery, so cleanup works
    even after the instances are already gone (spot reclaim, partial
    teardown).
    """
    if not ports:
        return
    assert provider_config is not None
    group_ids = _configured_security_groups()
    if not group_ids:
        logger.warning(
            f'cleanup_ports({cluster_name_on_cloud}): ports {ports} '
            'were opened on the shared default security group; leaving '
            'the rules (another cluster may rely on them). Configure '
            'aws.security_group_ids for revocable per-deployment '
            'groups.')
        return
    client = _client(provider_config)
    for gid in group_ids:
        for perm in _port_permissions(ports):
            try:
                client.revoke_ingress(gid, [perm])
            except ec2_api.Ec2ApiError as exc:
                logger.debug(f'revoke ingress on {gid}: {exc}')
