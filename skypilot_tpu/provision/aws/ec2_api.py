"""EC2 API client with a fake backend.

Parity: the reference drives boto3 from ``sky/provision/aws/instance.py``;
this build shells out to the ``aws`` CLI (``--output json``) — boto3 is not
a baked-in dependency and the CLI is what the storage layer already uses —
with the same two-transport shape as ``provision/gcp/tpu_api.py``:

* :class:`CliTransport` — real EC2 via ``aws ec2 ... --output json``.
* :class:`FakeEc2Service` — in-memory instances, used by tests and when
  ``SKYTPU_AWS_FAKE=1``. Fault injection:
  ``SKYTPU_AWS_FAKE_STOCKOUT='us-east-1a,...'`` makes RunInstances in
  those zones raise ``InsufficientInstanceCapacity`` — exercising the
  failover engine.
"""
import json
import os
import subprocess
import threading
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_FAKE_STATE_ENV = 'SKYTPU_AWS_FAKE_STATE'


class Ec2ApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class AwsCapacityError(Ec2ApiError, provision_common.CapacityError):
    """Capacity exhaustion. ``scope`` tells the failover engine how much
    to blocklist: 'zone' for a zonal stockout, 'region' for account/region
    quota limits (retrying sister zones cannot help)."""

    def __init__(self, message: str, scope: str = 'zone'):
        super().__init__(message)
        self.scope = scope


# Exact AWS error codes only: a bare 'capacity' substring would also match
# e.g. InvalidCapacityReservationId config errors and burn the candidate
# list (see FailoverCloudErrorHandler.classify).
_CAPACITY_SCOPES = {
    'insufficientinstancecapacity': 'zone',
    'instancelimitexceeded': 'region',
    'vcpulimitexceeded': 'region',
    'maxspotinstancecountexceeded': 'region',
}


def _capacity_scope(message: str):
    lowered = message.lower()
    for marker, scope in _CAPACITY_SCOPES.items():
        if marker in lowered:
            return scope
    return None


class CliTransport:
    """Real EC2 through the aws CLI."""

    def __init__(self, region: str):
        self.region = region

    def _run(self, args: List[str]) -> dict:
        proc = subprocess.run(
            ['aws', 'ec2', '--region', self.region, '--output', 'json'] +
            args,
            capture_output=True,
            text=True,
            timeout=300,
            check=False)
        if proc.returncode != 0:
            msg = proc.stderr.strip()
            scope = _capacity_scope(msg)
            if scope is not None:
                raise AwsCapacityError(msg, scope=scope)
            raise Ec2ApiError(f'aws ec2 {args[0]}: {msg}')
        return json.loads(proc.stdout) if proc.stdout.strip() else {}

    def run_instances(self, zone: Optional[str], count: int,
                      config: Dict[str, Any]) -> List[dict]:
        args = [
            'run-instances',
            '--count', str(count),
            '--instance-type', config['instance_type'],
            '--image-id', config.get('image_id') or
            'resolve:ssm:/aws/service/canonical/ubuntu/server/22.04/'
            'stable/current/amd64/hvm/ebs-gp2/ami-id',
            '--tag-specifications',
            json.dumps([{
                'ResourceType': 'instance',
                'Tags': [{'Key': k, 'Value': v}
                         for k, v in config.get('tags', {}).items()],
            }]),
        ]
        if config.get('key_name'):
            args += ['--key-name', config['key_name']]
        # Without an explicit security group the default-VPC default SG
        # blocks inbound SSH and the launch dies as a wait_for_ssh
        # timeout; users set aws.security_group_ids / aws.subnet_id in
        # ~/.skytpu/config.yaml.
        if config.get('security_group_ids'):
            args += ['--security-group-ids'] + list(
                config['security_group_ids'])
        if config.get('subnet_id'):
            args += ['--subnet-id', config['subnet_id']]
        if zone:
            args += ['--placement', json.dumps({'AvailabilityZone': zone})]
        if config.get('use_spot'):
            args += ['--instance-market-options',
                     json.dumps({'MarketType': 'spot'})]
        return self._run(args).get('Instances', [])

    def describe_instances(self,
                           filters: List[dict]) -> List[dict]:
        out = self._run(['describe-instances', '--filters',
                         json.dumps(filters)])
        instances = []
        for resv in out.get('Reservations', []):
            instances.extend(resv.get('Instances', []))
        return instances

    def stop_instances(self, ids: List[str]) -> None:
        if ids:
            self._run(['stop-instances', '--instance-ids'] + ids)

    def start_instances(self, ids: List[str]) -> None:
        if ids:
            self._run(['start-instances', '--instance-ids'] + ids)

    def terminate_instances(self, ids: List[str]) -> None:
        if ids:
            self._run(['terminate-instances', '--instance-ids'] + ids)

    # Security-group ingress (`ports:` exposure — parity: the
    # reference authorizes task ports on the cluster's SG).

    def authorize_ingress(self, group_id: str,
                          permissions: List[dict]) -> None:
        # Raw API fidelity (Duplicate errors propagate); idempotency is
        # the caller's policy (instance.open_ports).
        self._run(['authorize-security-group-ingress', '--group-id',
                   group_id, '--ip-permissions',
                   json.dumps(permissions)])

    def revoke_ingress(self, group_id: str,
                       permissions: List[dict]) -> None:
        self._run(['revoke-security-group-ingress', '--group-id',
                   group_id, '--ip-permissions',
                   json.dumps(permissions)])


class FakeEc2Service:
    """In-memory EC2: instant state transitions, per-region instances.

    State optionally persisted to a JSON file (``SKYTPU_AWS_FAKE_STATE``)
    so separate processes see the same cloud.
    """

    _lock = threading.Lock()
    _instances: Dict[str, Dict[str, Any]] = {}

    def __init__(self, region: str):
        self.region = region
        self._state_path = os.environ.get(_FAKE_STATE_ENV)

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding='utf-8') as f:
                return json.load(f)
        return FakeEc2Service._instances

    def _save(self, instances: Dict[str, Dict[str, Any]]) -> None:
        if self._state_path:
            with open(self._state_path, 'w', encoding='utf-8') as f:
                json.dump(instances, f)
        else:
            FakeEc2Service._instances = instances

    def run_instances(self, zone: Optional[str], count: int,
                      config: Dict[str, Any]) -> List[dict]:
        stockout = os.environ.get('SKYTPU_AWS_FAKE_STOCKOUT', '').split(',')
        if zone and zone in stockout:
            raise AwsCapacityError(
                f'An error occurred (InsufficientInstanceCapacity): '
                f'We currently do not have sufficient capacity in the '
                f'Availability Zone you requested ({zone}).')
        with FakeEc2Service._lock:
            instances = self._load()
            created = []
            for _ in range(count):
                iid = f'i-{uuid.uuid4().hex[:17]}'
                n = len(instances)
                inst = {
                    'InstanceId': iid,
                    'InstanceType': config['instance_type'],
                    'State': {'Name': 'running'},
                    'Placement': {'AvailabilityZone': zone or
                                  f'{self.region}a'},
                    'PrivateIpAddress': f'172.31.0.{n + 10}',
                    'PublicIpAddress': f'54.0.0.{n + 10}',
                    'SecurityGroups': [{'GroupId': 'sg-fake0001',
                                        'GroupName': 'default'}],
                    'Tags': [{'Key': k, 'Value': v}
                             for k, v in config.get('tags', {}).items()],
                    'Region': self.region,
                }
                instances[iid] = inst
                created.append(inst)
            self._save(instances)
            return created

    def describe_instances(self, filters: List[dict]) -> List[dict]:
        instances = self._load()
        out = []
        for inst in instances.values():
            if inst.get('Region') != self.region:
                continue
            ok = True
            for f in filters:
                name, values = f['Name'], f['Values']
                if name.startswith('tag:'):
                    key = name[4:]
                    tags = {t['Key']: t['Value']
                            for t in inst.get('Tags', [])}
                    ok = ok and tags.get(key) in values
                elif name == 'instance-state-name':
                    ok = ok and inst['State']['Name'] in values
            if ok:
                out.append(inst)
        return out

    def _set_state(self, ids: List[str], state: str) -> None:
        with FakeEc2Service._lock:
            instances = self._load()
            for iid in ids:
                if iid in instances:
                    instances[iid]['State']['Name'] = state
            self._save(instances)

    def stop_instances(self, ids: List[str]) -> None:
        self._set_state(ids, 'stopped')

    def start_instances(self, ids: List[str]) -> None:
        self._set_state(ids, 'running')

    def terminate_instances(self, ids: List[str]) -> None:
        self._set_state(ids, 'terminated')

    # Security-group rules live under 'sg:{region}/{gid}' keys (':'
    # keeps them disjoint from 'i-...' instance ids).

    def _sg_key(self, group_id: str) -> str:
        return f'sg:{self.region}/{group_id}'

    def authorize_ingress(self, group_id: str,
                          permissions: List[dict]) -> None:
        with FakeEc2Service._lock:
            instances = self._load()
            rules = instances.setdefault(self._sg_key(group_id),
                                         {'rules': []})['rules']
            # Validate-then-apply (the real API rejects the whole call).
            for perm in permissions:
                if perm in rules:
                    # Real-API fidelity: duplicates error (callers
                    # swallow it for idempotent relaunches).
                    raise Ec2ApiError(
                        'An error occurred '
                        '(InvalidPermission.Duplicate): the specified '
                        'rule already exists')
            rules.extend(permissions)
            self._save(instances)

    def revoke_ingress(self, group_id: str,
                       permissions: List[dict]) -> None:
        with FakeEc2Service._lock:
            instances = self._load()
            entry = instances.get(self._sg_key(group_id))
            rules = entry['rules'] if entry else []
            # Validate-then-apply (real-API atomicity — in-memory mode
            # shares the live dict, a mid-loop raise must not leave a
            # half-applied revoke).
            for perm in permissions:
                if perm not in rules:
                    raise Ec2ApiError(
                        'An error occurred '
                        '(InvalidPermission.NotFound): rule not found')
            for perm in permissions:
                rules.remove(perm)
            self._save(instances)

    def ingress_rules(self, group_id: str) -> List[dict]:
        entry = self._load().get(self._sg_key(group_id))
        return list(entry['rules']) if entry else []


def make_client(region: str):
    if os.environ.get('SKYTPU_AWS_FAKE', '0') == '1':
        return FakeEc2Service(region)
    return CliTransport(region)
