"""GCP TPU slice lifecycle (parity: ``sky/provision/gcp/instance.py`` +

``GCPTPUVMInstance`` in ``instance_utils.py:1191``).

A "cluster" of N logical nodes = N TPU slice nodes named
``{cluster}-{i}``; each slice contributes ``num_hosts`` SSH targets.
Single-slice clusters (num_nodes=1, the common case) are one TPU node.
"""
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu import skypilot_config
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import tpu_api

logger = sky_logging.init_logger(__name__)

_CLUSTER_LABEL = 'skytpu-cluster'

# GCP TPU node states → framework status strings.
_STATE_MAP = {
    'CREATING': 'pending',
    'STARTING': 'pending',
    'REPAIRING': 'pending',
    'READY': 'running',
    'RESTARTING': 'pending',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'terminating',
    'PREEMPTED': 'terminated',
    'TERMINATED': 'terminated',
    'HIDING': 'terminating',
    'HIDDEN': 'terminated',
    'UNHIDING': 'pending',
}


def _project_id(provider_config: Dict[str, Any]) -> str:
    project = provider_config.get('project_id') or skypilot_config.get_nested(
        ('gcp', 'project_id'), None) or os.environ.get('GOOGLE_CLOUD_PROJECT')
    if not project:
        raise common.ProvisionerError(
            'No GCP project configured. Set gcp.project_id in '
            '~/.skytpu/config.yaml or $GOOGLE_CLOUD_PROJECT.')
    return project


def _client(provider_config: Dict[str, Any]) -> tpu_api.TpuClient:
    return tpu_api.TpuClient(_project_id(provider_config))


def _node_name(cluster_name_on_cloud: str, index: int) -> str:
    return f'{cluster_name_on_cloud}-{index}'


def _cluster_nodes(client: tpu_api.TpuClient, zone: str,
                   cluster_name_on_cloud: str) -> List[dict]:
    nodes = client.list_nodes(zone)
    return [
        n for n in nodes
        if n.get('labels', {}).get(_CLUSTER_LABEL) == cluster_name_on_cloud
    ]


def _is_tpu_config(node_cfg: Dict[str, Any]) -> bool:
    return bool(node_cfg.get('accelerator_type'))


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    """Create (or resume) the cluster's nodes.

    Two backends behind one surface (parity: the reference's handler
    dispatch, instance_utils.py:141 GCPComputeInstance vs :1191
    GCPTPUVMInstance): TPU slice nodes via tpu.googleapis.com, GPU/CPU
    VMs via compute.googleapis.com.
    """
    if not _is_tpu_config(config.node_config):
        return _run_gce_instances(region, cluster_name_on_cloud, config)
    zone = config.provider_config['availability_zone']
    client = _client(config.provider_config)
    node_cfg = config.node_config

    existing = _cluster_nodes(client, zone, cluster_name_on_cloud)
    existing_by_name = {n['name'].split('/')[-1]: n for n in existing}

    created: List[str] = []
    resumed: List[str] = []
    to_create: List[Dict[str, Any]] = []
    head_id: Optional[str] = None
    for i in range(config.count):
        name = _node_name(cluster_name_on_cloud, i)
        if i == 0:
            head_id = name
        node = existing_by_name.get(name)
        if node is not None:
            state = node.get('state')
            if state == 'READY':
                continue
            if state == 'STOPPED' and config.resume_stopped_nodes:
                client.start_node(zone, name)
                resumed.append(name)
                continue
            if state in ('PREEMPTED', 'TERMINATED'):
                client.delete_node(zone, name)
            else:
                continue  # pending states: wait_instances handles it
        body: Dict[str, Any] = {
            'acceleratorType': node_cfg['accelerator_type'],
            'runtimeVersion': node_cfg['runtime_version'],
            'networkConfig': {'enableExternalIps': True},
            # Network tag: open_ports firewall rules target it.
            'tags': [_network_tag(cluster_name_on_cloud)],
            'labels': {_CLUSTER_LABEL: cluster_name_on_cloud,
                       **node_cfg.get('labels', {})},
            'metadata': {
                'ssh-keys': config.authentication_config.get('ssh_keys', ''),
            },
        }
        if node_cfg.get('topology'):
            # Explicit AcceleratorConfig pins the exact torus shape.
            body['acceleratorConfig'] = {
                'type': _accel_config_type(node_cfg['accelerator_type']),
                'topology': node_cfg['topology'],
            }
        if config.node_config.get('use_spot'):
            body['schedulingConfig'] = {'preemptible': True}
        reservation = skypilot_config.get_nested(
            ('gcp', 'specific_reservations'), None)
        if reservation:
            body['schedulingConfig'] = {
                **body.get('schedulingConfig', {}), 'reserved': True
            }
        if node_cfg.get('use_queued_resources'):
            # The capacity tier (spot/guaranteed) is expressed at the
            # QR level, not per node — a node-level schedulingConfig
            # alongside it is contradictory and 400s on the real API.
            qr_body = {k: v for k, v in body.items()
                       if k != 'schedulingConfig'}
            to_create.append({'node_id': name, 'node': qr_body})
        else:
            logger.debug(f'Creating TPU node {name} in {zone}: '
                         f'{node_cfg["accelerator_type"]}')
            client.create_node(zone, name, body)
        created.append(name)

    if to_create:
        # Queued-resources path: how real v5p capacity is obtained when
        # immediate create stocks out. ONE QR carries every missing node
        # (all-or-nothing gang grant, one wait); the id is unique per
        # attempt so a retry after preemption can never 409 against a
        # stale record — teardown sweeps all of the cluster's QRs by
        # prefix. Denied / timed-out requests classify as
        # GcpCapacityError so the failover engine blocklists the zone
        # (parity intent: sky/provision/gcp/mig_utils.py DWS +
        # instance_utils.py:311).
        import uuid as uuid_lib
        reservation = skypilot_config.get_nested(
            ('gcp', 'specific_reservations'), None)
        timeout = float(node_cfg.get('provision_timeout', 900))
        qr_id = (f'{_qr_prefix(cluster_name_on_cloud)}'
                 f'{uuid_lib.uuid4().hex[:8]}')
        logger.debug(
            f'Requesting queued resource {qr_id} in {zone}: '
            f'{len(to_create)}× {node_cfg["accelerator_type"]} '
            f'(timeout {int(timeout)}s)')
        client.create_queued_resource(
            zone, qr_id, to_create,
            valid_until_s=timeout,
            spot=bool(config.node_config.get('use_spot')),
            reserved=bool(reservation))
        client.wait_queued_resource(zone, qr_id, timeout=timeout)

    assert head_id is not None
    return common.ProvisionRecord(provider_name='gcp',
                                  region=region,
                                  zone=zone,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=resumed,
                                  created_instance_ids=created)


def _qr_prefix(cluster_name_on_cloud: str) -> str:
    return f'{cluster_name_on_cloud}-qr-'


# ------------------------------------------------------------------- GCE


def _gce_client(provider_config: Dict[str, Any]):
    from skypilot_tpu.provision.gcp import gce_api
    return gce_api.GceClient(_project_id(provider_config))


_GCE_IMAGE = ('projects/ubuntu-os-cloud/global/images/family/'
              'ubuntu-2204-lts')


def _run_gce_instances(region: str, cluster_name_on_cloud: str,
                       config: common.ProvisionConfig
                       ) -> common.ProvisionRecord:
    """GPU/CPU VMs via the GCE instances API (parity:
    GCPComputeInstance, instance_utils.py:141)."""
    from skypilot_tpu.provision.gcp import gce_api
    zone = config.provider_config['availability_zone']
    client = _gce_client(config.provider_config)
    node_cfg = config.node_config
    machine_type = node_cfg['instance_type']

    existing = {i['name']: i for i in client.list_instances(
        zone, label=(_CLUSTER_LABEL, cluster_name_on_cloud))}
    created: List[str] = []
    resumed: List[str] = []
    head_id: Optional[str] = None
    for i in range(config.count):
        name = _node_name(cluster_name_on_cloud, i)
        if i == 0:
            head_id = name
        inst = existing.get(name)
        if inst is not None:
            status = gce_api.STATE_MAP.get(inst.get('status'), 'pending')
            if status == 'running':
                continue
            if status == 'stopped':
                if not config.resume_stopped_nodes:
                    # Fail NOW: wait_instances can't transition a
                    # stopped VM, it would just time out for 30 min.
                    raise common.ProvisionerError(
                        f'Instance {name} is stopped and '
                        'resume_stopped_nodes is False; start the '
                        'cluster instead.')
                client.start(zone, name)
                resumed.append(name)
                continue
            continue  # pending/stopping: wait_instances handles it
        body: Dict[str, Any] = {
            'name': name,
            'machineType': f'zones/{zone}/machineTypes/{machine_type}',
            'disks': [{
                'boot': True,
                'autoDelete': True,
                'initializeParams': {
                    'sourceImage': node_cfg.get('image_id') or _GCE_IMAGE,
                    'diskSizeGb': str(node_cfg.get('disk_size', 256)),
                },
            }],
            'networkInterfaces': [{
                'network': 'global/networks/default',
                'accessConfigs': [{'type': 'ONE_TO_ONE_NAT',
                                   'name': 'External NAT'}],
            }],
            'labels': {_CLUSTER_LABEL: cluster_name_on_cloud,
                       **node_cfg.get('labels', {})},
            # Network tag: open_ports firewall rules target it.
            'tags': {'items': [_network_tag(cluster_name_on_cloud)]},
            'metadata': {'items': [{
                'key': 'ssh-keys',
                'value': config.authentication_config.get('ssh_keys', ''),
            }]},
        }
        # n1-family GPUs attach as guestAccelerators (a2/a3/g2 embed
        # theirs in the machine type) and require host-maintenance
        # TERMINATE.
        gpu = node_cfg.get('gpu')
        if gpu and gpu in gce_api.GUEST_ACCELERATORS:
            body['guestAccelerators'] = [{
                'acceleratorType':
                    f'zones/{zone}/acceleratorTypes/'
                    f'{gce_api.GUEST_ACCELERATORS[gpu]}',
                'acceleratorCount': int(node_cfg.get('gpu_count', 1)),
            }]
        scheduling: Dict[str, Any] = {}
        if gpu:
            scheduling['onHostMaintenance'] = 'TERMINATE'
        if node_cfg.get('use_spot'):
            scheduling.update({'provisioningModel': 'SPOT',
                               'preemptible': True,
                               'automaticRestart': False})
        if scheduling:
            body['scheduling'] = scheduling
        logger.debug(f'Creating GCE instance {name} in {zone}: '
                     f'{machine_type}')
        client.insert(zone, body)
        created.append(name)

    assert head_id is not None
    return common.ProvisionRecord(provider_name='gcp',
                                  region=region,
                                  zone=zone,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=resumed,
                                  created_instance_ids=created)


def _gce_cluster_instances(provider_config: Dict[str, Any],
                           cluster_name_on_cloud: str,
                           best_effort: bool = False,
                           client=None) -> List[dict]:
    """GCE instances labeled with the cluster.

    ``best_effort=True`` for paths that must keep working on TPU-only
    projects WITHOUT the Compute Engine API enabled (status polls,
    teardown): an API error there reads as 'no GCE instances', it must
    not abort TPU teardown or mask the queued-resource sweep.
    """
    zone = provider_config['availability_zone']
    client = client or _gce_client(provider_config)
    try:
        return client.list_instances(
            zone, label=(_CLUSTER_LABEL, cluster_name_on_cloud))
    except tpu_api.TpuApiError as exc:
        if best_effort:
            logger.debug(f'GCE list for {cluster_name_on_cloud}: {exc}')
            return []
        raise


def _accel_config_type(accelerator_type: str) -> str:
    gen = accelerator_type.split('-')[0].upper()  # v5p → V5P
    return {'V2': 'V2', 'V3': 'V3', 'V4': 'V4', 'V5E': 'V5LITE_POD',
            'V5P': 'V5P', 'V6E': 'V6E'}.get(gen, gen)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    """Block until every node (TPU slice or GCE VM) reaches `state`."""
    import time

    from skypilot_tpu.provision.gcp import gce_api
    assert provider_config is not None
    zone = provider_config['availability_zone']
    client = _client(provider_config)
    gce_client = _gce_client(provider_config)  # hoisted: token cache
    deadline = time.time() + 1800
    while True:
        nodes = _cluster_nodes(client, zone, cluster_name_on_cloud)
        statuses = [_STATE_MAP.get(n.get('state'), 'pending')
                    for n in nodes]
        if not nodes:
            statuses = [
                gce_api.STATE_MAP.get(i.get('status'), 'pending')
                for i in _gce_cluster_instances(provider_config,
                                                cluster_name_on_cloud,
                                                best_effort=True,
                                                client=gce_client)
            ]
        if statuses and all(s == state for s in statuses):
            return
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for {cluster_name_on_cloud} to reach '
                f'{state}; current: {statuses}')
        time.sleep(5)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    zone = provider_config['availability_zone']
    client = _client(provider_config)
    nodes = _cluster_nodes(client, zone, cluster_name_on_cloud)
    if not nodes:
        gce = _gce_cluster_instances(provider_config,
                                     cluster_name_on_cloud)
        if gce:
            return _gce_cluster_info(gce, cluster_name_on_cloud,
                                     provider_config)
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    custom = {}
    for slice_idx, node in enumerate(sorted(nodes,
                                            key=lambda n: n['name'])):
        name = node['name'].split('/')[-1]
        if head_id is None:
            head_id = name
            custom = {
                'accelerator_type': node.get('acceleratorType'),
                'runtime_version': node.get('runtimeVersion'),
                'topology': node.get('acceleratorConfig', {}).get('topology'),
            }
        infos = []
        # One InstanceInfo per worker host of the slice (parity:
        # instance_utils.py:1635-1656). The slice index rides along so
        # multislice clusters (num_nodes > 1 TPU nodes) get per-slice
        # TPU worker ids + MEGASCALE DCN envs (gang_run.build_rank_envs).
        for worker_idx, ep in enumerate(node.get('networkEndpoints', [])):
            infos.append(
                common.InstanceInfo(
                    instance_id=f'{name}/worker-{worker_idx}',
                    internal_ip=ep.get('ipAddress', ''),
                    external_ip=ep.get('accessConfig', {}).get('externalIp'),
                    tags={'worker_index': str(worker_idx),
                          'slice_index': str(slice_idx)},
                ))
        instances[name] = infos
    ssh_user = provider_config.get('ssh_user', 'skytpu')
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name='gcp',
        provider_config=provider_config,
        ssh_user=ssh_user,
        ssh_private_key=provider_config.get('ssh_private_key'),
        custom_metadata=custom,
    )


def _gce_cluster_info(gce_instances: List[dict],
                      cluster_name_on_cloud: str,
                      provider_config: Dict[str, Any]
                      ) -> common.ClusterInfo:
    del cluster_name_on_cloud
    instances: Dict[str, List[common.InstanceInfo]] = {}
    for inst in sorted(gce_instances, key=lambda i: i['name']):
        name = inst['name']
        nic = (inst.get('networkInterfaces') or [{}])[0]
        access = (nic.get('accessConfigs') or [{}])[0]
        instances[name] = [
            common.InstanceInfo(instance_id=name,
                                internal_ip=nic.get('networkIP', ''),
                                external_ip=access.get('natIP'),
                                tags={})
        ]
    heads = [n for n in instances if n.endswith('-0')]
    head_id = heads[0] if heads else (sorted(instances)[0]
                                      if instances else None)
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name='gcp',
        provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'skytpu'),
        ssh_private_key=provider_config.get('ssh_private_key'),
    )


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    """instance_id → status string (parity: query_instances)."""
    from skypilot_tpu.provision.gcp import gce_api
    assert provider_config is not None
    zone = provider_config['availability_zone']
    client = _client(provider_config)
    out: Dict[str, Optional[str]] = {}
    nodes = _cluster_nodes(client, zone, cluster_name_on_cloud)
    for node in nodes:
        status = _STATE_MAP.get(node.get('state'), 'pending')
        if non_terminated_only and status == 'terminated':
            continue
        out[node['name'].split('/')[-1]] = status
    if nodes:
        # A TPU cluster (even fully preempted/filtered) never has GCE
        # instances — skip the compute API entirely.
        return out
    for inst in _gce_cluster_instances(provider_config,
                                       cluster_name_on_cloud,
                                       best_effort=True):
        out[inst['name']] = gce_api.STATE_MAP.get(inst.get('status'),
                                                  'pending')
    return out


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    assert provider_config is not None
    zone = provider_config['availability_zone']
    client = _client(provider_config)
    for node in _cluster_nodes(client, zone, cluster_name_on_cloud):
        name = node['name'].split('/')[-1]
        if worker_only and name.endswith('-0'):
            continue
        if len(node.get('networkEndpoints', [])) > 1:
            raise common.ProvisionerError(
                f'TPU slice {name} is multi-host and cannot be stopped; '
                'only terminate is supported (GCP limitation).')
        client.stop_node(zone, name)
    gce = _gce_client(provider_config)
    for inst in _gce_cluster_instances(provider_config,
                                       cluster_name_on_cloud,
                                       best_effort=True, client=gce):
        if worker_only and inst['name'].endswith('-0'):
            continue
        gce.stop(zone, inst['name'])


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    zone = provider_config['availability_zone']
    client = _client(provider_config)
    for node in _cluster_nodes(client, zone, cluster_name_on_cloud):
        name = node['name'].split('/')[-1]
        if worker_only and name.endswith('-0'):
            continue
        client.delete_node(zone, name)
    if not worker_only:
        # Sweep the cluster's queued-resource records — including
        # STILL-PENDING requests whose nodes never materialized (a
        # grant racing teardown would otherwise create an orphan,
        # billed slice). Ids match '<cluster>-qr-<8 hex>' EXACTLY so a
        # sibling cluster literally named '<cluster>-qr' can't be swept.
        import re
        pattern = re.compile(
            re.escape(_qr_prefix(cluster_name_on_cloud)) +
            r'[0-9a-f]{8}$')
        for qr in client.list_queued_resources(zone):
            qr_id = qr.get('name', '').split('/')[-1]
            if pattern.fullmatch(qr_id):
                client.delete_queued_resource(zone, qr_id)
    # GCE half LAST and best-effort: a TPU-only project without the
    # Compute Engine API enabled must still complete TPU teardown and
    # the queued-resource sweep above.
    gce = _gce_client(provider_config)
    for inst in _gce_cluster_instances(provider_config,
                                       cluster_name_on_cloud,
                                       best_effort=True, client=gce):
        if worker_only and inst['name'].endswith('-0'):
            continue
        gce.delete(zone, inst['name'])


def _network_tag(cluster_name_on_cloud: str) -> str:
    return f'skytpu-{cluster_name_on_cloud}'


def _firewall_name(cluster_name_on_cloud: str) -> str:
    return f'skytpu-{cluster_name_on_cloud}-ports'


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    """ONE VPC firewall rule allowing the task's `ports:` to the
    cluster's network tag (parity: the reference's GCP firewall
    bootstrap in provision/gcp/config.py)."""
    if not ports:
        return
    assert provider_config is not None
    client = _gce_client(provider_config)
    client.upsert_firewall({
        'name': _firewall_name(cluster_name_on_cloud),
        'network': 'global/networks/default',
        'direction': 'INGRESS',
        'sourceRanges': ['0.0.0.0/0'],
        'targetTags': [_network_tag(cluster_name_on_cloud)],
        'allowed': [{'IPProtocol': 'tcp',
                     'ports': [str(p) for p in ports]}],
    })
    logger.info(f'Opened ports {ports} for {cluster_name_on_cloud} '
                '(VPC firewall rule).')


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del ports
    assert provider_config is not None
    client = _gce_client(provider_config)
    try:
        client.delete_firewall(_firewall_name(cluster_name_on_cloud))
    except tpu_api.TpuApiError as exc:
        # Best-effort: a project without the Compute API (TPU-only,
        # never opened ports) must not fail teardown here.
        logger.debug(f'cleanup_ports({cluster_name_on_cloud}): {exc}')
