"""TPU VM REST API client (tpu.googleapis.com v2) with a fake backend.

Parity: the reference drives this API from ``GCPTPUVMInstance``
(``sky/provision/gcp/instance_utils.py:1191``): create/stop/delete/query
nodes, poll long-running operations, and fan multi-host slices out via
``networkEndpoints[]`` (``:1635-1656``).

Transport is pluggable:

* :class:`RestTransport` — real HTTP via ``requests`` with a
  ``gcloud auth print-access-token`` bearer token.
* :class:`FakeTpuService` — in-memory implementation of the same surface,
  used by tests and when ``SKYTPU_GCP_FAKE=1`` (e.g. CI without egress).
  Fault injection: set ``SKYTPU_GCP_FAKE_STOCKOUT='zone1,zone2'`` to make
  those zones raise capacity errors — exercising the failover engine.
"""
import json
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_API_BASE = 'https://tpu.googleapis.com/v2'

_FAKE_STATE_ENV = 'SKYTPU_GCP_FAKE_STATE'  # json file for cross-process fakes


class TpuApiError(Exception):

    def __init__(self, status: int, message: str, body: Optional[dict] = None):
        super().__init__(f'TPU API error {status}: {message}')
        self.status = status
        self.message = message
        self.body = body or {}


class GcpCapacityError(TpuApiError, provision_common.CapacityError):
    """Stockout / quota errors — the failover engine blocklists the zone."""
    scope = 'zone'


def _get_access_token() -> str:
    proc = subprocess.run(['gcloud', 'auth', 'print-access-token'],
                          capture_output=True,
                          text=True,
                          timeout=30,
                          check=False)
    if proc.returncode != 0:
        raise TpuApiError(401, f'gcloud token failed: {proc.stderr.strip()}')
    return proc.stdout.strip()


class RestTransport:
    """Thin authenticated JSON-over-HTTP layer."""

    def __init__(self):
        import requests  # local import: only the real path needs it
        self._session = requests.Session()
        self._token: Optional[str] = None
        self._token_time = 0.0

    def _headers(self) -> Dict[str, str]:
        if self._token is None or time.time() - self._token_time > 1800:
            self._token = _get_access_token()
            self._token_time = time.time()
        return {
            'Authorization': f'Bearer {self._token}',
            'Content-Type': 'application/json',
        }

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[dict] = None) -> dict:
        url = f'{_API_BASE}/{path.lstrip("/")}'
        resp = self._session.request(method,
                                     url,
                                     headers=self._headers(),
                                     json=body,
                                     params=params,
                                     timeout=60)
        if resp.status_code >= 400:
            try:
                payload = resp.json()
            except ValueError:
                payload = {'error': {'message': resp.text}}
            message = payload.get('error', {}).get('message', resp.text)
            lowered = message.lower()
            if ('no more capacity' in lowered or 'stockout' in lowered or
                    'resource_exhausted' in lowered or
                    'quota' in lowered or
                    'not enough resources' in lowered):
                raise GcpCapacityError(resp.status_code, message, payload)
            raise TpuApiError(resp.status_code, message, payload)
        return resp.json() if resp.text else {}


class FakeTpuService:
    """In-memory tpu.googleapis.com: nodes + instant operations.

    State optionally persisted to a JSON file (``SKYTPU_GCP_FAKE_STATE``) so
    separate processes (CLI invocations in tests) see the same cloud.
    """

    _lock = threading.Lock()
    _nodes: Dict[str, Dict[str, Any]] = {}

    def __init__(self):
        self._state_path = os.environ.get(_FAKE_STATE_ENV)

    # -------------------------------------------------------- persistence

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding='utf-8') as f:
                return json.load(f)
        return FakeTpuService._nodes

    def _save(self, nodes: Dict[str, Dict[str, Any]]) -> None:
        if self._state_path:
            with open(self._state_path, 'w', encoding='utf-8') as f:
                json.dump(nodes, f)
        else:
            FakeTpuService._nodes = nodes

    # ----------------------------------------------------------- protocol

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[dict] = None) -> dict:
        with FakeTpuService._lock:
            return self._dispatch(method, path, body or {}, params or {})

    def _dispatch(self, method: str, path: str, body: dict,
                  params: dict) -> dict:
        nodes = self._load()
        parts = path.strip('/').split('/')
        # projects/{p}/locations/{zone}/nodes[...]
        zone = parts[3] if len(parts) > 3 else ''
        stockout_zones = os.environ.get('SKYTPU_GCP_FAKE_STOCKOUT', '')
        if '/queuedResources' in path:
            return self._dispatch_qr(method, path, body, params, zone,
                                     nodes)
        if method == 'POST' and parts[-1] == 'nodes':
            if zone in stockout_zones.split(','):
                raise GcpCapacityError(
                    429, f'There is no more capacity in the zone "{zone}"')
            node_id = params['nodeId']
            full = f'{path}/{node_id}'
            accel = body.get('acceleratorType', 'v5e-8')
            node = dict(body)
            node['name'] = full
            node['state'] = 'READY'
            node['networkEndpoints'] = self._make_endpoints(accel)
            nodes[full] = node
            self._save(nodes)
            return {'name': f'op/{uuid.uuid4()}', 'done': True,
                    'response': node}
        if method == 'GET' and parts[-1] == 'nodes':
            matched = [
                n for k, n in nodes.items()
                if k.startswith(path.strip('/') + '/') or
                k.split('/nodes/')[0] == path.strip('/').rsplit('/nodes')[0]
            ]
            return {'nodes': matched}
        if method == 'GET' and parts[-1] == 'operations':
            # Zone operations log: synthesize a preempted-type op per
            # PREEMPTED node (matching what the real log retains after
            # spot reclamation).
            loc = path.strip('/').rsplit('/operations', 1)[0]
            ops = []
            for key, node in nodes.items():
                if node.get('state') == 'PREEMPTED' and \
                        key.startswith(loc + '/nodes/'):
                    ops.append({
                        'name': f'{loc}/operations/preempt-'
                                f'{key.rsplit("/", 1)[1]}',
                        'metadata': {'type': 'preempted', 'target': key,
                                     'createTime':
                                         '2026-01-01T00:00:00Z'},
                        'done': True,
                    })
            return {'operations': ops}
        if method == 'GET':
            key = path.strip('/')
            if key.startswith('op/') or '/operations/' in key:
                return {'name': key, 'done': True}
            if key not in nodes:
                raise TpuApiError(404, f'Node {key} not found')
            return nodes[key]
        if method == 'DELETE':
            key = path.strip('/')
            nodes.pop(key, None)
            self._save(nodes)
            return {'name': f'op/{uuid.uuid4()}', 'done': True}
        if method == 'POST' and parts[-1].endswith(':stop'):
            key = path.strip('/').rsplit(':', 1)[0]
            if key in nodes:
                nodes[key]['state'] = 'STOPPED'
                self._save(nodes)
            return {'name': f'op/{uuid.uuid4()}', 'done': True}
        if method == 'POST' and parts[-1].endswith(':start'):
            key = path.strip('/').rsplit(':', 1)[0]
            if key in nodes:
                nodes[key]['state'] = 'READY'
                self._save(nodes)
            return {'name': f'op/{uuid.uuid4()}', 'done': True}
        raise TpuApiError(400, f'Fake: unsupported {method} {path}')

    def _dispatch_qr(self, method: str, path: str, body: dict,
                     params: dict, zone: str, nodes: dict) -> dict:
        """Queued-resources surface. Fault injection (comma-separated
        zone lists):

        * ``SKYTPU_GCP_FAKE_QR_DENY`` — QR transitions to FAILED.
        * ``SKYTPU_GCP_FAKE_QR_WAIT`` — QR stays WAITING_FOR_RESOURCES
          forever (exercises the provision_timeout cancel path).
        * otherwise the QR is granted: state ACTIVE + nodes created.
        """
        key = path.strip('/')
        deny = os.environ.get('SKYTPU_GCP_FAKE_QR_DENY', '').split(',')
        hold = os.environ.get('SKYTPU_GCP_FAKE_QR_WAIT', '').split(',')
        if method == 'POST' and key.endswith('queuedResources'):
            qr_id = params['queuedResourceId']
            full = f'{key}/{qr_id}'
            if zone in deny:
                # Real-API shape: reason under state.failedData.error.
                state = {'state': 'FAILED',
                         'stateInitiator': 'SERVICE',
                         'failedData': {'error': {
                             'code': 8,
                             'message': 'no capacity available in zone '
                                        f'{zone}'}}}
            elif zone in hold:
                state = {'state': 'WAITING_FOR_RESOURCES'}
            else:
                state = {'state': 'ACTIVE'}
                # Granted: materialize the requested nodes.
                for spec in body.get('tpu', {}).get('nodeSpec', []):
                    node_body = dict(spec.get('node', {}))
                    node_path = f"{spec['parent']}/nodes/{spec['nodeId']}"
                    accel = node_body.get('acceleratorType', 'v5e-8')
                    node_body['name'] = node_path
                    node_body['state'] = 'READY'
                    node_body['networkEndpoints'] = \
                        self._make_endpoints(accel)
                    nodes[node_path] = node_body
            qr = dict(body)
            qr['name'] = full
            qr['state'] = state
            nodes[full] = qr
            self._save(nodes)
            return {'name': f'op/{uuid.uuid4()}', 'done': True,
                    'response': qr}
        if method == 'GET' and key.endswith('queuedResources'):
            prefix = key + '/'
            return {'queuedResources':
                    [v for k, v in nodes.items()
                     if k.startswith(prefix)]}
        if method == 'GET':
            if key not in nodes:
                raise TpuApiError(404, f'Queued resource {key} not found')
            return nodes[key]
        if method == 'DELETE':
            if nodes.pop(key, None) is None:
                raise TpuApiError(404, f'Queued resource {key} not found')
            self._save(nodes)
            return {'name': f'op/{uuid.uuid4()}', 'done': True}
        raise TpuApiError(400, f'Fake: unsupported QR {method} {path}')

    @staticmethod
    def _make_endpoints(accelerator_type: str) -> List[dict]:
        # v5p-256 → 128 chips → 32 hosts; v5e-8 → 8 chips single host.
        from skypilot_tpu import topology as topo_lib
        gen_name, size = accelerator_type.rsplit('-', 1)
        gen = topo_lib.TPU_GENERATIONS[gen_name]
        chips = int(size) // gen.cores_per_chip
        if chips in gen.single_host_sizes:
            hosts = 1
        else:
            hosts = max(1, chips // gen.chips_per_host)
        return [{
            'ipAddress': f'10.0.0.{i + 2}',
            'accessConfig': {'externalIp': f'34.1.0.{i + 2}'},
        } for i in range(hosts)]


def make_transport():
    if os.environ.get('SKYTPU_GCP_FAKE', '0') == '1':
        return FakeTpuService()
    return RestTransport()


class TpuClient:
    """Typed wrapper over the node/operation surface."""

    def __init__(self, project: str, transport=None):
        self.project = project
        self.transport = transport or make_transport()

    def _loc(self, zone: str) -> str:
        return f'projects/{self.project}/locations/{zone}'

    def create_node(self, zone: str, node_id: str,
                    config: Dict[str, Any]) -> dict:
        op = self.transport.request('POST', f'{self._loc(zone)}/nodes',
                                    body=config, params={'nodeId': node_id})
        return self.wait_operation(op)

    def list_nodes(self, zone: str) -> List[dict]:
        resp = self.transport.request('GET', f'{self._loc(zone)}/nodes')
        return resp.get('nodes', [])

    def get_node(self, zone: str, node_id: str) -> dict:
        return self.transport.request('GET',
                                      f'{self._loc(zone)}/nodes/{node_id}')

    def delete_node(self, zone: str, node_id: str) -> dict:
        op = self.transport.request('DELETE',
                                    f'{self._loc(zone)}/nodes/{node_id}')
        return self.wait_operation(op)

    def stop_node(self, zone: str, node_id: str) -> dict:
        op = self.transport.request(
            'POST', f'{self._loc(zone)}/nodes/{node_id}:stop')
        return self.wait_operation(op)

    def start_node(self, zone: str, node_id: str) -> dict:
        op = self.transport.request(
            'POST', f'{self._loc(zone)}/nodes/{node_id}:start')
        return self.wait_operation(op)

    def list_preemption_events(self, zone: str) -> List[dict]:
        """Recent preemption events for spot slices in ``zone``.

        The TPU API surfaces preemptions two ways: the node transitions
        to PREEMPTED (visible in list_nodes until cleanup), and the
        zone's operations log records a ``preempted``-type operation —
        the only trace left AFTER a preempted node is deleted. The
        managed-jobs recovery path uses node state; this query is the
        audit/debug surface (parity: the reference's preemption-event
        checks on GCE instances, instance_utils.py).
        """
        # No server-side filter: operations.list's AIP-160 filter
        # grammar is fiddly across API versions — list and classify
        # client-side by the operation TYPE (strict; an op merely
        # mentioning 'preempt' in free text must not count).
        try:
            resp = self.transport.request(
                'GET', f'{self._loc(zone)}/operations')
        except TpuApiError as exc:
            if exc.status == 404:
                return []
            raise
        out = []
        for op in resp.get('operations', []):
            meta = op.get('metadata', {})
            op_type = str(meta.get('type', '')).lower()
            if 'preempt' in op_type:
                out.append({
                    'operation': op.get('name', ''),
                    'target': meta.get('target', ''),
                    'time': meta.get('createTime', ''),
                })
        return out

    # -------------------------------------------------- queued resources
    # Parity: the reference's DWS/capacity paths (mig_utils.py MIG +
    # instance_utils.py:311) — for TPUs the real mechanism is the
    # queued-resources API, how v5p slices are actually obtained when
    # on-demand create stocks out.

    def create_queued_resource(self, zone: str, qr_id: str,
                               node_specs: List[Dict[str, Any]],
                               valid_until_s: Optional[float] = None,
                               spot: bool = False,
                               reserved: bool = False) -> dict:
        """``node_specs``: [(node_id, node_body) dicts] — ONE QR for the
        whole gang, so multi-node clusters get an all-or-nothing grant
        instead of holding node 0's capacity while node N queues."""
        body: Dict[str, Any] = {
            'tpu': {
                'nodeSpec': [{
                    'parent': self._loc(zone),
                    'nodeId': spec['node_id'],
                    'node': spec['node'],
                } for spec in node_specs]
            }
        }
        if spot:
            body['spot'] = {}
        elif reserved:
            body['guaranteed'] = {'reserved': True}
        if valid_until_s:
            body['queueingPolicy'] = {
                'validUntilDuration': f'{int(valid_until_s)}s'
            }
        return self.transport.request(
            'POST', f'{self._loc(zone)}/queuedResources', body=body,
            params={'queuedResourceId': qr_id})

    def get_queued_resource(self, zone: str, qr_id: str) -> dict:
        return self.transport.request(
            'GET', f'{self._loc(zone)}/queuedResources/{qr_id}')

    def list_queued_resources(self, zone: str) -> List[dict]:
        resp = self.transport.request(
            'GET', f'{self._loc(zone)}/queuedResources')
        return resp.get('queuedResources', [])

    def delete_queued_resource(self, zone: str, qr_id: str) -> None:
        try:
            op = self.transport.request(
                'DELETE', f'{self._loc(zone)}/queuedResources/{qr_id}',
                params={'force': 'true'})
        except TpuApiError as exc:
            if exc.status == 404:
                return
            raise
        self.wait_operation(op)

    def wait_queued_resource(self, zone: str, qr_id: str,
                             timeout: float = 900.0) -> dict:
        """Poll a queued resource until granted, denied, or timed out.

        Terminal classification feeds the failover blocklist:
        * ACTIVE → the slice was granted; return the QR.
        * FAILED / SUSPENDED (stockout, quota, deadline exceeded) →
          GcpCapacityError (zone scope).
        * still WAITING past ``timeout`` → cancel the QR and raise
          GcpCapacityError so the failover engine moves on — an
          ungranted request held forever blocks the whole launch.
        """
        deadline = time.time() + timeout
        backoff = 2.0
        while True:
            qr = self.get_queued_resource(zone, qr_id)
            state = (qr.get('state') or {}).get('state', 'ACCEPTED')
            if state == 'ACTIVE':
                return qr
            if state in ('FAILED', 'SUSPENDING', 'SUSPENDED'):
                # The v2 API puts the denial reason in
                # state.failedData.error (stateInitiator is just an
                # enum of WHO moved the state).
                st = qr.get('state') or {}
                err = (st.get('failedData') or {}).get('error') or {}
                detail = err.get('message') or json.dumps(st)
                self.delete_queued_resource(zone, qr_id)
                raise GcpCapacityError(
                    429, f'Queued resource {qr_id} in {zone} was not '
                    f'granted (state={state}): {detail}', qr)
            if time.time() > deadline:
                self.delete_queued_resource(zone, qr_id)
                raise GcpCapacityError(
                    429, f'Queued resource {qr_id} in {zone} not granted '
                    f'within {int(timeout)}s (state={state}); cancelled '
                    'and failing over.', qr)
            time.sleep(backoff)
            backoff = min(backoff * 1.5, 15.0)

    def wait_operation(self, op: dict, timeout: float = 1800.0) -> dict:
        """Poll a long-running operation (parity: instance_utils.py
        :1212-1258 operation polling loop)."""
        deadline = time.time() + timeout
        backoff = 1.0
        while not op.get('done', False):
            if time.time() > deadline:
                raise TpuApiError(
                    504, f'Operation {op.get("name")} timed out.')
            time.sleep(backoff)
            backoff = min(backoff * 1.5, 10.0)
            op = self.transport.request('GET', op['name'])
        if 'error' in op:
            err = op['error']
            message = err.get('message', str(err))
            lowered = message.lower()
            if ('capacity' in lowered or 'stockout' in lowered or
                    'quota' in lowered):
                raise GcpCapacityError(429, message, op)
            raise TpuApiError(500, message, op)
        return op.get('response', op)
