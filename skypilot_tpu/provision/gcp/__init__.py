"""GCP provisioner: TPU VM slices (first-class) via tpu.googleapis.com.

Parity: ``sky/provision/gcp/`` — but TPU-only and TPU-first: the unit of
provisioning is a *slice node* whose ``networkEndpoints[]`` fan out to one
``InstanceInfo`` per worker host (parity: instance_utils.py:1635-1656).
Compute-VM support (GPU hosts) is routed through the same surface later.
"""
from skypilot_tpu.provision.gcp.instance import cleanup_ports
from skypilot_tpu.provision.gcp.instance import get_cluster_info
from skypilot_tpu.provision.gcp.instance import open_ports
from skypilot_tpu.provision.gcp.instance import query_instances
from skypilot_tpu.provision.gcp.instance import run_instances
from skypilot_tpu.provision.gcp.instance import stop_instances
from skypilot_tpu.provision.gcp.instance import terminate_instances
from skypilot_tpu.provision.gcp.instance import wait_instances

__all__ = [
    'cleanup_ports', 'get_cluster_info', 'open_ports', 'query_instances',
    'run_instances', 'stop_instances', 'terminate_instances',
    'wait_instances'
]
