"""GCE instance API client (compute.googleapis.com v1) with a fake.

Parity: ``GCPComputeInstance`` in
``sky/provision/gcp/instance_utils.py:141`` — the reference provisions
GCE VMs (GPU + CPU) alongside TPU nodes; this module is the GCE half of
the GCP provisioner (``tpu_api.py`` is the TPU half). Same two-transport
shape:

* :class:`RestTransport` — real HTTP with a ``gcloud`` bearer token.
* :class:`FakeGceService` — in-memory instances, used by tests and when
  ``SKYTPU_GCP_FAKE=1``. Fault injection:
  ``SKYTPU_GCP_FAKE_GCE_STOCKOUT='zone1,...'`` makes insert in those
  zones raise a zonal capacity error (ZONE_RESOURCE_POOL_EXHAUSTED).
"""
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision.gcp import tpu_api

logger = sky_logging.init_logger(__name__)

_API_BASE = 'https://compute.googleapis.com/compute/v1'

_FAKE_STATE_ENV = 'SKYTPU_GCP_GCE_FAKE_STATE'

# GCE status → framework status strings.
STATE_MAP = {
    'PROVISIONING': 'pending',
    'STAGING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'SUSPENDING': 'stopping',
    'SUSPENDED': 'stopped',
    'TERMINATED': 'stopped',  # GCE TERMINATED = stopped (not deleted)
}

# Accelerator name → GCE guestAccelerator type for machine families that
# do NOT embed their GPUs (n1). a2/a3/g2 machine types embed theirs.
GUEST_ACCELERATORS = {
    'V100': 'nvidia-tesla-v100',
    'T4': 'nvidia-tesla-t4',
    'P100': 'nvidia-tesla-p100',
}


class RestTransport:
    """compute.googleapis.com through requests + gcloud token (same
    auth pattern as tpu_api.RestTransport)."""

    def __init__(self):
        import requests
        self._session = requests.Session()
        self._token: Optional[str] = None
        self._token_time = 0.0

    def _headers(self) -> Dict[str, str]:
        if self._token is None or time.time() - self._token_time > 1800:
            self._token = tpu_api._get_access_token()  # pylint: disable=protected-access
            self._token_time = time.time()
        return {'Authorization': f'Bearer {self._token}',
                'Content-Type': 'application/json'}

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[dict] = None) -> dict:
        url = f'{_API_BASE}/{path.lstrip("/")}'
        resp = self._session.request(method, url,
                                     headers=self._headers(), json=body,
                                     params=params, timeout=60)
        if resp.status_code >= 400:
            try:
                payload = resp.json()
            except ValueError:
                payload = {'error': {'message': resp.text}}
            message = payload.get('error', {}).get('message', resp.text)
            lowered = message.lower()
            if ('resource_pool_exhausted' in lowered or
                    'zone_resource_pool_exhausted' in lowered or
                    'does not have enough resources' in lowered or
                    'quota' in lowered):
                raise tpu_api.GcpCapacityError(resp.status_code, message,
                                               payload)
            raise tpu_api.TpuApiError(resp.status_code, message, payload)
        return resp.json() if resp.text else {}


class FakeGceService:
    """In-memory GCE: instances + instant operations."""

    _lock = threading.Lock()
    _instances: Dict[str, Dict[str, Any]] = {}

    def __init__(self):
        self._state_path = os.environ.get(_FAKE_STATE_ENV)

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding='utf-8') as f:
                return json.load(f)
        return FakeGceService._instances

    def _save(self, instances: Dict[str, Dict[str, Any]]) -> None:
        if self._state_path:
            with open(self._state_path, 'w', encoding='utf-8') as f:
                json.dump(instances, f)
        else:
            FakeGceService._instances = instances

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[dict] = None) -> dict:
        with FakeGceService._lock:
            return self._dispatch(method, path, body or {}, params or {})

    def _dispatch(self, method: str, path: str, body: dict,
                  params: dict) -> dict:
        instances = self._load()
        parts = path.strip('/').split('/')
        # projects/{p}/zones/{zone}/instances[/...]
        zone = parts[3] if len(parts) > 3 else ''
        if '/global/firewalls' in path:
            key = path.strip('/')
            if method == 'POST':
                name = body['name']
                full = f'{key}/{name}'
                if full in instances:
                    # Real-API fidelity: duplicate insert is 409, NOT a
                    # silent replace (callers must PATCH).
                    raise tpu_api.TpuApiError(
                        409, f"The resource '{name}' already exists")
                instances[full] = dict(body)
                self._save(instances)
                return {'name': f'op/{uuid.uuid4()}', 'status': 'DONE'}
            if method == 'PATCH':
                if key not in instances:
                    raise tpu_api.TpuApiError(404, f'{key} not found')
                instances[key] = dict(body)
                self._save(instances)
                return {'name': f'op/{uuid.uuid4()}', 'status': 'DONE'}
            if method == 'GET':
                if key not in instances:
                    raise tpu_api.TpuApiError(404, f'{key} not found')
                return instances[key]
            if method == 'DELETE':
                if instances.pop(key, None) is None:
                    raise tpu_api.TpuApiError(404, f'{key} not found')
                self._save(instances)
                return {'name': f'op/{uuid.uuid4()}', 'status': 'DONE'}
        if method == 'POST' and parts[-1] == 'instances':
            stockout = os.environ.get('SKYTPU_GCP_FAKE_GCE_STOCKOUT', '')
            if zone in stockout.split(','):
                raise tpu_api.GcpCapacityError(
                    403, 'ZONE_RESOURCE_POOL_EXHAUSTED: The zone '
                    f'"{zone}" does not have enough resources.')
            name = body['name']
            key = f'{path.strip("/")}/{name}'
            inst = dict(body)
            inst['status'] = 'RUNNING'
            idx = len(instances) + 2
            inst['networkInterfaces'] = [{
                'networkIP': f'10.1.0.{idx}',
                'accessConfigs': [{'natIP': f'35.1.0.{idx}'}],
            }]
            instances[key] = inst
            self._save(instances)
            return {'name': f'op/{uuid.uuid4()}', 'status': 'DONE'}
        if method == 'GET' and parts[-1] == 'instances':
            prefix = path.strip('/') + '/'
            items = [v for k, v in instances.items()
                     if k.startswith(prefix)]
            flt = params.get('filter', '')
            if flt:
                # 'labels.<k>=<v>' — the single filter shape we emit.
                k, _, v = flt.replace('labels.', '').partition('=')
                items = [i for i in items
                         if i.get('labels', {}).get(k) == v.strip('"')]
            return {'items': items}
        key = path.strip('/')
        if method == 'GET':
            if key.startswith('op/'):
                return {'name': key, 'status': 'DONE'}
            if key not in instances:
                raise tpu_api.TpuApiError(404, f'{key} not found')
            return instances[key]
        if method == 'DELETE':
            instances.pop(key, None)
            self._save(instances)
            return {'name': f'op/{uuid.uuid4()}', 'status': 'DONE'}
        if method == 'POST' and key.endswith('/stop'):
            inst = instances.get(key.rsplit('/', 1)[0])
            if inst is not None:
                inst['status'] = 'TERMINATED'
                self._save(instances)
            return {'name': f'op/{uuid.uuid4()}', 'status': 'DONE'}
        if method == 'POST' and key.endswith('/start'):
            inst = instances.get(key.rsplit('/', 1)[0])
            if inst is not None:
                inst['status'] = 'RUNNING'
                self._save(instances)
            return {'name': f'op/{uuid.uuid4()}', 'status': 'DONE'}
        raise tpu_api.TpuApiError(400, f'Fake GCE: unsupported '
                                  f'{method} {path}')


def make_transport():
    if os.environ.get('SKYTPU_GCP_FAKE', '0') == '1':
        return FakeGceService()
    return RestTransport()


class GceClient:
    """Typed wrapper over the instances surface."""

    def __init__(self, project: str, transport=None):
        self.project = project
        self.transport = transport or make_transport()

    def _zone(self, zone: str) -> str:
        return f'projects/{self.project}/zones/{zone}'

    def insert(self, zone: str, body: Dict[str, Any]) -> dict:
        op = self.transport.request('POST',
                                    f'{self._zone(zone)}/instances',
                                    body=body)
        return self.wait_operation(zone, op)

    def list_instances(self, zone: str,
                       label: Optional[tuple] = None) -> List[dict]:
        params = {}
        if label is not None:
            params['filter'] = f'labels.{label[0]}="{label[1]}"'
        resp = self.transport.request(
            'GET', f'{self._zone(zone)}/instances', params=params)
        return resp.get('items', [])

    def delete(self, zone: str, name: str) -> dict:
        op = self.transport.request(
            'DELETE', f'{self._zone(zone)}/instances/{name}')
        return self.wait_operation(zone, op)

    def stop(self, zone: str, name: str) -> dict:
        op = self.transport.request(
            'POST', f'{self._zone(zone)}/instances/{name}/stop')
        return self.wait_operation(zone, op)

    def start(self, zone: str, name: str) -> dict:
        op = self.transport.request(
            'POST', f'{self._zone(zone)}/instances/{name}/start')
        return self.wait_operation(zone, op)

    # Firewalls (global resources) — `ports:` exposure targets the
    # cluster's network tag.

    def _global(self) -> str:
        return f'projects/{self.project}/global'

    def upsert_firewall(self, body: Dict[str, Any]) -> None:
        """Create or update the rule (idempotent: relaunching a cluster
        with `ports:` re-applies the same rule; changed ports patch
        through). Both mutations are polled to completion — a
        fire-and-forget insert would report 'opened' while an async
        quota failure left the ports closed."""
        try:
            op = self.transport.request(
                'POST', f'{self._global()}/firewalls', body=body)
        except tpu_api.TpuApiError as exc:
            if exc.status != 409:
                raise
            op = self.transport.request(
                'PATCH', f'{self._global()}/firewalls/{body["name"]}',
                body=body)
        self.wait_operation(None, op)

    def get_firewall(self, name: str) -> dict:
        return self.transport.request(
            'GET', f'{self._global()}/firewalls/{name}')

    def delete_firewall(self, name: str) -> None:
        try:
            op = self.transport.request(
                'DELETE', f'{self._global()}/firewalls/{name}')
        except tpu_api.TpuApiError as exc:
            if exc.status != 404:
                raise
            return
        self.wait_operation(None, op)

    def wait_operation(self, zone: Optional[str], op: dict,
                       timeout: float = 900.0) -> dict:
        """Poll a zonal (``zone`` set) or global (``zone=None``)
        operation to DONE."""
        deadline = time.time() + timeout
        backoff = 1.0
        while op.get('status') != 'DONE':
            if time.time() > deadline:
                raise tpu_api.TpuApiError(
                    504, f'GCE operation {op.get("name")} timed out.')
            time.sleep(backoff)
            backoff = min(backoff * 1.5, 10.0)
            # Real operations come back as BARE ids
            # ('operation-abc...'); the poll URL is the zonal/global
            # operations resource. A full resource path (the fake's
            # 'op/...' never reaches here: the fake returns DONE) is
            # used as-is.
            name = op['name']
            if not name.startswith('projects/'):
                scope = (self._zone(zone) if zone is not None
                         else self._global())
                name = (f'{scope}/operations/'
                        f'{name.rsplit("/", 1)[-1]}')
            op = self.transport.request('GET', name)
        if 'error' in op:
            errors = op['error'].get('errors', [])
            message = '; '.join(e.get('message', e.get('code', ''))
                                for e in errors) or str(op['error'])
            lowered = message.lower()
            if 'exhausted' in lowered or 'quota' in lowered:
                raise tpu_api.GcpCapacityError(429, message, op)
            raise tpu_api.TpuApiError(500, message, op)
        return op
