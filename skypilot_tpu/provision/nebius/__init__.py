"""Nebius compute provisioner (parity: ``sky/provision/nebius/``)."""
from skypilot_tpu.provision.nebius.instance import cleanup_ports
from skypilot_tpu.provision.nebius.instance import get_cluster_info
from skypilot_tpu.provision.nebius.instance import open_ports
from skypilot_tpu.provision.nebius.instance import query_instances
from skypilot_tpu.provision.nebius.instance import run_instances
from skypilot_tpu.provision.nebius.instance import stop_instances
from skypilot_tpu.provision.nebius.instance import terminate_instances
from skypilot_tpu.provision.nebius.instance import wait_instances
