"""Nebius compute API client (parity: ``sky/provision/nebius/utils.py``).

curl against the Nebius compute REST surface (IAM Bearer token from
$NEBIUS_IAM_TOKEN or ~/.nebius/iam_token), or the shared fake when
``SKYTPU_NEBIUS_FAKE=1``.
"""
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake
from skypilot_tpu.provision import rest_transport

_API_URL = 'https://api.nebius.cloud/compute/v1'

STATE_MAP = {
    'PROVISIONING': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'terminating',
    'DELETED': 'terminated',
    'running': 'running',
    'stopped': 'stopped',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('not enough resources', 'quota', 'resource_exhausted')


class NebiusApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class NebiusCapacityError(NebiusApiError, provision_common.CapacityError):
    """Region out of the requested platform/preset."""


def iam_token() -> Optional[str]:
    token = os.environ.get('NEBIUS_IAM_TOKEN')
    if token:
        return token
    path = os.path.expanduser('~/.nebius/iam_token')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            return f.read().strip() or None
    return None


class RestTransport:
    """Real Nebius through curl + the REST API."""

    def __init__(self, token: str):
        self.token = token

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        def classify(o: dict) -> None:
            if o.get('code'):
                msg = str(o.get('message', o))
                if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                    raise NebiusCapacityError(msg)
                raise NebiusApiError(msg)

        return rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}',
            f'header = "Authorization: Bearer {self.token}"\n', body,
            api_error=NebiusApiError, classify=classify)

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del use_spot  # no spot market (gated at the cloud level)
        body: Dict[str, Any] = {
            'name': name,
            'zoneId': region,
            'platformId': instance_type,
            'bootDiskSpec': {'size': '137438953472',
                             'imageFamily': 'ubuntu-22-04'},
        }
        if public_key:
            body['metadata'] = {
                'user-data': ('#cloud-config\nssh_authorized_keys:\n'
                              f'  - {public_key}\n')
            }
        out = self._run('POST', '/instances', body)
        instance_id = out.get('metadata', {}).get('instanceId') or \
            out.get('id')
        if not instance_id:
            raise NebiusApiError(
                f'Instance create returned no id: {out!r}')
        return str(instance_id)

    def list(self) -> List[Dict[str, Any]]:
        out = self._run('GET', '/instances')
        return [{
            'id': str(i['id']),
            'name': i.get('name', ''),
            'instance_type': i.get('platformId', ''),
            'region': i.get('zoneId', ''),
            'status': i.get('status', 'PROVISIONING'),
            'ip': i.get('publicIp'),
            'private_ip': i.get('privateIp', ''),
        } for i in out.get('instances', [])]

    def stop(self, iid: str) -> None:
        self._run('POST', f'/instances/{iid}:stop')

    def start(self, iid: str) -> None:
        self._run('POST', f'/instances/{iid}:start')

    def terminate(self, iid: str) -> None:
        self._run('DELETE', f'/instances/{iid}')


def make_client(region=None):
    del region  # global API
    if neocloud_fake.fake_enabled('NEBIUS'):
        return neocloud_fake.FakeNeoClient(
            'NEBIUS', lambda region: NebiusCapacityError(
                f'Not enough resources in {region}. (fake)'))
    token = iam_token()
    if token is None:
        raise NebiusApiError('No Nebius IAM token configured.')
    return RestTransport(token)
