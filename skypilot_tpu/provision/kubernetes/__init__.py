"""Kubernetes provisioner: GKE TPU podslices + CPU/GPU pods.

Parity: ``sky/provision/kubernetes/`` — kubectl-based, with an in-memory
fake cluster for credential-free end-to-end tests.
"""
from skypilot_tpu.provision.kubernetes.instance import cleanup_ports
from skypilot_tpu.provision.kubernetes.instance import get_cluster_info
from skypilot_tpu.provision.kubernetes.instance import open_ports
from skypilot_tpu.provision.kubernetes.instance import query_instances
from skypilot_tpu.provision.kubernetes.instance import run_instances
from skypilot_tpu.provision.kubernetes.instance import stop_instances
from skypilot_tpu.provision.kubernetes.instance import terminate_instances
from skypilot_tpu.provision.kubernetes.instance import wait_instances

__all__ = [
    'cleanup_ports', 'get_cluster_info', 'open_ports', 'query_instances',
    'run_instances', 'stop_instances', 'terminate_instances',
    'wait_instances'
]
