"""Kubernetes pod lifecycle: pods as instances, GKE TPU podslices first.

Parity: ``sky/provision/kubernetes/instance.py`` — redesigned around the
framework's slice model: a logical node is either a plain CPU/GPU pod or a
TPU podslice whose H hosts fan out to one pod per TPU VM host, mirroring the
GCP provisioner's ``networkEndpoints[]`` fan-out. Pods carry the GKE TPU
nodeSelectors (``cloud.google.com/gke-tpu-accelerator`` +
``gke-tpu-topology``; parity utils.py:96-102) and request ``google.com/tpu``
chips per host, so GKE schedules all H pods onto one podslice nodepool.
"""
import json
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.kubernetes import k8s_api

logger = sky_logging.init_logger(__name__)

_CLUSTER_LABEL = 'skytpu-cluster'
_NODE_LABEL = 'skytpu-node'
_HOST_LABEL = 'skytpu-host'

_DEFAULT_IMAGE = 'python:3.10-slim'

_PHASE_MAP = {
    'Pending': 'pending',
    'Running': 'running',
    'Succeeded': 'terminated',
    'Failed': 'terminated',
    'Unknown': 'pending',
    'Terminating': 'terminating',
}


def _namespace(provider_config: Dict[str, Any]) -> str:
    ns = provider_config.get('namespace')
    if ns:
        return ns
    # In-cluster auth defaults to the service account's own namespace.
    if (k8s_api.resolve_context(provider_config.get('context')) ==
            k8s_api.IN_CLUSTER_CONTEXT):
        return k8s_api.in_cluster_namespace()
    return 'default'


def _client(provider_config: Dict[str, Any]):
    return k8s_api.make_client(provider_config.get('context'))


def _pod_name(cluster: str, node_idx: int, host_idx: int,
              num_hosts: int) -> str:
    if num_hosts == 1:
        return f'{cluster}-{node_idx}'
    return f'{cluster}-{node_idx}-{host_idx}'


def _build_manifest(cluster: str, node_idx: int, host_idx: int,
                    node_cfg: Dict[str, Any]) -> dict:
    num_hosts = int(node_cfg.get('num_hosts', 1))
    name = _pod_name(cluster, node_idx, host_idx, num_hosts)
    image = node_cfg.get('image') or _DEFAULT_IMAGE
    requests: Dict[str, str] = {}
    if node_cfg.get('cpus'):
        requests['cpu'] = str(node_cfg['cpus'])
    if node_cfg.get('memory'):
        requests['memory'] = f'{node_cfg["memory"]}Gi'
    limits: Dict[str, str] = {}
    selector: Dict[str, str] = dict(node_cfg.get('node_selector', {}))
    if node_cfg.get('tpu_accelerator'):
        selector[k8s_api.GKE_TPU_ACCELERATOR_LABEL] = (
            node_cfg['tpu_accelerator'])
        selector[k8s_api.GKE_TPU_TOPOLOGY_LABEL] = node_cfg['tpu_topology']
        chips = str(node_cfg.get('chips_per_host', 0))
        requests[k8s_api.TPU_RESOURCE_KEY] = chips
        limits[k8s_api.TPU_RESOURCE_KEY] = chips
    elif node_cfg.get('gpu'):
        count = str(int(node_cfg.get('gpu_count', 1)))
        requests[k8s_api.GPU_RESOURCE_KEY] = count
        limits[k8s_api.GPU_RESOURCE_KEY] = count
    container: Dict[str, Any] = {
        'name': 'skytpu',
        'image': image,
        # The pod idles; the runtime (skylet) is started by the
        # provision orchestrator over the exec transport.
        'command': ['/bin/bash', '-c', 'sleep infinity'],
        'resources': {'requests': requests, 'limits': limits},
    }
    manifest: Dict[str, Any] = {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {
            'name': name,
            'labels': {
                _CLUSTER_LABEL: cluster,
                _NODE_LABEL: str(node_idx),
                _HOST_LABEL: str(host_idx),
            },
        },
        'spec': {
            'restartPolicy': 'Never',
            'containers': [container],
        },
    }
    if selector:
        manifest['spec']['nodeSelector'] = selector
    overlay = _pod_config_overlay(node_cfg)
    if overlay:
        _merge_pod_config(manifest, overlay)
    return manifest


def _pod_config_overlay(node_cfg: Dict[str, Any]) -> Dict[str, Any]:
    """The user's pod-spec overlay: the global ``kubernetes.pod_config``
    from ~/.skytpu/config.yaml, optionally overridden by a
    ``pod_config`` key a caller places in node_config (the provisioner-
    level hook for per-launch overlays).

    This is how PVC volumes, tolerations, imagePullSecrets, and any
    other pod field the framework doesn't model directly reach the pod
    (parity: sky/provision/kubernetes/utils.py:2234
    combine_pod_config_fields).
    """
    from skypilot_tpu import skypilot_config
    overlay: Dict[str, Any] = {}
    global_cfg = skypilot_config.get_nested(('kubernetes', 'pod_config'),
                                            None)
    if global_cfg:
        _merge_pod_config(overlay, global_cfg)
    task_cfg = node_cfg.get('pod_config')
    if task_cfg:
        _merge_pod_config(overlay, task_cfg)
    return overlay


def _merge_pod_config(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    """Deep-merge ``src`` into ``dst`` with the reference's semantics
    (utils.py combine_pod_config_fields / config_utils.merge_k8s_configs):
    nested dicts merge key-by-key, lists APPEND, scalars overwrite — with
    TWO exceptions:

    * ``containers`` merges positionally, so a pod_config
      ``containers[0].volumeMounts`` lands on the skytpu container
      instead of adding a second container;
    * ``volumes``/``volumeMounts`` entries merge BY ``name`` (append when
      the name is new): duplicate volume names from two overlay sources
      would make the apiserver reject the pod outright.

    Appending everywhere else is what lets two overlay sources each
    contribute a toleration / imagePullSecret without clobbering each
    other."""
    for key, value in src.items():
        if (key in dst and isinstance(dst[key], dict) and
                isinstance(value, dict)):
            _merge_pod_config(dst[key], value)
        elif (key in dst and isinstance(dst[key], list) and
                isinstance(value, list)):
            if key == 'containers':
                for i, item in enumerate(value):
                    if (i < len(dst[key]) and
                            isinstance(dst[key][i], dict) and
                            isinstance(item, dict)):
                        _merge_pod_config(dst[key][i], item)
                    else:
                        dst[key].append(item)
            elif key in ('volumes', 'volumeMounts'):
                by_name = {item['name']: item for item in dst[key]
                           if isinstance(item, dict) and 'name' in item}
                for item in value:
                    name = item.get('name') if isinstance(item, dict) \
                        else None
                    if name is not None and name in by_name:
                        _merge_pod_config(by_name[name], item)
                    else:
                        dst[key].append(
                            json.loads(json.dumps(item)) if isinstance(
                                item, (dict, list)) else item)
            else:
                dst[key].extend(
                    json.loads(json.dumps(item)) if isinstance(
                        item, (dict, list)) else item for item in value)
        else:
            dst[key] = (json.loads(json.dumps(value))
                        if isinstance(value, (dict, list)) else value)


def _cleanup_cluster_pods(client, namespace: str,
                          cluster_name_on_cloud: str) -> None:
    for pod in client.list_pods(namespace,
                                f'{_CLUSTER_LABEL}={cluster_name_on_cloud}'):
        client.delete_pod(namespace, pod['metadata']['name'])


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    """Create the cluster's pods (idempotent per pod name)."""
    client = _client(config.provider_config)
    namespace = _namespace(config.provider_config)
    node_cfg = config.node_config
    num_hosts = int(node_cfg.get('num_hosts', 1))

    existing = {
        p['metadata']['name']
        for p in client.list_pods(namespace,
                                  f'{_CLUSTER_LABEL}={cluster_name_on_cloud}')
    }
    created: List[str] = []
    head_id: Optional[str] = None
    try:
        for i in range(config.count):
            instance_id = f'{cluster_name_on_cloud}-{i}'
            if i == 0:
                head_id = instance_id
            for h in range(num_hosts):
                name = _pod_name(cluster_name_on_cloud, i, h, num_hosts)
                if name in existing:
                    continue
                manifest = _build_manifest(cluster_name_on_cloud, i, h,
                                           node_cfg)
                logger.debug(f'Creating pod {namespace}/{name}')
                client.create_pod(namespace, manifest)
                created.append(name)
    except k8s_api.K8sCapacityError:
        # Failover moves to the next context; pods created here would
        # otherwise squat on this cluster's capacity forever.
        _cleanup_cluster_pods(client, namespace, cluster_name_on_cloud)
        raise
    assert head_id is not None
    return common.ProvisionRecord(provider_name='kubernetes',
                                  region=region,
                                  zone=None,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=[],
                                  created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    import time
    assert provider_config is not None
    client = _client(provider_config)
    namespace = _namespace(provider_config)
    deadline = time.time() + 600
    while True:
        pods = client.list_pods(namespace,
                                f'{_CLUSTER_LABEL}={cluster_name_on_cloud}')
        phases = [
            _PHASE_MAP.get(p.get('status', {}).get('phase'), 'pending')
            for p in pods
        ]
        if pods and all(s == state for s in phases):
            return
        for pod in pods:
            status = pod.get('status', {})
            # A Pending pod the scheduler has rejected is a capacity
            # signal, not a transient: hand it to the failover engine
            # (real clusters; the fake raises at create time).
            for cond in status.get('conditions', []):
                if (cond.get('type') == 'PodScheduled' and
                        cond.get('status') == 'False' and
                        cond.get('reason') == 'Unschedulable'):
                    # Clean up before failing over: the Pending pods would
                    # schedule later and squat on the nodepool.
                    _cleanup_cluster_pods(client, namespace,
                                          cluster_name_on_cloud)
                    raise k8s_api.K8sCapacityError(
                        f'Pod {pod["metadata"]["name"]} unschedulable: '
                        f'{cond.get("message", "")}')
            # Fail fast on terminally-dead pods instead of burning the
            # whole deadline (OOMKill / container exit; restartPolicy is
            # Never).
            if status.get('phase') in ('Failed', 'Succeeded'):
                raise common.ProvisionerError(
                    f'Pod {pod["metadata"]["name"]} reached terminal phase '
                    f'{status.get("phase")} during provisioning.')
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for pods of {cluster_name_on_cloud} to '
                f'reach {state}; current: {phases}')
        time.sleep(2)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    client = _client(provider_config)
    namespace = _namespace(provider_config)
    pods = client.list_pods(namespace,
                            f'{_CLUSTER_LABEL}={cluster_name_on_cloud}')

    def _key(pod) -> tuple:
        labels = pod['metadata'].get('labels', {})
        return (int(labels.get(_NODE_LABEL, 0)),
                int(labels.get(_HOST_LABEL, 0)))

    instances: Dict[str, List[common.InstanceInfo]] = {}
    custom: Dict[str, Any] = {}
    for pod in sorted(pods, key=_key):
        labels = pod['metadata'].get('labels', {})
        node_idx = labels.get(_NODE_LABEL, '0')
        instance_id = f'{cluster_name_on_cloud}-{node_idx}'
        tags = {
            'pod_name': pod['metadata']['name'],
            'namespace': namespace,
        }
        if provider_config.get('context'):
            tags['context'] = provider_config['context']
        # Access mode (parity: the reference's networking_mode):
        # 'kubectl-exec' (default, no sshd needed) or 'portforward-ssh'
        # (sshd in the pod, SSH over kubectl port-forward).
        if provider_config.get('access_mode'):
            tags['access_mode'] = provider_config['access_mode']
        pod_dir = pod['metadata'].get('annotations',
                                      {}).get('skytpu/pod-dir')
        if pod_dir:
            # Fake backend: the pod is a local directory — the runtime
            # drives it through the local transport.
            tags['node_dir'] = pod_dir
        selector = pod.get('spec', {}).get('nodeSelector', {})
        if k8s_api.GKE_TPU_ACCELERATOR_LABEL in selector and not custom:
            custom = {
                'accelerator_type':
                    selector[k8s_api.GKE_TPU_ACCELERATOR_LABEL],
                'topology': selector.get(k8s_api.GKE_TPU_TOPOLOGY_LABEL),
            }
        instances.setdefault(instance_id, []).append(
            common.InstanceInfo(
                instance_id=pod['metadata']['name'],
                internal_ip=pod.get('status', {}).get('podIP', ''),
                external_ip=None,
                tags=tags,
            ))
    head_id = f'{cluster_name_on_cloud}-0' if instances else None
    if head_id is not None and head_id not in instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name='kubernetes',
        provider_config=provider_config,
        custom_metadata=custom,
    )


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    assert provider_config is not None
    client = _client(provider_config)
    namespace = _namespace(provider_config)
    # One status per LOGICAL node (slice), like the GCP provisioner: a
    # multi-host slice is running iff every one of its pods is running.
    per_node: Dict[str, List[str]] = {}
    for pod in client.list_pods(namespace,
                                f'{_CLUSTER_LABEL}={cluster_name_on_cloud}'):
        status = _PHASE_MAP.get(pod.get('status', {}).get('phase'),
                                'pending')
        node_idx = pod['metadata'].get('labels', {}).get(_NODE_LABEL, '0')
        per_node.setdefault(f'{cluster_name_on_cloud}-{node_idx}',
                            []).append(status)
    out: Dict[str, Optional[str]] = {}
    for node_id, statuses in per_node.items():
        if all(s == 'running' for s in statuses):
            agg = 'running'
        elif any(s == 'terminated' for s in statuses):
            agg = 'terminated'
        else:
            agg = 'pending'
        if non_terminated_only and agg == 'terminated':
            continue
        out[node_id] = agg
    return out


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    raise common.ProvisionerError(
        'Kubernetes pods cannot be stopped; only terminated '
        '(parity: the reference marks STOP unsupported on Kubernetes).')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    namespace = _namespace(provider_config)
    for pod in client.list_pods(namespace,
                                f'{_CLUSTER_LABEL}={cluster_name_on_cloud}'):
        labels = pod['metadata'].get('labels', {})
        if worker_only and labels.get(_NODE_LABEL) == '0':
            continue
        client.delete_pod(namespace, pod['metadata']['name'])
    if not worker_only:
        # Drop the cluster's port-exposure service with its pods.
        client.delete_service(namespace,
                              _ports_service_name(cluster_name_on_cloud))


def _ports_service_name(cluster_name_on_cloud: str) -> str:
    return f'{cluster_name_on_cloud}-ports'


def _parse_ports(ports: List[str]) -> List[int]:
    # Delegates to the ONE shared expansion; the provisioner surface
    # keeps its typed error.
    from skypilot_tpu.utils import common_utils
    try:
        out = common_utils.expand_ports(ports)
    except ValueError as e:
        raise common.ProvisionerError(str(e)) from e
    if not out:
        raise common.ProvisionerError(f'No ports parsed from {ports!r}.')
    return out


def _real_open_ports(cluster_name_on_cloud: str, ports: List[str],
                     provider_config: Dict[str, Any]) -> None:
    """Expose ports via ONE NodePort service selecting the cluster's
    head pod (parity: the reference's network_utils NodePort services
    for `ports:`)."""
    client = _client(provider_config)
    namespace = _namespace(provider_config)
    manifest = {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {
            'name': _ports_service_name(cluster_name_on_cloud),
            'labels': {_CLUSTER_LABEL: cluster_name_on_cloud},
        },
        'spec': {
            'type': 'NodePort',
            'selector': {_CLUSTER_LABEL: cluster_name_on_cloud,
                         _NODE_LABEL: '0', _HOST_LABEL: '0'},
            'ports': [{'name': f'port-{p}', 'port': p,
                       'targetPort': p, 'protocol': 'TCP'}
                      for p in _parse_ports(ports)],
        },
    }
    svc = client.create_service(namespace, manifest)
    mapping = {str(p.get('port')): p.get('nodePort')
               for p in svc.get('spec', {}).get('ports', [])}
    logger.info(f'Opened ports on {cluster_name_on_cloud}: '
                f'port→NodePort {mapping}')


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    """NodePort service exposing the head pod's ports (parity:
    sky/provision/kubernetes/network.py NodePort mode)."""
    if not ports:
        return
    assert provider_config is not None
    _real_open_ports(cluster_name_on_cloud, ports, provider_config)


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del ports
    assert provider_config is not None
    client = _client(provider_config)
    client.delete_service(_namespace(provider_config),
                          _ports_service_name(cluster_name_on_cloud))
