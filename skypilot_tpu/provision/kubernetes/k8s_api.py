"""Kubernetes API client (pods + nodes) with a fake backend.

Parity: the reference drives the ``kubernetes`` Python client from
``sky/provision/kubernetes/utils.py`` / ``instance.py``. This build shells
out to ``kubectl`` instead (the Python client is not a baked-in dependency)
and keeps the same two-transport shape as ``provision/gcp/tpu_api.py``:

* :class:`KubectlTransport` — real clusters via the ``kubectl`` binary
  (``-o json`` everywhere).
* :class:`FakeK8sService` — an in-memory cluster with GKE-style TPU
  nodepools, used by tests and when ``SKYTPU_K8S_FAKE=1``. Fake pods are
  backed by local directories so the full runtime (skylet, gang_run, jobs)
  runs against them exactly like the local cloud. Fault injection: set
  ``SKYTPU_K8S_FAKE_UNSCHEDULABLE=1`` to make every pod unschedulable —
  exercising the failover engine.

Cluster capacity discovery (node labels/allocatable) replaces a static
service catalog: a Kubernetes "catalog" is whatever the nodes advertise
(parity: sky/provision/kubernetes/utils.py node-label detectors).
"""
import json
import os
import shutil
import subprocess
import threading
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# GKE TPU node labels (parity: sky/provision/kubernetes/utils.py:96-102).
GKE_TPU_ACCELERATOR_LABEL = 'cloud.google.com/gke-tpu-accelerator'
GKE_TPU_TOPOLOGY_LABEL = 'cloud.google.com/gke-tpu-topology'
# Resource key Kubernetes uses to track Google TPU chips on nodes
# (parity: utils.py TPU_RESOURCE_KEY).
TPU_RESOURCE_KEY = 'google.com/tpu'
GPU_RESOURCE_KEY = 'nvidia.com/gpu'

_FAKE_STATE_ENV = 'SKYTPU_K8S_FAKE_STATE'  # json file for cross-process fakes
_FAKE_ROOT = '~/.skytpu/k8s_fake'

# ---------------------------------------------------------------- auth
# Two auth modes (parity: sky/provision/kubernetes/utils.py:1468-1598
# load_kube_config vs in-cluster service-account resolution):
#
# * kubeconfig — the default: kubectl resolves $KUBECONFIG /
#   ~/.kube/config, optionally pinned with --context.
# * in-cluster — the API server itself runs inside a cluster (helm
#   deployment): auth from the pod's mounted service-account token,
#   addressed by the reserved context name ``in-cluster``.

IN_CLUSTER_CONTEXT = 'in-cluster'
_SA_DIR_ENV = 'SKYTPU_K8S_SA_DIR'  # test override for the mount path
_DEFAULT_SA_DIR = '/var/run/secrets/kubernetes.io/serviceaccount'


def _sa_dir() -> str:
    return os.environ.get(_SA_DIR_ENV, _DEFAULT_SA_DIR)


def in_cluster_available() -> bool:
    """True when a pod service account is mounted AND the apiserver env
    is present — i.e. we are running inside a Kubernetes cluster."""
    d = _sa_dir()
    return (os.environ.get('KUBERNETES_SERVICE_HOST') is not None and
            os.path.isfile(os.path.join(d, 'token')) and
            os.path.isfile(os.path.join(d, 'ca.crt')))


def in_cluster_namespace() -> str:
    """The namespace the service account lives in (defaults to
    'default' when the mount lacks a namespace file)."""
    path = os.path.join(_sa_dir(), 'namespace')
    try:
        with open(path, encoding='utf-8') as f:
            return f.read().strip() or 'default'
    except OSError:
        return 'default'


def _in_cluster_flags() -> List[str]:
    """kubectl flags replacing --context for in-cluster auth.

    The service-account token must NOT ride on argv (`--token` is
    world-readable via /proc/*/cmdline); instead a 0600 kubeconfig
    referencing the token FILE is materialized — kubectl re-reads
    `tokenFile` per request, so projected-token rotation works too.
    """
    if not in_cluster_available():
        raise K8sApiError(
            "Context 'in-cluster' was requested but this process is "
            'not running inside a Kubernetes pod (no service-account '
            'mount / KUBERNETES_SERVICE_HOST). Use a kubeconfig '
            'context instead.')
    d = _sa_dir()
    host = os.environ['KUBERNETES_SERVICE_HOST']
    port = os.environ.get('KUBERNETES_SERVICE_PORT', '443')
    cfg_dir = os.path.join(os.path.expanduser('~'), '.skytpu', 'k8s')
    os.makedirs(cfg_dir, exist_ok=True)
    path = os.path.join(cfg_dir, 'incluster.kubeconfig')
    content = (
        'apiVersion: v1\n'
        'kind: Config\n'
        'clusters:\n'
        '- name: in-cluster\n'
        '  cluster:\n'
        f'    server: https://{host}:{port}\n'
        f'    certificate-authority: {os.path.join(d, "ca.crt")}\n'
        'users:\n'
        '- name: sa\n'
        '  user:\n'
        f'    tokenFile: {os.path.join(d, "token")}\n'
        'contexts:\n'
        '- name: in-cluster\n'
        '  context:\n'
        '    cluster: in-cluster\n'
        '    user: sa\n'
        'current-context: in-cluster\n')
    # Rewrite only on change; always enforce owner-only perms.
    try:
        with open(path, encoding='utf-8') as f:
            current = f.read()
    except OSError:
        current = None
    if current != content:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, 'w', encoding='utf-8') as f:
            f.write(content)
    os.chmod(path, 0o600)
    return ['--kubeconfig', path]


def available_contexts() -> List[str]:
    """kubeconfig contexts plus the in-cluster pseudo-context (parity:
    utils.py:1578 — the reference appends its in-cluster context name
    the same way). Used by `sky check` and multi-context failover."""
    out: List[str] = []
    try:
        proc = subprocess.run(
            ['kubectl', 'config', 'get-contexts', '-o', 'name'],
            capture_output=True, text=True, timeout=30, check=False)
        if proc.returncode == 0:
            out = [l.strip() for l in proc.stdout.splitlines()
                   if l.strip()]
    except (FileNotFoundError, subprocess.TimeoutExpired):
        pass
    if in_cluster_available():
        out.append(IN_CLUSTER_CONTEXT)
    return out


def resolve_context(context: Optional[str]) -> Optional[str]:
    """Auth resolution: an explicit context wins; otherwise use the
    kubeconfig default when one exists, else fall back to in-cluster
    when available (the helm-deployed API server path)."""
    if context:
        return context
    # $KUBECONFIG is a colon-separated path LIST (kubectl merges them);
    # any existing entry means kubeconfig auth wins over in-cluster.
    kubeconfig = os.environ.get('KUBECONFIG',
                                os.path.expanduser('~/.kube/config'))
    for path in kubeconfig.split(os.pathsep):
        if path and os.path.exists(os.path.expanduser(path)):
            return None  # kubectl resolves the current kubeconfig context
    if in_cluster_available():
        return IN_CLUSTER_CONTEXT
    return None


class K8sApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class K8sCapacityError(K8sApiError, provision_common.CapacityError):
    """Pod cannot be scheduled (no node fits) — the failover engine treats
    this like a zonal stockout and tries the next context."""
    scope = 'zone'


class KubectlTransport:
    """Real clusters through the ``kubectl`` binary (kubeconfig or
    in-cluster service-account auth — see module auth notes)."""

    def __init__(self, context: Optional[str] = None):
        self.context = resolve_context(context)

    def _base(self) -> List[str]:
        argv = ['kubectl']
        if self.context == IN_CLUSTER_CONTEXT:
            argv += _in_cluster_flags()
        elif self.context:
            argv += ['--context', self.context]
        return argv

    def _run(self, args: List[str],
             stdin: Optional[str] = None) -> str:
        proc = subprocess.run(self._base() + args,
                              input=stdin,
                              capture_output=True,
                              text=True,
                              timeout=120,
                              check=False)
        if proc.returncode != 0:
            msg = proc.stderr.strip() or proc.stdout.strip()
            lowered = msg.lower()
            if ('insufficient' in lowered or 'unschedulable' in lowered or
                    'exceeded quota' in lowered):
                raise K8sCapacityError(msg)
            raise K8sApiError(f'kubectl {" ".join(args[:3])}: {msg}')
        return proc.stdout

    # ------------------------------------------------------------ surface

    def list_nodes(self) -> List[dict]:
        out = self._run(['get', 'nodes', '-o', 'json'])
        return json.loads(out).get('items', [])

    def create_pod(self, namespace: str, manifest: dict) -> dict:
        self._run(['-n', namespace, 'create', '-f', '-'],
                  stdin=json.dumps(manifest))
        return self.get_pod(namespace, manifest['metadata']['name'])

    def get_pod(self, namespace: str, name: str) -> dict:
        out = self._run(['-n', namespace, 'get', 'pod', name, '-o', 'json'])
        return json.loads(out)

    def list_pods(self, namespace: str,
                  label_selector: Optional[str] = None) -> List[dict]:
        args = ['-n', namespace, 'get', 'pods', '-o', 'json']
        if label_selector:
            args += ['-l', label_selector]
        return json.loads(self._run(args)).get('items', [])

    def delete_pod(self, namespace: str, name: str) -> None:
        try:
            self._run(['-n', namespace, 'delete', 'pod', name,
                       '--ignore-not-found', '--wait=false'])
        except K8sApiError as e:
            logger.debug(f'delete pod {name}: {e}')

    def current_context(self) -> Optional[str]:
        if self.context == IN_CLUSTER_CONTEXT:
            return IN_CLUSTER_CONTEXT
        try:
            return self._run(['config', 'current-context']).strip() or None
        except (K8sApiError, FileNotFoundError):
            return None

    # Services (port exposure — parity: the reference creates NodePort
    # services for `ports:` via network_utils).

    def create_service(self, namespace: str, manifest: dict) -> dict:
        # apply -o json returns the created object (assigned nodePorts
        # included) in one round trip.
        out = self._run(['-n', namespace, 'apply', '-f', '-', '-o',
                         'json'], stdin=json.dumps(manifest))
        return json.loads(out)

    def get_service(self, namespace: str, name: str) -> dict:
        out = self._run(['-n', namespace, 'get', 'service', name, '-o',
                         'json'])
        return json.loads(out)

    def delete_service(self, namespace: str, name: str) -> None:
        try:
            self._run(['-n', namespace, 'delete', 'service', name,
                       '--ignore-not-found', '--wait=false'])
        except K8sApiError as e:
            logger.debug(f'delete service {name}: {e}')


# Default fake cluster: two CPU nodes plus a 4-host v5e-16 TPU podslice
# nodepool (GKE labels as on real GKE TPU nodepools). Override with
# SKYTPU_K8S_FAKE_NODES='{"name": {"labels": {...}, "allocatable": {...}}}'.
_DEFAULT_FAKE_NODES: Dict[str, Dict[str, Any]] = {
    **{
        f'cpu-node-{i}': {
            'labels': {},
            'allocatable': {'cpu': 16, 'memory_gib': 64},
        } for i in range(2)
    },
    **{
        f'tpu-v5e-node-{i}': {
            'labels': {
                GKE_TPU_ACCELERATOR_LABEL: 'tpu-v5-lite-podslice',
                GKE_TPU_TOPOLOGY_LABEL: '4x4',
            },
            'allocatable': {'cpu': 24, 'memory_gib': 48,
                            TPU_RESOURCE_KEY: 4},
        } for i in range(4)
    },
    'gpu-l4-node-0': {
        'labels': {'cloud.google.com/gke-accelerator': 'nvidia-l4'},
        'allocatable': {'cpu': 16, 'memory_gib': 64, GPU_RESOURCE_KEY: 4},
    },
}


class FakeK8sService:
    """In-memory Kubernetes: nodes with GKE TPU labels, schedulable pods.

    State optionally persisted to a JSON file (``SKYTPU_K8S_FAKE_STATE``)
    so separate processes (CLI invocations in tests) see the same cluster.
    Each Running pod is backed by a directory under ``~/.skytpu/k8s_fake``;
    the provisioner exposes it as a local-transport host.
    """

    _lock = threading.Lock()
    _pods: Dict[str, Dict[str, Any]] = {}

    def __init__(self, context: Optional[str] = None):
        self.context = context or 'fake-gke'
        # File-backed by default: CLI/API-server requests run in separate
        # processes and must see one consistent fake cluster.
        self._state_path = os.environ.get(_FAKE_STATE_ENV) or os.path.join(
            os.path.expanduser(_FAKE_ROOT), 'state.json')

    # -------------------------------------------------------- persistence

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding='utf-8') as f:
                return json.load(f)
        return FakeK8sService._pods

    def _save(self, pods: Dict[str, Dict[str, Any]]) -> None:
        if self._state_path:
            os.makedirs(os.path.dirname(self._state_path), exist_ok=True)
            with open(self._state_path, 'w', encoding='utf-8') as f:
                json.dump(pods, f)
        else:
            FakeK8sService._pods = pods

    def _nodes(self) -> Dict[str, Dict[str, Any]]:
        override = os.environ.get('SKYTPU_K8S_FAKE_NODES')
        if override:
            return json.loads(override)
        return _DEFAULT_FAKE_NODES

    # ------------------------------------------------------------ surface

    def list_nodes(self) -> List[dict]:
        out = []
        for name, spec in self._nodes().items():
            alloc = dict(spec.get('allocatable', {}))
            allocatable = {
                'cpu': str(alloc.get('cpu', 0)),
                'memory': f'{alloc.get("memory_gib", 0)}Gi',
            }
            if alloc.get(TPU_RESOURCE_KEY):
                allocatable[TPU_RESOURCE_KEY] = str(alloc[TPU_RESOURCE_KEY])
            if alloc.get(GPU_RESOURCE_KEY):
                allocatable[GPU_RESOURCE_KEY] = str(alloc[GPU_RESOURCE_KEY])
            out.append({
                'metadata': {'name': name,
                             'labels': dict(spec.get('labels', {}))},
                'status': {'allocatable': allocatable},
            })
        return out

    @staticmethod
    def _qty(value) -> float:
        """Parse a Kubernetes quantity ('500m', '16Gi', '4') to a float in
        base units (cpu cores / GiB / count)."""
        s = str(value)
        if s.endswith('m'):
            return float(s[:-1]) / 1000.0
        for suffix, scale in (('Gi', 1.0), ('Mi', 1 / 1024), ('Ki', 2**-20)):
            if s.endswith(suffix):
                return float(s[:-len(suffix)]) * scale
        return float(s)

    def _fits(self, node: Dict[str, Any], selector: Dict[str, str],
              requests: Dict[str, Any],
              used: Dict[str, float]) -> bool:
        labels = node.get('labels', {})
        if any(labels.get(k) != v for k, v in selector.items()):
            return False
        alloc = node.get('allocatable', {})
        cpu_free = self._qty(alloc.get('cpu', 0)) - used.get('cpu', 0.0)
        if requests.get('cpu', 0.0) > cpu_free:
            return False
        for key in (TPU_RESOURCE_KEY, GPU_RESOURCE_KEY):
            free = self._qty(alloc.get(key, 0)) - used.get(key, 0.0)
            if requests.get(key, 0.0) > free:
                return False
        return True

    def _schedule(self, pods: Dict[str, Dict[str, Any]],
                  manifest: dict) -> str:
        """Pick a node for the pod; raise K8sCapacityError if none fits."""
        # Fault injection: '1' = every context unschedulable; a comma
        # list = only those contexts (exercises the multi-context
        # failover chain: ctx-a stocks out, ctx-b takes the pods).
        unsched = os.environ.get('SKYTPU_K8S_FAKE_UNSCHEDULABLE', '0')
        if unsched == '1' or (unsched not in ('0', '') and
                              self.context in unsched.split(',')):
            raise K8sCapacityError(
                f'0/6 nodes are available in context {self.context}: '
                'insufficient capacity (fault injection).')
        spec = manifest.get('spec', {})
        selector = spec.get('nodeSelector', {})
        containers = spec.get('containers', [])
        requests: Dict[str, Any] = {}
        for c in containers:
            for k, v in c.get('resources', {}).get('requests', {}).items():
                requests[k] = requests.get(k, 0) + self._qty(v)
        # Current usage per node.
        usage: Dict[str, Dict[str, float]] = {}
        for pod in pods.values():
            node = pod.get('spec', {}).get('nodeName')
            if not node:
                continue
            per = usage.setdefault(node, {})
            for c in pod.get('spec', {}).get('containers', []):
                for k, v in c.get('resources', {}).get('requests',
                                                       {}).items():
                    per[k] = per.get(k, 0) + self._qty(v)
        for name, node in self._nodes().items():
            if self._fits(node, selector, requests, usage.get(name, {})):
                return name
        raise K8sCapacityError(
            f'0/{len(self._nodes())} nodes are available: insufficient '
            f'{TPU_RESOURCE_KEY if TPU_RESOURCE_KEY in requests else "cpu"} '
            f'for selector {selector}.')

    def create_pod(self, namespace: str, manifest: dict) -> dict:
        with FakeK8sService._lock:
            pods = self._load()
            name = manifest['metadata']['name']
            key = f'{namespace}/{name}'
            if key in pods:
                return pods[key]
            node_name = self._schedule(pods, manifest)
            pod_dir = os.path.join(os.path.expanduser(_FAKE_ROOT),
                                   namespace, name)
            os.makedirs(pod_dir, exist_ok=True)
            pod = json.loads(json.dumps(manifest))  # deep copy
            pod['spec']['nodeName'] = node_name
            pod['status'] = {
                'phase': 'Running',
                'podIP': f'10.244.0.{len(pods) + 2}',
            }
            pod['metadata']['namespace'] = namespace
            # Backing directory: the pod's filesystem for the runtime.
            pod['metadata'].setdefault('annotations',
                                       {})['skytpu/pod-dir'] = pod_dir
            pods[key] = pod
            self._save(pods)
            return pod

    def get_pod(self, namespace: str, name: str) -> dict:
        pods = self._load()
        key = f'{namespace}/{name}'
        if key not in pods:
            raise K8sApiError(f'pods "{name}" not found')
        return pods[key]

    def list_pods(self, namespace: str,
                  label_selector: Optional[str] = None) -> List[dict]:
        pods = self._load()
        # Selector terms: 'k=v' (equality) or bare 'k' (existence) —
        # the two forms kubectl -l accepts that the framework uses.
        wanted: Dict[str, Optional[str]] = {}
        if label_selector:
            for part in label_selector.split(','):
                if '=' in part:
                    k, _, v = part.partition('=')
                    wanted[k] = v
                else:
                    wanted[part] = None  # existence
        out = []
        for key, pod in pods.items():
            if not key.startswith(f'{namespace}/'):
                continue
            labels = pod.get('metadata', {}).get('labels', {})
            if all((k in labels) if v is None else (labels.get(k) == v)
                   for k, v in wanted.items()):
                out.append(pod)
        return out

    def delete_pod(self, namespace: str, name: str) -> None:
        with FakeK8sService._lock:
            pods = self._load()
            pod = pods.pop(f'{namespace}/{name}', None)
            self._save(pods)
        if pod is None:
            return
        pod_dir = pod.get('metadata', {}).get('annotations',
                                              {}).get('skytpu/pod-dir')
        if pod_dir and os.path.isdir(pod_dir):
            # The pod's processes die with the pod, exactly like the local
            # cloud's nodes (reuses the /proc environ sweep).
            from skypilot_tpu.provision.local import instance as local_inst
            local_inst._kill_node_processes(pod_dir)  # pylint: disable=protected-access
            shutil.rmtree(pod_dir, ignore_errors=True)

    def current_context(self) -> Optional[str]:
        return self.context

    # Services: stored under 'svc:{ns}/{name}' keys, disjoint from pod
    # keys by the ':' (pod names are DNS labels, no colons).

    def create_service(self, namespace: str, manifest: dict) -> dict:
        with FakeK8sService._lock:
            pods = self._load()
            name = manifest['metadata']['name']
            svc = json.loads(json.dumps(manifest))
            # Assign NodePorts like a real apiserver would.
            for i, port in enumerate(
                    svc.get('spec', {}).get('ports', [])):
                port.setdefault('nodePort', 30000 + i)
            svc['metadata']['namespace'] = namespace
            pods[f'svc:{namespace}/{name}'] = svc
            self._save(pods)
            return svc

    def get_service(self, namespace: str, name: str) -> dict:
        pods = self._load()
        key = f'svc:{namespace}/{name}'
        if key not in pods:
            raise K8sApiError(f'services "{name}" not found')
        return pods[key]

    def delete_service(self, namespace: str, name: str) -> None:
        with FakeK8sService._lock:
            pods = self._load()
            pods.pop(f'svc:{namespace}/{name}', None)
            self._save(pods)


def make_client(context: Optional[str] = None):
    if os.environ.get('SKYTPU_K8S_FAKE', '0') == '1':
        return FakeK8sService(context)
    return KubectlTransport(context)
