"""Shared scaffolding for zoneless "neocloud" provisioners (Lambda,
RunPod): these APIs have no tags, so cluster membership is encoded in the
instance NAME (``<cluster>-<i>``), and the lifecycle surface reduces to a
client with list/terminate plus per-cloud create/stop verbs.

Keeping the name parsing, polling, and ClusterInfo assembly here means a
fix lands once, not per cloud.
"""
import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common

logger = sky_logging.init_logger(__name__)


def parse_node_index(name: str,
                     cluster_name_on_cloud: str) -> Optional[int]:
    """``<cluster>-<i>`` → i; None when the name is NOT a member.

    Strict integer suffix: a foreign instance named
    ``<cluster>-backup`` must not be adopted as node 0 (it would be
    terminated by ``down``).
    """
    prefix = f'{cluster_name_on_cloud}-'
    if not name.startswith(prefix):
        return None
    suffix = name[len(prefix):]
    try:
        return int(suffix)
    except ValueError:
        return None


def cluster_members(items: List[dict],
                    cluster_name_on_cloud: str) -> List[dict]:
    """Filter + rank-sort API listings down to actual cluster members."""
    return list(
        members_by_index(items, cluster_name_on_cloud).values())


def members_by_index(items: List[dict],
                     cluster_name_on_cloud: str) -> Dict[int, dict]:
    """Rank → member dict (insertion-ordered by rank)."""
    members = []
    for item in items:
        idx = parse_node_index(item['name'], cluster_name_on_cloud)
        if idx is not None:
            members.append((idx, item))
    return dict(sorted(members, key=lambda p: p[0]))


def wait_for_state(list_fn: Callable[[], List[dict]],
                   state_map: Dict[str, str],
                   cluster_name_on_cloud: str,
                   state: str,
                   timeout: float = 600.0,
                   poll: float = 5.0) -> None:
    deadline = time.time() + timeout
    while True:
        items = list_fn()
        states = [state_map.get(i['status'], 'pending') for i in items]
        if items and all(s == state for s in states):
            return
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for {cluster_name_on_cloud} to reach '
                f'{state}; current: {states}')
        time.sleep(poll)


def build_cluster_info(items: List[dict], provider_name: str,
                       provider_config: Dict[str, Any],
                       default_ssh_user: str) -> common.ClusterInfo:
    """Rank-ordered members (from :func:`cluster_members`) → ClusterInfo."""
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    for item in items:
        if head_id is None:  # caller passes rank-sorted members
            head_id = item['id']
        instances[item['id']] = [
            common.InstanceInfo(
                instance_id=item['id'],
                internal_ip=item.get('private_ip', ''),
                external_ip=item.get('ip'),
                tags={'name': item['name']},
            )
        ]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name=provider_name,
        provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', default_ssh_user),
        ssh_private_key=provider_config.get('ssh_private_key'),
    )


def query_statuses(items: List[dict], state_map: Dict[str, str],
                   non_terminated_only: bool) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for item in items:
        status = state_map.get(item['status'], 'pending')
        if non_terminated_only and status == 'terminated':
            continue
        out[item['id']] = status
    return out


def make_lifecycle(provider_name: str,
                   make_client: Callable[[Optional[str]], Any],
                   state_map: Dict[str, str], capacity_error: type,
                   default_ssh_user: str,
                   supports_stop: bool = True) -> Dict[str, Callable]:
    """Full PROVISIONER_SURFACE for a name-membership REST cloud.

    ``make_client(region)`` builds the API client — region-scoped APIs
    (OCI) use it, global ones ignore it. The client must expose:
    ``deploy(name, region, instance_type, use_spot, public_key) -> id``,
    ``list() -> [normalized dicts]``, ``stop(id)``, ``start(id)``,
    ``terminate(id)``. Clouds with quirks (Lambda's no-stop + SSH-key
    registry, RunPod's pod bodies) keep hand-written modules; the
    uniform ones (DigitalOcean, Fluidstack, Vast, OCI, Nebius,
    Paperspace, Cudo) use this factory so the lifecycle logic exists
    once.
    """

    def _client_for(region: Optional[str],
                    provider_config: Optional[Dict[str, Any]] = None):
        if region is None and provider_config:
            region = provider_config.get('region')
        return make_client(region)

    def _live_members(client, cluster_name_on_cloud: str) -> List[dict]:
        return [
            m for m in cluster_members(client.list(), cluster_name_on_cloud)
            if state_map.get(m['status']) not in ('terminating',
                                                 'terminated')
        ]

    def run_instances(region, cluster_name_on_cloud, config):
        client = _client_for(region, config.provider_config)
        existing = _live_members(client, cluster_name_on_cloud)
        by_index = members_by_index(existing, cluster_name_on_cloud)
        created: List[str] = []
        resumed: List[str] = []
        try:
            for i in range(config.count):
                member = by_index.get(i)
                if member is not None:
                    if state_map.get(member['status']) == 'stopped':
                        if not config.resume_stopped_nodes:
                            raise common.ProvisionerError(
                                f'Node {i} of {cluster_name_on_cloud} is '
                                'stopped and resume_stopped_nodes is '
                                'False; start the cluster instead.')
                        client.start(member['id'])
                        resumed.append(member['id'])
                    continue
                iid = client.deploy(
                    name=f'{cluster_name_on_cloud}-{i}',
                    region=region,
                    instance_type=config.node_config['instance_type'],
                    use_spot=config.node_config.get('use_spot', False),
                    public_key=config.authentication_config.get(
                        'ssh_public_key'))
                created.append(iid)
        except capacity_error:
            # Partial creates bill until rolled back; best-effort per
            # node so NOTHING (API errors, curl timeouts, bad JSON) can
            # mask the capacity error the failover engine classifies.
            for iid in created:
                try:
                    client.terminate(iid)
                except Exception as exc:  # pylint: disable=broad-except
                    logger.warning(
                        f'Rollback terminate of {iid} failed: {exc}')
            for iid in resumed:
                try:
                    client.stop(iid)
                except Exception as exc:  # pylint: disable=broad-except
                    logger.warning(f'Rollback stop of {iid} failed: {exc}')
            raise
        head = by_index.get(0)
        head_id = head['id'] if head is not None else (
            created[0] if created else None)
        assert head_id is not None
        return common.ProvisionRecord(provider_name=provider_name,
                                      region=region,
                                      zone=None,
                                      cluster_name=cluster_name_on_cloud,
                                      head_instance_id=head_id,
                                      resumed_instance_ids=resumed,
                                      created_instance_ids=created)

    def wait_instances(region, cluster_name_on_cloud, state='running',
                       provider_config=None):
        client = _client_for(region, provider_config)
        wait_for_state(
            lambda: _live_members(client, cluster_name_on_cloud),
            state_map, cluster_name_on_cloud, state)

    def get_cluster_info(region, cluster_name_on_cloud,
                         provider_config=None):
        assert provider_config is not None
        client = _client_for(region, provider_config)
        return build_cluster_info(
            _live_members(client, cluster_name_on_cloud), provider_name,
            provider_config, default_ssh_user=default_ssh_user)

    def query_instances(cluster_name_on_cloud, provider_config=None,
                        non_terminated_only=True):
        client = _client_for(None, provider_config)
        return query_statuses(
            cluster_members(client.list(), cluster_name_on_cloud),
            state_map, non_terminated_only)

    def _ids(client, cluster_name_on_cloud: str,
             worker_only: bool) -> List[str]:
        return [
            m['id']
            for m in _live_members(client, cluster_name_on_cloud)
            if not (worker_only and parse_node_index(
                m['name'], cluster_name_on_cloud) == 0)
        ]

    def stop_instances(cluster_name_on_cloud, provider_config=None,
                       worker_only=False):
        if not supports_stop:
            from skypilot_tpu import exceptions
            raise exceptions.NotSupportedError(
                f'{provider_name} instances cannot be stopped — only '
                'terminated.')
        client = _client_for(None, provider_config)
        for iid in _ids(client, cluster_name_on_cloud, worker_only):
            client.stop(iid)

    def terminate_instances(cluster_name_on_cloud, provider_config=None,
                            worker_only=False):
        client = _client_for(None, provider_config)
        for iid in _ids(client, cluster_name_on_cloud, worker_only):
            client.terminate(iid)

    def open_ports(cluster_name_on_cloud, ports, provider_config=None):
        logger.debug(f'open_ports({cluster_name_on_cloud}, {ports})')

    def cleanup_ports(cluster_name_on_cloud, ports, provider_config=None):
        logger.debug(f'cleanup_ports({cluster_name_on_cloud}, {ports})')

    return {
        'run_instances': run_instances,
        'wait_instances': wait_instances,
        'get_cluster_info': get_cluster_info,
        'query_instances': query_instances,
        'stop_instances': stop_instances,
        'terminate_instances': terminate_instances,
        'open_ports': open_ports,
        'cleanup_ports': cleanup_ports,
    }
