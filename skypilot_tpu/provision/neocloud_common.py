"""Shared scaffolding for zoneless "neocloud" provisioners (Lambda,
RunPod): these APIs have no tags, so cluster membership is encoded in the
instance NAME (``<cluster>-<i>``), and the lifecycle surface reduces to a
client with list/terminate plus per-cloud create/stop verbs.

Keeping the name parsing, polling, and ClusterInfo assembly here means a
fix lands once, not per cloud.
"""
import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.provision import common


def parse_node_index(name: str,
                     cluster_name_on_cloud: str) -> Optional[int]:
    """``<cluster>-<i>`` → i; None when the name is NOT a member.

    Strict integer suffix: a foreign instance named
    ``<cluster>-backup`` must not be adopted as node 0 (it would be
    terminated by ``down``).
    """
    prefix = f'{cluster_name_on_cloud}-'
    if not name.startswith(prefix):
        return None
    suffix = name[len(prefix):]
    try:
        return int(suffix)
    except ValueError:
        return None


def cluster_members(items: List[dict],
                    cluster_name_on_cloud: str) -> List[dict]:
    """Filter + rank-sort API listings down to actual cluster members."""
    return list(
        members_by_index(items, cluster_name_on_cloud).values())


def members_by_index(items: List[dict],
                     cluster_name_on_cloud: str) -> Dict[int, dict]:
    """Rank → member dict (insertion-ordered by rank)."""
    members = []
    for item in items:
        idx = parse_node_index(item['name'], cluster_name_on_cloud)
        if idx is not None:
            members.append((idx, item))
    return dict(sorted(members, key=lambda p: p[0]))


def wait_for_state(list_fn: Callable[[], List[dict]],
                   state_map: Dict[str, str],
                   cluster_name_on_cloud: str,
                   state: str,
                   timeout: float = 600.0,
                   poll: float = 5.0) -> None:
    deadline = time.time() + timeout
    while True:
        items = list_fn()
        states = [state_map.get(i['status'], 'pending') for i in items]
        if items and all(s == state for s in states):
            return
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for {cluster_name_on_cloud} to reach '
                f'{state}; current: {states}')
        time.sleep(poll)


def build_cluster_info(items: List[dict], provider_name: str,
                       provider_config: Dict[str, Any],
                       default_ssh_user: str) -> common.ClusterInfo:
    """Rank-ordered members (from :func:`cluster_members`) → ClusterInfo."""
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    for item in items:
        if head_id is None:  # caller passes rank-sorted members
            head_id = item['id']
        instances[item['id']] = [
            common.InstanceInfo(
                instance_id=item['id'],
                internal_ip=item.get('private_ip', ''),
                external_ip=item.get('ip'),
                tags={'name': item['name']},
            )
        ]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name=provider_name,
        provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', default_ssh_user),
        ssh_private_key=provider_config.get('ssh_private_key'),
    )


def query_statuses(items: List[dict], state_map: Dict[str, str],
                   non_terminated_only: bool) -> Dict[str, Optional[str]]:
    out: Dict[str, Optional[str]] = {}
    for item in items:
        status = state_map.get(item['status'], 'pending')
        if non_terminated_only and status == 'terminated':
            continue
        out[item['id']] = status
    return out
