"""Paperspace API client (parity: ``sky/provision/paperspace/utils.py``).

curl against ``https://api.paperspace.com/v1`` (Bearer key from
$PAPERSPACE_API_KEY or ~/.paperspace/config.json), or the shared fake
when ``SKYTPU_PAPERSPACE_FAKE=1``.
"""
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake
from skypilot_tpu.provision import rest_transport

_API_URL = 'https://api.paperspace.com/v1'

STATE_MAP = {
    'provisioning': 'pending',
    'starting': 'pending',
    'ready': 'running',
    'stopping': 'stopping',
    'off': 'stopped',
    'releasing': 'terminating',
    'released': 'terminated',
    'running': 'running',
    'stopped': 'stopped',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('out of stock', 'no machines available',
                     'insufficient capacity')


class PaperspaceApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class PaperspaceCapacityError(PaperspaceApiError,
                              provision_common.CapacityError):
    """Region out of the requested machine type."""


def api_key() -> Optional[str]:
    key = os.environ.get('PAPERSPACE_API_KEY')
    if key:
        return key
    path = os.path.expanduser('~/.paperspace/config.json')
    if os.path.exists(path):
        try:
            with open(path, encoding='utf-8') as f:
                return json.load(f).get('apiKey') or None
        except (json.JSONDecodeError, OSError):
            return None
    return None


class RestTransport:
    """Real Paperspace through curl + the REST API."""

    def __init__(self, key: str):
        self.key = key

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        def classify(o: dict) -> None:
            if o.get('error'):
                msg = str(o.get('message', o['error']))
                if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                    raise PaperspaceCapacityError(msg)
                raise PaperspaceApiError(msg)

        return rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}',
            f'header = "Authorization: Bearer {self.key}"\n', body,
            api_error=PaperspaceApiError, classify=classify)

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del use_spot  # no spot market (gated at the cloud level)
        body: Dict[str, Any] = {
            'name': name,
            'region': region,
            'machineType': instance_type,
            'templateId': 'ubuntu-22.04',
            'diskSize': 100,
        }
        if public_key:
            # Startup scripts run as root; the key must land in the
            # 'paperspace' login user's authorized_keys, not /root/.ssh.
            body['startupScript'] = (
                'mkdir -p /home/paperspace/.ssh && '
                f'echo {json.dumps(public_key)} >> '
                '/home/paperspace/.ssh/authorized_keys && '
                'chown -R paperspace:paperspace /home/paperspace/.ssh && '
                'chmod 700 /home/paperspace/.ssh')
        out = self._run('POST', '/machines', body)
        machine_id = out.get('id') or out.get('data', {}).get('id')
        if not machine_id:
            raise PaperspaceApiError(
                f'Machine create returned no id: {out!r}')
        return str(machine_id)

    def list(self) -> List[Dict[str, Any]]:
        out = self._run('GET', '/machines')
        items = out if isinstance(out, list) else out.get('items', [])
        return [{
            'id': str(m['id']),
            'name': m.get('name', ''),
            'instance_type': m.get('machineType', ''),
            'region': m.get('region', ''),
            'status': m.get('state', 'provisioning'),
            'ip': m.get('publicIp'),
            'private_ip': m.get('privateIp', ''),
        } for m in items]

    def stop(self, iid: str) -> None:
        self._run('PATCH', f'/machines/{iid}/stop')

    def start(self, iid: str) -> None:
        self._run('PATCH', f'/machines/{iid}/start')

    def terminate(self, iid: str) -> None:
        self._run('DELETE', f'/machines/{iid}')


def make_client(region=None):
    del region  # global API
    if neocloud_fake.fake_enabled('PAPERSPACE'):
        return neocloud_fake.FakeNeoClient(
            'PAPERSPACE', lambda region: PaperspaceCapacityError(
                f'Out of stock in {region}. (fake)'))
    key = api_key()
    if key is None:
        raise PaperspaceApiError('No Paperspace API key configured.')
    return RestTransport(key)
