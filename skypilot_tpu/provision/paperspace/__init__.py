"""Paperspace machine provisioner (parity: ``sky/provision/paperspace/``)."""
from skypilot_tpu.provision.paperspace.instance import cleanup_ports
from skypilot_tpu.provision.paperspace.instance import get_cluster_info
from skypilot_tpu.provision.paperspace.instance import open_ports
from skypilot_tpu.provision.paperspace.instance import query_instances
from skypilot_tpu.provision.paperspace.instance import run_instances
from skypilot_tpu.provision.paperspace.instance import stop_instances
from skypilot_tpu.provision.paperspace.instance import terminate_instances
from skypilot_tpu.provision.paperspace.instance import wait_instances
