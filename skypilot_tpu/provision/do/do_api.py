"""DigitalOcean API client (parity: ``sky/provision/do/utils.py``).

Two transports: curl against ``https://api.digitalocean.com/v2`` (Bearer
token from $DIGITALOCEAN_TOKEN or doctl's config), or the shared
:class:`~skypilot_tpu.provision.neocloud_fake.FakeNeoClient` when
``SKYTPU_DO_FAKE=1``. Normalized instance dicts per
``neocloud_common.make_lifecycle``.
"""
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import neocloud_fake
from skypilot_tpu.provision import rest_transport

_API_URL = 'https://api.digitalocean.com/v2'

# Droplet statuses → uniform vocabulary; the fake's normalized statuses
# map to themselves.
STATE_MAP = {
    'new': 'pending',
    'active': 'running',
    'off': 'stopped',
    'archive': 'terminated',
    'running': 'running',
    'stopped': 'stopped',
    'terminated': 'terminated',
}

_CAPACITY_MARKERS = ('is currently unavailable', 'droplet limit',
                     'out of capacity')


class DoApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class DoCapacityError(DoApiError, provision_common.CapacityError):
    """Region cannot serve the size. Zoneless: scope = region."""


def api_token() -> Optional[str]:
    token = os.environ.get('DIGITALOCEAN_TOKEN')
    if token:
        return token
    path = os.path.expanduser('~/.config/doctl/config.yaml')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            for line in f:
                if line.strip().startswith('access-token:'):
                    return line.split(':', 1)[1].strip().strip('"')
    return None


class RestTransport:
    """Real DigitalOcean through curl + the REST API."""

    def __init__(self, token: str):
        self.token = token

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> Any:
        def classify(o: dict) -> None:
            if o.get('message') and o.get('id'):
                msg = str(o['message'])
                if any(m in msg.lower() for m in _CAPACITY_MARKERS):
                    raise DoCapacityError(msg)
                raise DoApiError(msg)

        return rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}',
            f'header = "Authorization: Bearer {self.token}"\n', body,
            api_error=DoApiError, classify=classify)

    def deploy(self, name: str, region: str, instance_type: str,
               use_spot: bool, public_key: Optional[str]) -> str:
        del use_spot  # DO has no spot market (gated at the cloud level)
        body: Dict[str, Any] = {
            'name': name,
            'region': region,
            'size': instance_type,
            'image': 'ubuntu-22-04-x64',
        }
        if public_key:
            body['user_data'] = ('#cloud-config\nssh_authorized_keys:\n'
                                 f'  - {public_key}\n')
        out = self._run('POST', '/droplets', body)
        return str(out['droplet']['id'])

    def list(self) -> List[Dict[str, Any]]:
        out = self._run('GET', '/droplets?per_page=200')
        droplets = []
        for d in out.get('droplets', []):
            v4 = d.get('networks', {}).get('v4', [])
            pub = next((n['ip_address'] for n in v4
                        if n.get('type') == 'public'), None)
            priv = next((n['ip_address'] for n in v4
                         if n.get('type') == 'private'), '')
            droplets.append({
                'id': str(d['id']),
                'name': d.get('name', ''),
                'instance_type': d.get('size_slug', ''),
                'region': d.get('region', {}).get('slug', ''),
                'status': d.get('status', 'new'),
                'ip': pub,
                'private_ip': priv,
            })
        return droplets

    def _action(self, iid: str, action_type: str) -> None:
        self._run('POST', f'/droplets/{iid}/actions',
                  {'type': action_type})

    def stop(self, iid: str) -> None:
        self._action(iid, 'power_off')

    def start(self, iid: str) -> None:
        self._action(iid, 'power_on')

    def terminate(self, iid: str) -> None:
        self._run('DELETE', f'/droplets/{iid}')


def make_client(region=None):
    del region  # global API
    if neocloud_fake.fake_enabled('DO'):
        return neocloud_fake.FakeNeoClient(
            'DO', lambda region: DoCapacityError(
                f'size is currently unavailable in {region}. (fake)'))
    token = api_token()
    if token is None:
        raise DoApiError('No DigitalOcean token configured.')
    return RestTransport(token)
