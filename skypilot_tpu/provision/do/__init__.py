"""DigitalOcean droplet provisioner (parity: ``sky/provision/do/``)."""
from skypilot_tpu.provision.do.instance import cleanup_ports
from skypilot_tpu.provision.do.instance import get_cluster_info
from skypilot_tpu.provision.do.instance import open_ports
from skypilot_tpu.provision.do.instance import query_instances
from skypilot_tpu.provision.do.instance import run_instances
from skypilot_tpu.provision.do.instance import stop_instances
from skypilot_tpu.provision.do.instance import terminate_instances
from skypilot_tpu.provision.do.instance import wait_instances
