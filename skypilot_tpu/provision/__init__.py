"""Provision router: dispatch lifecycle calls to per-cloud modules.

Parity: ``sky/provision/__init__.py:37-197`` (@_route_to_cloud_impl).
"""
import functools
import importlib
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common

_PROVIDER_MODULES = {
    'aws': 'skypilot_tpu.provision.aws',
    'azure': 'skypilot_tpu.provision.azure',
    'cudo': 'skypilot_tpu.provision.cudo',
    'do': 'skypilot_tpu.provision.do',
    'fluidstack': 'skypilot_tpu.provision.fluidstack',
    'gcp': 'skypilot_tpu.provision.gcp',
    'ibm': 'skypilot_tpu.provision.ibm',
    'kubernetes': 'skypilot_tpu.provision.kubernetes',
    'lambda': 'skypilot_tpu.provision.lambda_cloud',
    'local': 'skypilot_tpu.provision.local',
    'nebius': 'skypilot_tpu.provision.nebius',
    'oci': 'skypilot_tpu.provision.oci',
    'paperspace': 'skypilot_tpu.provision.paperspace',
    'runpod': 'skypilot_tpu.provision.runpod',
    'scp': 'skypilot_tpu.provision.scp',
    'vast': 'skypilot_tpu.provision.vast',
    'vsphere': 'skypilot_tpu.provision.vsphere',
}


def has_provisioner(provider_name: str) -> bool:
    """Whether this build can actually create instances on the cloud.

    Catalog-only clouds (none currently — Azure gained a provisioner)
    are rankable by the optimizer but must be rejected BEFORE any
    cluster records are written.
    """
    return provider_name.lower() in _PROVIDER_MODULES


def _get_module(provider_name: str):
    key = provider_name.lower()
    if key not in _PROVIDER_MODULES:
        from skypilot_tpu import exceptions
        raise exceptions.NotSupportedError(
            f'{provider_name} has no instance provisioner in this build '
            f'(provisioners exist for: {sorted(_PROVIDER_MODULES)}). '
            'Pick a different cloud, or pin resources to a supported one.')
    return importlib.import_module(_PROVIDER_MODULES[key])


def _route(fn_name: str):

    def call(provider_name: str, *args, **kwargs):
        module = _get_module(provider_name)
        impl = getattr(module, fn_name, None)
        if impl is None:
            raise NotImplementedError(
                f'{provider_name} provisioner does not implement {fn_name}')
        return impl(*args, **kwargs)

    call.__name__ = fn_name
    return call


# Uniform provisioner surface (parity: run/stop/terminate/wait/open_ports/
# get_cluster_info dispatchers). Single source of truth: the conformance
# test asserts every provider module implements all of this set.
PROVISIONER_SURFACE = (
    'run_instances',
    'stop_instances',
    'terminate_instances',
    'wait_instances',
    'get_cluster_info',
    'query_instances',
    'open_ports',
    'cleanup_ports',
)

for _fn in PROVISIONER_SURFACE:
    globals()[_fn] = _route(_fn)
del _fn
