"""Local provisioner: nodes are directories + local processes.

The credential-free analogue of the reference's `sky local up` kind cluster:
lets the entire stack (provision → setup → skylet → exec → logs → autostop)
run end-to-end on one machine, which is how the test suite exercises the
backend (SURVEY §4 "Multi-node without a real cluster").
"""
from skypilot_tpu.provision.local.instance import cleanup_ports
from skypilot_tpu.provision.local.instance import get_cluster_info
from skypilot_tpu.provision.local.instance import open_ports
from skypilot_tpu.provision.local.instance import query_instances
from skypilot_tpu.provision.local.instance import run_instances
from skypilot_tpu.provision.local.instance import stop_instances
from skypilot_tpu.provision.local.instance import terminate_instances
from skypilot_tpu.provision.local.instance import wait_instances

__all__ = [
    'cleanup_ports', 'get_cluster_info', 'open_ports', 'query_instances',
    'run_instances', 'stop_instances', 'terminate_instances',
    'wait_instances'
]
