"""Local node lifecycle: a node = a directory with a state file.

Teardown semantics mirror a real cloud: stopping/terminating a "node" kills
every process running on it (skylet daemon, job runners, gang ranks, task
children) — the analogue of the VM going away. Node processes are found by
scanning ``/proc/*/environ`` for ``SKYTPU_SKYLET_HOME``/``HOME`` pointing
inside the node dir (every process the runtime spawns on a local node
carries one of these; see ``gang_run._make_argv`` and
``command_runner.LocalProcessRunner``).
"""
import json
import os
import shutil
import signal
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common

CLUSTER_ROOT = '~/.skytpu/local_cluster'


def _self_matches(cluster_dir: str) -> bool:
    """Does the CURRENT process live on one of this cluster's nodes?

    True when a node's own skylet drives the teardown (autostop down) —
    it must self-exit after sweeping its peers, or it would survive its
    "VM" being terminated.
    """
    cluster_dir = os.path.realpath(cluster_dir)
    home = os.environ.get('SKYTPU_SKYLET_HOME') or os.environ.get('HOME', '')
    home = os.path.realpath(home) if home else ''
    return home == cluster_dir or home.startswith(cluster_dir + os.sep)


def _find_node_pids(cluster_dir: str,
                    workers_only: bool = False) -> List[int]:
    """PIDs of processes whose home env points inside cluster_dir."""
    cluster_dir = os.path.realpath(cluster_dir)
    head_dir = os.path.join(cluster_dir, 'node-0').encode()
    needles = (f'SKYTPU_SKYLET_HOME={cluster_dir}'.encode(),
               f'HOME={cluster_dir}'.encode(),
               f'SKYTPU_NODE_DIR={cluster_dir}'.encode())
    pids: List[int] = []
    me = os.getpid()
    for entry in os.listdir('/proc'):
        if not entry.isdigit() or int(entry) == me:
            continue
        try:
            with open(f'/proc/{entry}/environ', 'rb') as f:
                environ = f.read()
        except OSError:
            continue
        for var in environ.split(b'\0'):
            if any(var == n or var.startswith(n + b'/') for n in needles):
                if workers_only and head_dir in var:
                    break
                pids.append(int(entry))
                break
    return pids


def _kill_node_processes(cluster_dir: str,
                         workers_only: bool = False) -> None:
    """SIGTERM → short grace → SIGKILL every process of this cluster's
    nodes, so teardown never leaks skylet/gang_run/task trees."""
    pids = _find_node_pids(cluster_dir, workers_only=workers_only)
    for sig, grace in ((signal.SIGTERM, 1.0), (signal.SIGKILL, 0.5)):
        if not pids:
            return
        for pid in pids:
            try:
                os.kill(pid, sig)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.time() + grace
        while pids and time.time() < deadline:
            pids = [p for p in pids if os.path.exists(f'/proc/{p}')]
            if pids:
                time.sleep(0.05)


def _cluster_dir(cluster_name_on_cloud: str) -> str:
    # Inside a node (skylet autostop/self-down), $HOME IS the node dir,
    # so the ~-relative root would resolve to a path that doesn't exist
    # and every lifecycle call would silently no-op. The node's own
    # position (SKYTPU_NODE_DIR = <root>/<cluster>/node-N) locates the
    # real cluster dir.
    node_dir = os.environ.get('SKYTPU_NODE_DIR', '').rstrip('/')
    if node_dir:
        cand = os.path.dirname(node_dir)
        if os.path.basename(cand) == cluster_name_on_cloud:
            return cand
    return os.path.expanduser(
        os.path.join(CLUSTER_ROOT, cluster_name_on_cloud))


def _state_path(cluster_name_on_cloud: str) -> str:
    return os.path.join(_cluster_dir(cluster_name_on_cloud), 'state.json')


def _load_state(cluster_name_on_cloud: str) -> Dict[str, str]:
    path = _state_path(cluster_name_on_cloud)
    if not os.path.exists(path):
        return {}
    with open(path, encoding='utf-8') as f:
        return json.load(f)


def _save_state(cluster_name_on_cloud: str, state: Dict[str, str]) -> None:
    os.makedirs(_cluster_dir(cluster_name_on_cloud), exist_ok=True)
    with open(_state_path(cluster_name_on_cloud), 'w',
              encoding='utf-8') as f:
        json.dump(state, f)


class LocalCapacityError(common.CapacityError):
    """Injected stockout (see SKYTPU_LOCAL_PROVISION_FAIL_FILE)."""
    scope = 'zone'


def _maybe_inject_capacity_failure() -> None:
    """Fault injection for recovery/failover tests: if
    ``SKYTPU_LOCAL_PROVISION_FAIL_FILE`` names a file holding an integer
    N > 0, decrement it and raise a zonal stockout. The file (not an env
    count) makes the budget shared across the controller's spawned
    processes and lets a test arm failures mid-run."""
    path = os.environ.get('SKYTPU_LOCAL_PROVISION_FAIL_FILE')
    if not path:
        return
    try:
        with open(path, encoding='utf-8') as f:
            remaining = int(f.read().strip() or '0')
    except (OSError, ValueError):
        return
    if remaining <= 0:
        return
    with open(path, 'w', encoding='utf-8') as f:
        f.write(str(remaining - 1))
    raise LocalCapacityError(
        f'injected local stockout ({remaining - 1} left)')


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    _maybe_inject_capacity_failure()
    state = _load_state(cluster_name_on_cloud)
    created, resumed = [], []
    for i in range(config.count):
        node_id = f'{cluster_name_on_cloud}-{i}'
        node_dir = os.path.join(_cluster_dir(cluster_name_on_cloud),
                                f'node-{i}')
        os.makedirs(node_dir, exist_ok=True)
        prev = state.get(node_id)
        if prev == 'running':
            continue
        if prev == 'stopped':
            resumed.append(node_id)
        else:
            created.append(node_id)
        state[node_id] = 'running'
    _save_state(cluster_name_on_cloud, state)
    return common.ProvisionRecord(provider_name='local',
                                  region='local',
                                  zone='local-a',
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=f'{cluster_name_on_cloud}-0',
                                  resumed_instance_ids=resumed,
                                  created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    del region, cluster_name_on_cloud, state, provider_config  # instant


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    state = _load_state(cluster_name_on_cloud)
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    for node_id in sorted(state):
        if state[node_id] != 'running':
            continue
        idx = int(node_id.rsplit('-', 1)[1])
        node_dir = os.path.join(_cluster_dir(cluster_name_on_cloud),
                                f'node-{idx}')
        if head_id is None or idx == 0:
            head_id = node_id
        instances[node_id] = [
            common.InstanceInfo(instance_id=node_id,
                                internal_ip=node_dir,
                                external_ip=None,
                                # One local node = one "slice" (matching
                                # GCP, where a TPU node IS a slice): a
                                # num_nodes>1 local cluster is the
                                # multislice test double — gang envs get
                                # per-slice worker ids + MEGASCALE.
                                tags={'node_dir': node_dir,
                                      'slice_index': str(idx)})
        ]
    return common.ClusterInfo(instances=instances,
                              head_instance_id=head_id,
                              provider_name='local',
                              provider_config=provider_config or {},
                              ssh_user=os.environ.get('USER', 'root'))


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    state = _load_state(cluster_name_on_cloud)
    return dict(state)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    state = _load_state(cluster_name_on_cloud)
    for node_id in state:
        if worker_only and node_id.endswith('-0'):
            continue
        state[node_id] = 'stopped'
    _save_state(cluster_name_on_cloud, state)
    # A stopped node's processes die with the "VM".
    cluster_dir = _cluster_dir(cluster_name_on_cloud)
    _kill_node_processes(cluster_dir, workers_only=worker_only)
    if not worker_only and _self_matches(cluster_dir):
        os._exit(0)  # the calling skylet's own node just "stopped"


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    if worker_only:
        state = _load_state(cluster_name_on_cloud)
        for node_id in list(state):
            if not node_id.endswith('-0'):
                state.pop(node_id)
        _save_state(cluster_name_on_cloud, state)
        _kill_node_processes(_cluster_dir(cluster_name_on_cloud),
                             workers_only=True)
        return
    cluster_dir = _cluster_dir(cluster_name_on_cloud)
    _kill_node_processes(cluster_dir)
    shutil.rmtree(cluster_dir, ignore_errors=True)
    if _self_matches(cluster_dir):
        os._exit(0)  # autostop-down from this node's own skylet


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    pass


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    pass
