"""Lambda Cloud provisioner (parity: ``sky/provision/lambda_cloud/``)."""
from skypilot_tpu.provision.lambda_cloud.instance import cleanup_ports
from skypilot_tpu.provision.lambda_cloud.instance import get_cluster_info
from skypilot_tpu.provision.lambda_cloud.instance import open_ports
from skypilot_tpu.provision.lambda_cloud.instance import query_instances
from skypilot_tpu.provision.lambda_cloud.instance import run_instances
from skypilot_tpu.provision.lambda_cloud.instance import stop_instances
from skypilot_tpu.provision.lambda_cloud.instance import terminate_instances
from skypilot_tpu.provision.lambda_cloud.instance import wait_instances
