"""Lambda Cloud API client with a fake backend.

Parity: the reference wraps the Lambda REST API in
``sky/provision/lambda_cloud/lambda_utils.py``; same two-transport shape
as ``provision/aws/ec2_api.py``:

* :class:`RestTransport` — real API via curl against
  ``https://cloud.lambdalabs.com/api/v1`` (no vendor SDK needed).
* :class:`FakeLambdaService` — in-memory instances, used by tests and
  when ``SKYTPU_LAMBDA_FAKE=1``. Fault injection:
  ``SKYTPU_LAMBDA_FAKE_STOCKOUT='us-east-1,...'`` makes launch in those
  regions raise ``insufficient-capacity``.

Both transports normalize instances to::

    {'id', 'name', 'instance_type', 'region', 'status', 'ip',
     'private_ip'}

Lambda statuses: booting | active | terminating | terminated. There is
no stop state — instances only run or die.
"""
import json
import os
import subprocess
import threading
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common as provision_common
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import rest_transport

logger = sky_logging.init_logger(__name__)

_FAKE_STATE_ENV = 'SKYTPU_LAMBDA_FAKE_STATE'
_API_URL = 'https://cloud.lambdalabs.com/api/v1'


class LambdaApiError(Exception):

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class LambdaCapacityError(LambdaApiError, provision_common.CapacityError):
    """Region out of capacity. Lambda has no zones, so the inherited
    ``CapacityError.scope = 'region'`` default is exactly right — the
    failover engine blocklists the whole region."""


def _is_capacity_code(code: str) -> bool:
    # Exact API error codes (https://cloud.lambdalabs.com/api/v1/docs):
    # launch returns instance-operations/launch/insufficient-capacity.
    return 'insufficient-capacity' in code.lower()


class RestTransport:
    """Real Lambda Cloud through curl + the REST API."""

    def __init__(self, api_key: str):
        self.api_key = api_key

    @staticmethod
    def _classify(out: dict) -> None:
        """Marker check for Lambda error bodies (200 or 4xx alike)."""
        if 'error' in out:
            err = out['error']
            code = err.get('code', '') if isinstance(err, dict) else \
                str(err)
            msg = err.get('message', code) if isinstance(err, dict) else \
                code
            if _is_capacity_code(code):
                raise LambdaCapacityError(msg)
            raise LambdaApiError(msg)

    def _run(self, method: str, path: str,
             body: Optional[dict] = None) -> dict:
        out = rest_transport.classified_curl_json(
            method, f'{_API_URL}{path}', f'user = "{self.api_key}:"\n',
            body, api_error=LambdaApiError, classify=self._classify)
        return out.get('data', out)

    def launch(self, name: str, region: str, instance_type: str,
               ssh_key_names: List[str]) -> str:
        data = self._run(
            'POST', '/instance-operations/launch', {
                'region_name': region,
                'instance_type_name': instance_type,
                'ssh_key_names': ssh_key_names,
                'quantity': 1,
                'name': name,
            })
        return data['instance_ids'][0]

    def list_instances(self) -> List[Dict[str, Any]]:
        data = self._run('GET', '/instances')
        return [{
            'id': inst['id'],
            'name': inst.get('name', ''),
            'instance_type': inst.get('instance_type',
                                      {}).get('name', ''),
            'region': inst.get('region', {}).get('name', ''),
            'status': inst.get('status', 'booting'),
            'ip': inst.get('ip'),
            'private_ip': inst.get('private_ip', ''),
        } for inst in data]

    def terminate(self, ids: List[str]) -> None:
        if ids:
            self._run('POST', '/instance-operations/terminate',
                      {'instance_ids': ids})

    def ensure_ssh_key(self, name: str, public_key: str) -> None:
        keys = self._run('GET', '/ssh-keys')
        for k in keys:
            if k.get('name') != name:
                continue
            if k.get('public_key', '').strip() == public_key.strip():
                return
            # Same name, different key (local keypair was regenerated):
            # the stale registration would make every new instance
            # unreachable over SSH.
            self._run('DELETE', f'/ssh-keys/{k["id"]}')
            break
        self._run('POST', '/ssh-keys', {'name': name,
                                        'public_key': public_key})


class FakeLambdaService:
    """In-memory Lambda Cloud: instant boot, no zones, no stop."""

    _lock = threading.Lock()
    _instances: Dict[str, Dict[str, Any]] = {}
    _ssh_keys: Dict[str, str] = {}

    def __init__(self, api_key: str = 'fake'):
        self.api_key = api_key
        self._state_path = os.environ.get(_FAKE_STATE_ENV)

    def _load(self) -> Dict[str, Dict[str, Any]]:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding='utf-8') as f:
                return json.load(f)
        return FakeLambdaService._instances

    def _save(self, instances: Dict[str, Dict[str, Any]]) -> None:
        if self._state_path:
            with open(self._state_path, 'w', encoding='utf-8') as f:
                json.dump(instances, f)
        else:
            FakeLambdaService._instances = instances

    def launch(self, name: str, region: str, instance_type: str,
               ssh_key_names: List[str]) -> str:
        del ssh_key_names
        stockout = os.environ.get('SKYTPU_LAMBDA_FAKE_STOCKOUT',
                                  '').split(',')
        if region in stockout:
            raise LambdaCapacityError(
                f'instance-operations/launch/insufficient-capacity: Not '
                f'enough capacity in {region} to fulfill your request. '
                '(fake)')
        with FakeLambdaService._lock:
            instances = self._load()
            iid = f'lam-{uuid.uuid4().hex[:12]}'
            n = len(instances)
            instances[iid] = {
                'id': iid,
                'name': name,
                'instance_type': instance_type,
                'region': region,
                'status': 'active',
                'ip': f'129.146.0.{n + 10}',
                'private_ip': f'10.19.0.{n + 10}',
            }
            self._save(instances)
            return iid

    def list_instances(self) -> List[Dict[str, Any]]:
        return [dict(i) for i in self._load().values()
                if i['status'] != 'terminated']

    def terminate(self, ids: List[str]) -> None:
        with FakeLambdaService._lock:
            instances = self._load()
            for iid in ids:
                if iid in instances:
                    instances[iid]['status'] = 'terminated'
            self._save(instances)

    def ensure_ssh_key(self, name: str, public_key: str) -> None:
        FakeLambdaService._ssh_keys[name] = public_key


def make_client(api_key: Optional[str] = None):
    if os.environ.get('SKYTPU_LAMBDA_FAKE', '0') == '1':
        return FakeLambdaService()
    if api_key is None:
        from skypilot_tpu.clouds.lambda_cloud import Lambda
        api_key = Lambda._api_key()  # pylint: disable=protected-access
    if api_key is None:
        raise LambdaApiError('No Lambda API key configured.')
    return RestTransport(api_key)
