"""Lambda Cloud instance lifecycle (parity:
``sky/provision/lambda_cloud/instance.py``).

Lambda has no tags: cluster membership is encoded in the instance NAME
(``<cluster>-<i>`` — strict integer suffix, see
``provision/neocloud_common.py``). No stop support — instances only run
or terminate.
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision import neocloud_common
from skypilot_tpu.provision.lambda_cloud import lambda_api

logger = sky_logging.init_logger(__name__)

_STATE_MAP = {
    'booting': 'pending',
    'active': 'running',
    'unhealthy': 'running',
    'terminating': 'terminating',
    'terminated': 'terminated',
}

_SSH_KEY_NAME = 'skytpu-key'


def _client(provider_config: Dict[str, Any]) -> Any:
    del provider_config
    return lambda_api.make_client()


def _cluster_instances(client,
                       cluster_name_on_cloud: str,
                       include_terminated: bool = False) -> List[dict]:
    # The real API keeps listing terminating/terminated instances for a
    # while; treating them as live members would make a relaunch after
    # `down` adopt corpses and hang in wait_instances.
    members = neocloud_common.cluster_members(client.list_instances(),
                                              cluster_name_on_cloud)
    if include_terminated:
        return members
    return [
        inst for inst in members
        if _STATE_MAP.get(inst['status']) not in ('terminating',
                                                  'terminated')
    ]


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = _client(config.provider_config)
    public_key = config.authentication_config.get('ssh_public_key')
    if public_key:
        client.ensure_ssh_key(_SSH_KEY_NAME, public_key)
    existing = _cluster_instances(client, cluster_name_on_cloud)
    by_index = neocloud_common.members_by_index(existing,
                                                cluster_name_on_cloud)

    created: List[str] = []
    try:
        for i in range(config.count):
            if i in by_index:
                continue  # no stop state: an existing instance is live
            iid = client.launch(
                name=f'{cluster_name_on_cloud}-{i}',
                region=region,
                instance_type=config.node_config['instance_type'],
                ssh_key_names=[_SSH_KEY_NAME])
            created.append(iid)
    except lambda_api.LambdaCapacityError:
        # Partial creates bill until terminated; failover may leave this
        # region for good. Best-effort: no rollback failure (API error,
        # curl timeout, bad JSON) may mask the capacity error the
        # failover engine needs.
        if created:
            try:
                client.terminate(created)
            except Exception as cleanup_exc:  # pylint: disable=broad-except
                logger.warning(f'Rollback terminate of {created} failed: '
                               f'{cleanup_exc}')
        raise
    head = by_index.get(0)
    head_id = head['id'] if head is not None else (
        created[0] if created else None)
    assert head_id is not None
    return common.ProvisionRecord(provider_name='lambda',
                                  region=region,
                                  zone=None,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=[],
                                  created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    neocloud_common.wait_for_state(
        lambda: _cluster_instances(client, cluster_name_on_cloud),
        _STATE_MAP, cluster_name_on_cloud, state)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    client = _client(provider_config)
    return neocloud_common.build_cluster_info(
        _cluster_instances(client, cluster_name_on_cloud), 'lambda',
        provider_config, default_ssh_user='ubuntu')


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    assert provider_config is not None
    client = _client(provider_config)
    return neocloud_common.query_statuses(
        _cluster_instances(client, cluster_name_on_cloud,
                           include_terminated=True), _STATE_MAP,
        non_terminated_only)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    from skypilot_tpu import exceptions
    raise exceptions.NotSupportedError(
        'Lambda instances cannot be stopped — only terminated. '
        '(The Lambda cloud declares STOP unsupported; reaching this is '
        'a feature-gate bug.)')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    ids = [
        inst['id']
        for inst in _cluster_instances(client, cluster_name_on_cloud)
        if not (worker_only and neocloud_common.parse_node_index(
            inst['name'], cluster_name_on_cloud) == 0)
    ]
    client.terminate(ids)


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    # Lambda exposes all ports on the public IP; nothing to do.
    logger.debug(f'open_ports({cluster_name_on_cloud}, {ports})')


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.debug(f'cleanup_ports({cluster_name_on_cloud}, {ports})')
