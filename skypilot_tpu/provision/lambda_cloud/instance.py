"""Lambda Cloud instance lifecycle (parity:
``sky/provision/lambda_cloud/instance.py``).

Lambda has no tags: cluster membership is encoded in the instance NAME
(``<cluster>-<i>``), mirroring the reference's name-prefix scheme. No
stop support — instances only run or terminate.
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.lambda_cloud import lambda_api

logger = sky_logging.init_logger(__name__)

_STATE_MAP = {
    'booting': 'pending',
    'active': 'running',
    'unhealthy': 'running',
    'terminating': 'terminating',
    'terminated': 'terminated',
}

_SSH_KEY_NAME = 'skytpu-key'


def _client(provider_config: Dict[str, Any]) -> Any:
    del provider_config
    return lambda_api.make_client()


def _node_index(inst: dict, cluster_name_on_cloud: str) -> int:
    suffix = inst['name'][len(cluster_name_on_cloud) + 1:]
    try:
        return int(suffix)
    except ValueError:
        return 0


def _cluster_instances(client,
                       cluster_name_on_cloud: str,
                       include_terminated: bool = False) -> List[dict]:
    # The real API keeps listing terminating/terminated instances for a
    # while; treating them as live members would make a relaunch after
    # `down` adopt corpses and hang in wait_instances.
    return [
        inst for inst in client.list_instances()
        if inst['name'].startswith(f'{cluster_name_on_cloud}-') and
        (include_terminated or
         _STATE_MAP.get(inst['status']) not in ('terminating',
                                                'terminated'))
    ]


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = _client(config.provider_config)
    public_key = config.authentication_config.get('ssh_public_key')
    if public_key:
        client.ensure_ssh_key(_SSH_KEY_NAME, public_key)
    existing = _cluster_instances(client, cluster_name_on_cloud)
    by_index = {_node_index(i, cluster_name_on_cloud): i for i in existing}

    created: List[str] = []
    try:
        for i in range(config.count):
            if i in by_index:
                continue  # no stop state: an existing instance is live
            iid = client.launch(
                name=f'{cluster_name_on_cloud}-{i}',
                region=region,
                instance_type=config.node_config['instance_type'],
                ssh_key_names=[_SSH_KEY_NAME])
            created.append(iid)
    except lambda_api.LambdaCapacityError:
        # Partial creates bill until terminated; failover may leave this
        # region for good.
        if created:
            client.terminate(created)
        raise
    head = by_index.get(0)
    head_id = head['id'] if head is not None else (
        created[0] if created else None)
    assert head_id is not None
    return common.ProvisionRecord(provider_name='lambda',
                                  region=region,
                                  zone=None,
                                  cluster_name=cluster_name_on_cloud,
                                  head_instance_id=head_id,
                                  resumed_instance_ids=[],
                                  created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = 'running',
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    import time
    assert provider_config is not None
    client = _client(provider_config)
    deadline = time.time() + 600
    while True:
        insts = _cluster_instances(client, cluster_name_on_cloud)
        states = [_STATE_MAP.get(i['status'], 'pending') for i in insts]
        if insts and all(s == state for s in states):
            return
        if time.time() > deadline:
            raise common.ProvisionerError(
                f'Timed out waiting for {cluster_name_on_cloud} to reach '
                f'{state}; current: {states}')
        time.sleep(5)


def get_cluster_info(
        region: str,
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None
) -> common.ClusterInfo:
    assert provider_config is not None
    client = _client(provider_config)
    instances: Dict[str, List[common.InstanceInfo]] = {}
    head_id = None
    insts = _cluster_instances(client, cluster_name_on_cloud)
    for inst in sorted(insts,
                       key=lambda i: _node_index(i, cluster_name_on_cloud)):
        if head_id is None:  # sorted: node 0 first
            head_id = inst['id']
        instances[inst['id']] = [
            common.InstanceInfo(
                instance_id=inst['id'],
                internal_ip=inst.get('private_ip', ''),
                external_ip=inst.get('ip'),
                tags={'name': inst['name']},
            )
        ]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head_id,
        provider_name='lambda',
        provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'ubuntu'),
        ssh_private_key=provider_config.get('ssh_private_key'),
    )


def query_instances(
        cluster_name_on_cloud: str,
        provider_config: Optional[Dict[str, Any]] = None,
        non_terminated_only: bool = True) -> Dict[str, Optional[str]]:
    assert provider_config is not None
    client = _client(provider_config)
    out: Dict[str, Optional[str]] = {}
    for inst in _cluster_instances(client, cluster_name_on_cloud,
                                   include_terminated=True):
        status = _STATE_MAP.get(inst['status'], 'pending')
        if non_terminated_only and status == 'terminated':
            continue
        out[inst['id']] = status
    return out


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    from skypilot_tpu import exceptions
    raise exceptions.NotSupportedError(
        'Lambda instances cannot be stopped — only terminated. '
        '(The Lambda cloud declares STOP unsupported; reaching this is '
        'a feature-gate bug.)')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    assert provider_config is not None
    client = _client(provider_config)
    ids = [
        inst['id']
        for inst in _cluster_instances(client, cluster_name_on_cloud)
        if not (worker_only and
                _node_index(inst, cluster_name_on_cloud) == 0)
    ]
    client.terminate(ids)


def open_ports(cluster_name_on_cloud: str,
               ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    # Lambda exposes all ports on the public IP; nothing to do.
    logger.debug(f'open_ports({cluster_name_on_cloud}, {ports})')


def cleanup_ports(cluster_name_on_cloud: str,
                  ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    logger.debug(f'cleanup_ports({cluster_name_on_cloud}, {ports})')
