"""Benchmark callback core: per-step timestamps → summary JSON.

Parity: ``sky/callbacks/sky_callback/base.py`` — the summary file layout
(boot time, first/last step timestamps, step count) is what
``benchmark_utils`` downloads and summarizes. The log dir comes from
``$SKYTPU_BENCH_LOG_DIR`` (exported by `bench launch`), defaulting to
``~/.skytpu/bench`` so local runs also record.
"""
import contextlib
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Optional

SUMMARY_FILE = 'summary.json'
ENV_LOG_DIR = 'SKYTPU_BENCH_LOG_DIR'

_global_cb: Optional['BenchmarkCallback'] = None


class BenchmarkCallback:
    """Writes a rolling summary.json with step timing statistics."""

    # Rewrite the summary every N steps (cheap: one small JSON).
    FLUSH_EVERY = 10

    def __init__(self, log_dir: Optional[str] = None,
                 total_steps: Optional[int] = None):
        log_dir = log_dir or os.environ.get(ENV_LOG_DIR) or os.path.join(
            os.path.expanduser('~'), '.skytpu', 'bench')
        self.log_dir = os.path.expanduser(log_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        self.total_steps = total_steps
        self.boot_time = time.time()
        self.first_step_time: Optional[float] = None
        self.last_step_time: Optional[float] = None
        self.num_steps = 0
        # True once on_step_begin was used: first_step_time is then a step
        # START, so [first, last] spans num_steps full steps; end-only
        # loops span num_steps - 1 (the summarizer needs to know which).
        self.begin_instrumented = False
        self._step_start: Optional[float] = None
        self._lock = threading.Lock()
        self._flush()

    def on_step_begin(self) -> None:
        now = time.time()
        with self._lock:
            self.begin_instrumented = True
            if self.first_step_time is None:
                self.first_step_time = now
            self._step_start = now

    def on_step_end(self) -> None:
        now = time.time()
        with self._lock:
            if self.first_step_time is None:
                # Loop that only calls on_step_end: first end bounds step 1.
                self.first_step_time = self._step_start or now
            self.num_steps += 1
            self.last_step_time = now
            if self.num_steps % self.FLUSH_EVERY == 0:
                self._flush_locked()

    @contextlib.contextmanager
    def step(self):
        self.on_step_begin()
        try:
            yield
        finally:
            self.on_step_end()

    def close(self) -> None:
        self._flush()

    def _flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        payload = {
            'boot_time': self.boot_time,
            'first_step_time': self.first_step_time,
            'last_step_time': self.last_step_time,
            'num_steps': self.num_steps,
            'total_steps': self.total_steps,
            'begin_instrumented': self.begin_instrumented,
        }
        tmp = os.path.join(self.log_dir, SUMMARY_FILE + '.tmp')
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(payload, f)
        os.replace(tmp, os.path.join(self.log_dir, SUMMARY_FILE))


# ------------------------------------------------------------- module API


def init(log_dir: Optional[str] = None,
         total_steps: Optional[int] = None) -> BenchmarkCallback:
    """Parity: sky_callback.init — module-level singleton."""
    global _global_cb
    _global_cb = BenchmarkCallback(log_dir=log_dir,
                                   total_steps=total_steps)
    return _global_cb


def _cb() -> BenchmarkCallback:
    global _global_cb
    if _global_cb is None:
        _global_cb = BenchmarkCallback()
    return _global_cb


def on_step_begin() -> None:
    _cb().on_step_begin()


def on_step_end() -> None:
    _cb().on_step_end()


@contextlib.contextmanager
def step():
    with _cb().step():
        yield


def instrument(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Wrap a train-step function: each call is one timed step."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with _cb().step():
            return fn(*args, **kwargs)

    return wrapped
