"""In-training step-timing hooks feeding the benchmark subsystem.

Parity: the ``sky-callback`` package (``sky/callbacks/``, SURVEY §2.10) —
a tiny, dependency-free logger user training code calls per step; the
benchmark collector reads the produced JSON to compute steps/sec, $/step
and ETA. Works with any loop (JAX included) via ``init`` + ``on_step_end``
or the ``step()`` context manager / ``instrument()`` wrapper.
"""
from skypilot_tpu.callbacks.base import BenchmarkCallback
from skypilot_tpu.callbacks.base import init
from skypilot_tpu.callbacks.base import instrument
from skypilot_tpu.callbacks.base import on_step_begin
from skypilot_tpu.callbacks.base import on_step_end
from skypilot_tpu.callbacks.base import step

__all__ = ['init', 'on_step_begin', 'on_step_end', 'step', 'instrument',
           'BenchmarkCallback']
