"""Continuous-batching decode engine: slot-scheduled serving on one cache.

``decode.generate`` is static-batch, run-to-completion: a batch is
admitted together, the scan runs every ``max_new_tokens`` step even after
all rows hit EOS, and a new request waits behind the longest sequence in
flight. On accelerators that is the dominant serving throughput loss —
the chip's batch lanes sit idle exactly when traffic is mixed-length,
which is always (the TPU concurrency-utilization problem of PAPERS.md's
"Exploring the limits of Concurrency in ML Training on Google TPUs",
applied to inference).

:class:`DecodeEngine` replaces run-to-completion with slot scheduling
(the vLLM/JetStream continuous-batching model, on the in-tree
flash-decode path):

* **One persistent KV cache** of ``num_slots`` lanes
  (``[L, num_slots, max_len, Hkv, hd]``, bf16 or int8+scales), donated
  through every jitted call so prefill scatters and per-step updates
  mutate the same HBM buffers for the life of the process.
* **insert()** prefills a single request ([1, S_bucket] — prompt lengths
  round up to a small set of bucket shapes so compiles stay bounded) and
  scatters its K/V prefix into a free lane via
  ``decode.prefill_into_slot``; the first token samples from the prefill
  logits, so TTFT does not wait for a decode step.
* **step()** runs ``step_chunk`` batched ``decode_step`` s across ALL
  slots with per-slot positions; per-slot EOS/budget masks freeze
  finished lanes (their emitted positions are forced to EOS exactly like
  ``generate``'s done mask, which is what makes greedy engine output
  token-identical to static ``generate``).
* Finished slots are **evicted and immediately refilled** from the
  admission queue, so a short request never waits for a long one and
  lane occupancy stays high. Occupancy is measured, not assumed:
  ``stats()['mean_occupancy']`` is delivered-tokens / lane-steps.

Host/device split: per-slot scheduling state (which request owns which
lane, budgets, done flags) lives in numpy mirrors; each ``step()`` makes
one jitted call of ``step_chunk`` fused decode steps and one host fetch.
``step_chunk`` amortizes dispatch overhead; 1 gives token-granular
streaming and exact occupancy accounting.

**Paged mode** (``paged=True``) replaces the dense per-slot
``[max_len]`` lanes with a global block pool
(``decode.init_block_pool``: ``[L, n_blocks, block_k, Hkv, hd]``) plus
per-slot int32 block tables, so HBM scales with live tokens instead of
``num_slots × max_len``:

* A host-side :class:`BlockAllocator` hands out pool blocks with
  refcounting (a block may be owned by several slots AND the prefix
  cache at once) and copy-on-write (``cow``: a shared block about to be
  written is cloned first via ``decode.copy_block``).
* A :class:`RadixPrefixCache` — a radix tree over block-sized token
  runs — remembers which pool blocks hold which prompt prefixes.
  Admissions whose prompt matches a cached branch reference those
  blocks copy-free and prefill ONLY the suffix
  (``decode.paged_prefill_with_prefix``), skipping the forward pass
  over the shared prefix entirely. Unreferenced branches are LRU-
  evicted when the pool runs dry.
* Admission reserves the request's worst-case blocks
  (``ceil((prompt + max_new_tokens)/block_k)`` minus shared ones), so a
  running request can never hit pool exhaustion mid-decode; when the
  reservation cannot be met even after eviction, the request stays
  queued.

**Speculative decoding** (``dcfg.spec_k > 0``, paged + greedy): a
truncated-layer drafter (the served model's first
``spec_drafter_layers`` blocks + tied out-norm/lm_head — no second
weight set) proposes ``spec_k`` tokens per lane per step, and ONE
batched multi-token verify scores every lane's drafts against the full
model over the existing block tables (``decode._paged_verify_step`` —
a q-length ``spec_k+1`` decode through the same pool). Accept/rollback
is host-side and purely positional: the accepted run's K/V were
already written correctly by verify, and a rejected tail is "rolled
back" by not advancing ``pos`` past it (the stale entries sit in
lane-private blocks, are never attended — attention masks by
position — and are overwritten when a real token reaches that
position). No block churn, no device rollback. Greedy output is
token-identical to the non-speculative paged path (every emitted token
is the full model's argmax in the true context), pinned in tier-1.

**Chunked prefill** (``prefill_chunk > 0``, paged): an admission whose
un-cached suffix exceeds ``SKYTPU_PREFILL_CHUNK`` tokens no longer
runs one monolithic prefill that stalls every decode lane for its full
length — the blocks are reserved up front, the slot parks in a
"prefilling" state (its table rows stay scratch-pointed so frozen-lane
writes cannot touch half-filled blocks), and each engine step advances
it by ONE bounded chunk (``decode.paged_prefill_with_prefix`` over the
already-written prefix) before the decode dispatch runs. The bound is
per prompt: concurrent chunked admissions each advance one chunk per
step, so a burst of long prompts does not serialize admission. The last
chunk's logits produce the first token; only then does the real table
install and the lane join decoding. The PR 9 ``engine.stall`` detector
tags each stalled step with its prefill/decode composition, so a
chunk-induced stall is distinguishable from a true wedge.

Admission is **per-tenant fair**: the queue is one FIFO per tenant
drained round-robin, so one tenant's burst cannot monopolize slots or
pool blocks. Over-budget requests are clamped (budget) or rejected
(prompt too long) with a journaled ``engine.reject`` instead of
crashing the loop.

Telemetry: ``skytpu_engine_*`` metrics through the process registry
(queue depth, slot occupancy, admitted/evicted counters, TTFT and
per-token histograms; paged mode adds ``skytpu_engine_blocks_total`` /
``skytpu_engine_blocks_used``, ``skytpu_engine_prefix_hit_ratio`` and
``skytpu_engine_prefill_tokens_saved_total``) and
``engine.admit``/``engine.evict``/``engine.reject`` flight-recorder
events, so a serving replica's scheduling decisions are reconstructable
after the fact.
"""
import collections
import concurrent.futures
import functools
import heapq
import itertools
import os
import threading
import time
import traceback
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import block_store
from skypilot_tpu.models import decode, llama
from skypilot_tpu.models import prefix_transfer
from skypilot_tpu.observability import journal
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import request_trace
from skypilot_tpu.observability import runtime_metrics
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import common_utils

IDLE_SLEEP_ENV = 'SKYTPU_ENGINE_IDLE_SLEEP_SECONDS'
# Supervisor restart budget: a step() crash fails in-flight requests
# fast, rebuilds device state, and restarts the loop — at most
# MAX_RESTARTS times within a rolling RESTART_WINDOW. One more crash
# inside the window marks the engine permanently failed (/healthz goes
# 503 for good; the replica manager's probe/retry machinery replaces
# the replica).
MAX_RESTARTS_ENV = 'SKYTPU_ENGINE_MAX_RESTARTS'
DEFAULT_MAX_RESTARTS = 3
RESTART_WINDOW_ENV = 'SKYTPU_ENGINE_RESTART_WINDOW_SECONDS'
DEFAULT_RESTART_WINDOW_SECONDS = 300.0
# Chunked prefill: paged admissions whose un-cached suffix exceeds this
# many tokens split into one-chunk-per-step prefills interleaved with
# decode steps (0 disables — the pre-chunking monolithic prefill).
PREFILL_CHUNK_ENV = 'SKYTPU_PREFILL_CHUNK'

# The pool's block 0 is engine-owned scratch: freed slots' table rows
# point at it so frozen lanes write harmlessly, and bucket-padding
# prefill writes spill into it. The allocator never hands it out.
SCRATCH_BLOCK = 0


class PoolExhausted(RuntimeError):
    """Raised when an admission's block reservation cannot be met (even
    after prefix-cache eviction). The admission loop leaves the request
    queued and retries after the next eviction frees blocks."""


class BlockAllocator:
    """Refcounted free-list allocator over the paged KV pool's blocks.

    Blocks are plain ints in [1, num_blocks). A block's refcount is the
    number of owners: each slot whose table references it, plus the
    radix prefix cache if a tree node holds it. ``free`` is implicit —
    the refcount hitting zero returns the block to the free list.
    Host-side only; device buffers never move.
    """

    def __init__(self, num_blocks: int, reserved: int = 1):
        if num_blocks <= reserved:
            raise ValueError(f'num_blocks must be > {reserved}, got '
                             f'{num_blocks}')
        self.num_blocks = num_blocks
        self._reserved = reserved
        self._free: List[int] = list(range(num_blocks - 1, reserved - 1,
                                           -1))
        self._ref = np.zeros((num_blocks,), np.int32)

    def available(self) -> int:
        return len(self._free)

    def used(self) -> int:
        return (self.num_blocks - self._reserved) - len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks (refcount 1 each); raises PoolExhausted."""
        if n > len(self._free):
            raise PoolExhausted(
                f'need {n} blocks, {len(self._free)} free')
        out = [self._free.pop() for _ in range(n)]
        self._ref[out] = 1
        return out

    def incref(self, blocks) -> None:
        for b in blocks:
            assert self._ref[b] > 0, f'incref of free block {b}'
            self._ref[b] += 1

    def decref(self, blocks) -> List[int]:
        """Drop one ref per block; returns the blocks actually freed."""
        freed = []
        for b in blocks:
            assert self._ref[b] > 0, f'decref of free block {b}'
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)
                freed.append(b)
        return freed

    def refcount(self, block: int) -> int:
        return int(self._ref[block])

    def cow(self, block: int) -> Tuple[int, bool]:
        """Copy-on-write: a caller holding one ref and about to WRITE
        ``block``. Sole owner → write in place (no copy). Shared →
        allocate a clone target; the caller device-copies
        (``decode.copy_block``), keeps its ref on the original until
        release, and writes the clone. Returns (writable_block,
        needs_copy)."""
        if self._ref[block] == 1:
            return block, False
        return self.alloc(1)[0], True


class _RadixNode:
    """One edge of the prefix tree: a run of whole blocks. ``keys[i]``
    is the tuple of block_k token ids cached in pool block
    ``blocks[i]``. ``lock`` counts in-flight requests whose admission
    matched through this node (evicting it would free blocks they
    read)."""

    __slots__ = ('keys', 'blocks', 'children', 'parent', 'lock', 'last')

    def __init__(self, keys, blocks, parent):
        self.keys: List[tuple] = keys
        self.blocks: List[int] = blocks
        self.children: dict = {}
        self.parent = parent
        self.lock = 0
        self.last = 0


class RadixPrefixCache:
    """Radix tree mapping prompt-token prefixes → pool blocks.

    Granularity is one block (``block_k`` tokens): edges hold runs of
    whole blocks, children are keyed by their first block's token
    tuple, and matching/splitting happen at block boundaries — partial
    blocks are never shared (so shared blocks are immutable and
    copy-on-write is only ever needed at the one boundary block of a
    full-prompt hit). The tree owns one allocator ref per held block;
    ``evict`` LRU-walks unlocked leaves and drops those refs under pool
    pressure.
    """

    def __init__(self, block_k: int, allocator: BlockAllocator):
        self.block_k = block_k
        self._alloc = allocator
        self._root = _RadixNode([], [], None)
        self._clock = 0
        self._n_blocks = 0          # blocks currently held by the tree
        self._n_nodes = 0           # edges in the tree (root excluded)

    # ------------------------------------------------------------ utils

    def _block_keys(self, tokens) -> List[tuple]:
        bk = self.block_k
        return [tuple(tokens[i * bk:(i + 1) * bk])
                for i in range(len(tokens) // bk)]

    def held_blocks(self) -> int:
        return self._n_blocks

    def node_count(self) -> int:
        return self._n_nodes

    def _touch(self, node: '_RadixNode') -> None:
        self._clock += 1
        node.last = self._clock

    # ------------------------------------------------------------ match

    def match(self, tokens) -> Tuple[List[int], List['_RadixNode']]:
        """Longest cached prefix of ``tokens`` in whole blocks.

        Returns (blocks, path): pool blocks holding the matched prefix
        in order, and the tree nodes traversed. The caller receives one
        allocator ref per matched block and a lock on every path node —
        both must be returned via :meth:`release` when the request
        finishes."""
        keys = self._block_keys(tokens)
        blocks: List[int] = []
        path: List[_RadixNode] = []
        node = self._root
        i = 0
        while i < len(keys):
            child = node.children.get(keys[i])
            if child is None:
                break
            n = 0
            while (n < len(child.keys) and i + n < len(keys) and
                   child.keys[n] == keys[i + n]):
                n += 1
            if n == 0:
                break
            blocks.extend(child.blocks[:n])
            path.append(child)
            self._touch(child)
            i += n
            if n < len(child.keys):
                break
            node = child
        if blocks:
            self._alloc.incref(blocks)
            for p in path:
                p.lock += 1
        return blocks, path

    def release(self, path) -> None:
        for p in path:
            assert p.lock > 0
            p.lock -= 1

    # ----------------------------------------------------------- insert

    def insert(self, tokens, blocks) -> int:
        """Record that ``blocks[i]`` holds tokens
        ``tokens[i*block_k:(i+1)*block_k]`` (len(tokens) must be a
        whole number of blocks). Already-cached prefixes are deduped
        against the existing branch; only the divergent suffix is
        adopted (tree increfs those blocks). Returns the number of
        blocks newly adopted."""
        keys = self._block_keys(tokens)
        assert len(keys) == len(blocks), (len(keys), len(blocks))
        node = self._root
        i = 0
        while i < len(keys):
            child = node.children.get(keys[i])
            if child is None:
                new = _RadixNode(keys[i:], list(blocks[i:]), node)
                node.children[keys[i]] = new
                self._touch(new)
                self._n_nodes += 1
                adopted = len(new.blocks)
                self._alloc.incref(new.blocks)
                self._n_blocks += adopted
                return adopted
            n = 0
            while (n < len(child.keys) and i + n < len(keys) and
                   child.keys[n] == keys[i + n]):
                n += 1
            self._touch(child)
            if n < len(child.keys):
                if i + n == len(keys):
                    return 0        # new prompt is a prefix of the edge
                self._split(child, n)
            i += n
            node = child
        return 0

    def _split(self, node: '_RadixNode', at: int) -> None:
        """Split an edge at block index ``at`` (0 < at < len): the node
        keeps the prefix, a new child takes the tail (and the node's
        children). Locks stay on the prefix node — a lock protects the
        whole path above it, and the tail's blocks keep their tree
        refs via the new child."""
        tail = _RadixNode(node.keys[at:], node.blocks[at:], node)
        tail.children = node.children
        for c in tail.children.values():
            c.parent = tail
        tail.last = node.last
        node.keys = node.keys[:at]
        node.blocks = node.blocks[:at]
        node.children = {tail.keys[0]: tail}
        self._n_nodes += 1

    # ------------------------------------------------------------ evict

    def evict(self, need_blocks: int) -> int:
        """LRU-evict unlocked leaves until ``need_blocks`` allocator
        blocks came free (or nothing is evictable). Returns blocks
        freed.

        A leaf is only worth evicting if at least one of its blocks is
        SOLELY tree-held (refcount 1): dropping an entry whose blocks
        an active slot still pins frees zero HBM — it would just
        destroy future prefix hits for nothing, so those leaves are
        skipped (they become reclaimable once their slots evict).

        One tree walk collects the LRU-ordered leaf list; evicting a
        leaf can turn its parent into a fresh leaf, which is appended
        to the candidate heap directly — no per-victim re-walk, so a
        pressure episode is O(nodes log nodes), not O(nodes^2)."""
        freed = 0
        heap = [(n.last, id(n), n) for n in self._iter_nodes()
                if not n.children and n is not self._root]
        heapq.heapify(heap)
        while freed < need_blocks and heap:
            _, _, victim = heapq.heappop(heap)
            if victim.lock != 0 or victim.children:
                continue            # locked, or stale entry
            if all(self._alloc.refcount(b) > 1 for b in victim.blocks):
                continue            # pinned by slots: freeing gains 0
            freed += len(self._alloc.decref(victim.blocks))
            self._n_blocks -= len(victim.blocks)
            self._n_nodes -= 1
            parent = victim.parent
            del parent.children[victim.keys[0]]
            if (parent is not self._root and not parent.children):
                heapq.heappush(heap, (parent.last, id(parent), parent))
        return freed

    def _iter_nodes(self):
        stack = [self._root]
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())


class Request:
    """One generation request tracked through the engine.

    ``on_token(token, done)`` (optional) fires from the engine loop
    thread per generated token — the model server bridges it onto its
    asyncio loop for SSE streaming. ``on_finish()`` (optional
    attribute) fires once when the request reaches a terminal state —
    including rejections and admission errors that never produced a
    token, which ``on_token`` alone would miss (a client waiting on
    the token stream must not hang out a timeout to learn its request
    was rejected instantly). ``tokens`` accumulates the full
    generation; ``wait()`` blocks until eviction.
    """
    _ids = itertools.count()

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 on_token: Optional[Callable[[int, bool], None]] = None,
                 request_id: Optional[str] = None,
                 tenant: str = 'default',
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 prefix_hint: Optional[str] = None):
        if max_new_tokens < 1:
            raise ValueError(f'max_new_tokens must be >= 1, got '
                             f'{max_new_tokens}')
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError('empty prompt')
        self.max_new_tokens = int(max_new_tokens)
        self.on_token = on_token
        self.on_finish: Optional[Callable[[], None]] = None
        # Admission-fairness key: requests queue per tenant and admit
        # round-robin across tenants (the model server maps X-Tenant /
        # body "tenant" here).
        self.tenant = str(tenant)
        self.id = (request_id if request_id is not None
                   else f'r{next(self._ids)}')
        # Per-request trace id (the server's X-Request-Id): the engine
        # stamps this request's journal rows with it, so `skytpu trace
        # <id>` joins the HTTP request to its engine timeline. None →
        # rows carry the ambient process trace context. span_id (the
        # model server's per-request `server.request` span) nests those
        # rows under the HTTP span in the rendered tree, which itself
        # parents under the LB's `lb.proxy` span when the request came
        # through the load balancer.
        self.trace_id = trace_id
        self.span_id = span_id
        # Cross-replica prefix tier: the LB's X-Skytpu-Prefix-Owner hop
        # header — the peer most likely holding this prompt's cached KV
        # blocks. Tried FIRST on a local radix miss.
        self.prefix_hint = prefix_hint
        # Disaggregated prefill/decode: when set, the engine streams
        # this request's KV blocks to a decode peer as prefill chunks
        # complete (``handoff_push(tokens_prefix, payload) -> bool``,
        # True = acked) and — once every full block is acked — finishes
        # the request as ``'handoff'`` without decoding: the decode
        # replica owns the token stream. Any push failure degrades to
        # decode-in-place on this replica. ``handoff_peer`` (the decode
        # peer's URL, when known) feeds the engine's per-peer failure
        # backoff.
        self.handoff_push: Optional[Callable[..., bool]] = None
        self.handoff_peer: Optional[str] = None
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.enqueue_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    # ------------------------------------------------- engine-side hooks

    def _deliver(self, token: int, done: bool) -> None:
        self.tokens.append(token)
        if self.first_token_ts is None:
            self.first_token_ts = time.perf_counter()
        if self.on_token is not None:
            self.on_token(token, done)

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self.finish_ts = time.perf_counter()
        self._done.set()
        if self.on_finish is not None:
            self.on_finish()


def _scan_engine_steps(decode_fn, dcfg: decode.DecodeConfig, token, pos,
                       done, remaining, keys, cache):
    """The ONE copy of the engine's per-step scheduling semantics,
    shared by the dense and paged twins (only ``decode_fn`` — how
    (token, pos, cache) → (logits, cache) — differs between them).

    Per-step semantics mirror ``decode._generate_impl.step`` exactly
    for live lanes (same sample → EOS-mask → done-fold order, so greedy
    output is token-identical to static ``generate``); done lanes
    additionally FREEZE their position instead of advancing, bounding
    writes for lanes that idle across many chunks (emitted tokens are
    forced to EOS either way, so the freeze is unobservable in the
    output stream)."""
    def step(carry, key):
        tok, p, dn, rem, cache_c = carry
        logits, cache_c = decode_fn(tok, p, cache_c)
        nxt = decode._sample(logits, key, dcfg.temperature)  # pylint: disable=protected-access
        if dcfg.eos_id is not None:
            nxt = jnp.where(dn, dcfg.eos_id, nxt)
            dn_new = dn | (nxt == dcfg.eos_id)
        else:
            nxt = jnp.where(dn, tok, nxt)
            dn_new = dn
        # One budget unit per live step; exhaustion folds into done.
        rem = rem - jnp.where(dn, 0, 1)
        dn_new = dn_new | (rem <= 0)
        p = jnp.where(dn, p, p + 1)
        return (nxt, p, dn_new, rem, cache_c), nxt

    (token, pos, done, remaining, cache), toks = jax.lax.scan(
        step, (token, pos, done, remaining, cache), keys)
    return toks, token, pos, done, remaining, cache


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'dcfg', 'n_steps'),
                   donate_argnums=(6,))
def _engine_steps_impl(params, token, pos, done, remaining, keys, cache,
                       cfg: llama.LlamaConfig, dcfg: decode.DecodeConfig,
                       n_steps: int):
    """``n_steps`` fused decode steps over every slot.

    token/pos/remaining [num_slots] int32, done [num_slots] bool, keys
    [n_steps, 2] uint32 (sampling; unused for greedy), cache donated.
    Returns (tokens [n_steps, num_slots], token, pos, done, remaining,
    cache). Step semantics: :func:`_scan_engine_steps`.
    """
    del n_steps

    def decode_fn(tok, p, cache_c):
        return decode._decode_step(  # pylint: disable=protected-access
            params, tok, p, cfg, dcfg, cache_c)

    return _scan_engine_steps(decode_fn, dcfg, token, pos, done,
                              remaining, keys, cache)


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'dcfg', 'n_steps', 'mesh'),
                   donate_argnums=(7,))
def _engine_paged_steps_impl(params, token, pos, done, remaining, keys,
                             block_tables, cache,
                             cfg: llama.LlamaConfig,
                             dcfg: decode.DecodeConfig, n_steps: int,
                             mesh=None):
    """Paged twin of :func:`_engine_steps_impl`: identical per-step
    semantics, but the cache is the global block pool and every K/V
    read/write indirects through ``block_tables`` [num_slots,
    max_len // block_k] (frozen lanes keep writing their frozen
    position — eviction repoints their table rows at the scratch block,
    so those writes can never land in a reallocated block). ``mesh``
    (static; Mesh hashes by devices + axis names) is the
    tensor-parallel serving mesh — params/pool arrive sharded over its
    'model' axis and the paged kernel dispatches per shard."""
    del n_steps

    def decode_fn(tok, p, cache_c):
        return decode._paged_decode_step(  # pylint: disable=protected-access
            params, tok, p, block_tables, cfg, dcfg, cache_c, mesh=mesh)

    return _scan_engine_steps(decode_fn, dcfg, token, pos, done,
                              remaining, keys, cache)


@functools.partial(jax.jit, static_argnames=('cfg', 'dcfg', 'mesh'),
                   donate_argnums=(5,))
def _engine_spec_step_impl(params, token, pos, draft_tables,
                           block_tables, cache,
                           cfg: llama.LlamaConfig,
                           dcfg: decode.DecodeConfig, mesh=None):
    """One speculative round over every slot in ONE dispatch: draft
    ``spec_k`` tokens per lane with the truncated-layer drafter (pool
    read-only), then one batched multi-token verify of
    ``[token, drafts]`` against the full model through the block
    tables. Returns (drafts [num_slots, spec_k], verify argmax
    [num_slots, spec_k+1], cache). Acceptance is host-side
    (:meth:`DecodeEngine._spec_round`): the device never needs to know
    how much of the draft survived — rejected positions are simply
    never advanced past, and their cache entries are overwritten when
    a real token reaches them.

    ``draft_tables`` is the host-narrowed ``block_tables[:, :n]`` slice
    covering the max LIVE block count across lanes (power-of-two
    bucketed so compiles stay bounded): the drafter's per-round history
    gather materializes only the live prefix of the pool view instead
    of the full table width. Verify keeps the full tables — its writes
    land at pos..pos+spec_k, which may cross into blocks past the live
    prefix."""
    drafts = decode._spec_draft_tokens(  # pylint: disable=protected-access
        params, token, pos, draft_tables, cfg, dcfg, cache)
    seq = jnp.concatenate([token[:, None], drafts], axis=1)
    logits, cache = decode._paged_verify_step(  # pylint: disable=protected-access
        params, seq, pos, block_tables, cfg, dcfg, cache, mesh=mesh)
    vtok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return drafts, vtok, cache


@functools.partial(jax.jit, static_argnames=('cfg',), donate_argnums=(4,))
def _prefill_greedy_impl(params, tokens, prompt_len, slot, cache,
                         cfg: llama.LlamaConfig):
    """Greedy insert fast path: prefill + first-token argmax in ONE
    dispatch. Sampling (temperature > 0) keeps the two-call path — it
    needs the raw logits on the engine side."""
    last, cache = decode._prefill_into_slot(  # pylint: disable=protected-access
        params, tokens, prompt_len, slot, cfg, cache)
    return jnp.argmax(last).astype(jnp.int32), cache


def _default_buckets(max_len: int) -> Tuple[int, ...]:
    """Prompt-length buckets: powers of two from 8 up to max_len (one
    prefill compile per bucket actually used)."""
    buckets = []
    b = 8
    while b < max_len:
        buckets.append(min(b, max_len))
        b *= 2
    if not buckets or buckets[-1] < max_len:
        buckets.append(max_len)
    return tuple(buckets)


class DecodeEngine:
    """Slot-based continuous-batching engine over ``models/decode``.

    Thread model: ``submit()`` is thread-safe (the server's request
    handlers call it); ``insert()``/``step()``/``run_forever()`` must run
    on ONE engine loop thread (they own the donated cache).
    """

    # Lock discipline (enforced by `skytpu lint`'s lock-discipline
    # rule, docs/analysis.md): the admission queues are the one
    # mutex-shared seam; everything else host-side-mutable is confined
    # to the engine loop thread and must not be touched from the
    # cross-thread entry points below (snapshot reads that accept a
    # benign race suppress inline with their justification).
    _GUARDED_BY = {
        '_queues': '_queue_lock',
        '_rr_offset': '_queue_lock',
        '_export_jobs': '_export_lock',
        '_slots': 'loop',
        '_token': 'loop',
        '_pos': 'loop',
        '_done': 'loop',
        '_remaining': 'loop',
        '_allocator': 'loop',
        '_radix': 'loop',
        '_block_table_np': 'loop',
        '_block_table_dev': 'loop',
        '_slot_refs': 'loop',
        '_slot_nodes': 'loop',
        '_prefill_state': 'loop',
        '_spill_pending': 'loop',
        '_spill_inflight': 'loop',
        '_spill_seen': 'loop',
    }
    # Entry points other threads call (HTTP handlers, the supervisor's
    # observers). submit/queue_depth take _queue_lock; stats/
    # active_slots/free_slots are the snapshot surface.
    _CROSS_THREAD_METHODS = ('submit', 'queue_depth', 'stats',
                             'spec_stats', 'cache_stats', 'flush_journal',
                             'active_slots', 'free_slots',
                             'export_prefix_blocks',
                             'inject_handoff_blocks', 'handoff_stats')

    def __init__(self, params, cfg: llama.LlamaConfig,
                 dcfg: decode.DecodeConfig, num_slots: int,
                 step_chunk: int = 1,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 rng: Optional[jax.Array] = None,
                 name: str = 'engine',
                 paged: bool = False,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 tp: int = 1,
                 prefix_peers: Optional[Sequence[str]] = None,
                 prefix_fetch_budget: Optional[float] = None,
                 prefix_fetch_fn: Optional[Callable] = None,
                 store_url: Optional[str] = None,
                 store_fetch_fn: Optional[Callable] = None,
                 store_spill_fn: Optional[Callable] = None,
                 journal_db: Optional[str] = None):
        if num_slots < 1:
            raise ValueError(f'num_slots must be >= 1, got {num_slots}')
        if step_chunk < 1:
            raise ValueError(f'step_chunk must be >= 1, got {step_chunk}')
        tp = int(tp)
        if tp < 1:
            raise ValueError(f'tp must be >= 1, got {tp}')
        if tp > 1:
            # Tensor-parallel serving shards the PAGED pool by KV head
            # (the dense cache has no TP story — the pool is the thing
            # being sharded) and needs the head counts to split evenly,
            # else a shard would own a fractional GQA group.
            if not paged:
                raise ValueError('tensor-parallel serving (tp > 1) '
                                 'requires paged=True')
            if cfg.n_kv_heads % tp or cfg.n_heads % tp:
                raise ValueError(
                    f'tp={tp} must divide n_kv_heads '
                    f'({cfg.n_kv_heads}) and n_heads ({cfg.n_heads})')
        if dcfg.spec_k:
            # Speculative decoding rides the paged pool (verify is a
            # multi-token decode over the block tables) and commits the
            # full model's argmax — greedy by construction.
            if not paged:
                raise ValueError('speculative decoding (spec_k > 0) '
                                 'requires paged=True')
            if dcfg.temperature != 0.0:
                raise ValueError(
                    'speculative decoding is greedy-only; got '
                    f'temperature={dcfg.temperature}')
            if not 1 <= dcfg.spec_drafter_layers <= cfg.n_layers:
                raise ValueError(
                    f'spec_drafter_layers must be in [1, '
                    f'{cfg.n_layers}], got {dcfg.spec_drafter_layers}')
        self.tp = tp
        # serving_mesh raises when tp exceeds the visible device count
        # — at multi-host scale that count is the whole slice's devices
        # (jax.distributed.initialize ran first; see
        # parallel/distributed.py), so one engine replica spans the
        # slice while the host-side allocator/radix cache stay exactly
        # as they are: block tables are replicated, sharding only
        # touches the head axis.
        self.mesh = mesh_lib.serving_mesh(tp) if tp > 1 else None
        if self.mesh is not None:
            params = mesh_lib.shard_serving_params(
                params, self.mesh, mesh_lib.serving_param_specs(cfg))
        self.params = params
        self.cfg = cfg
        self.dcfg = dcfg
        self.num_slots = num_slots
        self.step_chunk = step_chunk
        self.name = name
        self.paged = paged
        self._block_k = dcfg.kernel_block_k
        self._buckets = (tuple(sorted(int(b) for b in prefill_buckets))
                         if prefill_buckets
                         else _default_buckets(dcfg.max_len))
        assert self._buckets[-1] <= dcfg.max_len, self._buckets
        if paged:
            bk = self._block_k
            if dcfg.max_len % bk:
                raise ValueError(
                    f'paged mode needs max_len ({dcfg.max_len}) '
                    f'divisible by block_k ({bk})')
            # Prefill scatters whole blocks: snap buckets up to block
            # multiples (dedup keeps the compile count bounded).
            self._buckets = tuple(sorted({
                min(-(-b // bk) * bk, dcfg.max_len)
                for b in self._buckets}))
            self._max_blocks = dcfg.max_len // bk
            # Default pool: the same token capacity the dense cache
            # would reserve (+1 scratch) — equal HBM, so any extra
            # concurrency is pure paging/prefix-sharing win.
            self.num_blocks = (num_blocks if num_blocks is not None
                               else num_slots * self._max_blocks + 1)
        else:
            self.num_blocks = 0
        # Chunked prefill is a paged-admission policy (the chunk calls
        # hand their block rows to decode.paged_prefill_with_prefix
        # explicitly); dense mode ignores it.
        if prefill_chunk is None:
            prefill_chunk = common_utils.env_int(PREFILL_CHUNK_ENV, 0)
        self.prefill_chunk = max(0, int(prefill_chunk)) if paged else 0
        self._prompt_tokens_total = 0
        self._prompt_tokens_saved = 0
        # Cross-replica prefix tier (paged only): peers consulted on a
        # local radix miss, bounded by the fetch budget — a slow peer
        # degrades the admission to plain prefill, never stalls it.
        # prefix_fetch_fn(peer_url, tokens, from_tokens, budget) is the
        # transport (HTTP by default); tests/benches inject direct
        # engine-to-engine calls.
        if prefix_peers is None:
            raw = os.environ.get(prefix_transfer.PREFIX_PEERS_ENV, '')
            prefix_peers = [u.strip() for u in raw.split(',')
                            if u.strip()]
        self.prefix_peers: List[str] = list(prefix_peers) if paged else []
        self.prefix_fetch_budget = (
            prefix_fetch_budget if prefix_fetch_budget is not None
            else common_utils.env_float(
                prefix_transfer.FETCH_BUDGET_ENV,
                prefix_transfer.DEFAULT_FETCH_BUDGET_SECONDS))
        # Only fetch when at least this many block-aligned tokens stand
        # to be gained (default: one block — the smallest unit a peer
        # can ship).
        self._prefix_fetch_min_tokens = common_utils.env_int(
            prefix_transfer.FETCH_MIN_TOKENS_ENV, self._block_k)
        # Unique engine identity: the default transport sends it with
        # every fetch and /prefix_blocks echoes a self-marker when it
        # arrives at the engine that minted it — the ONLY reliable
        # self-detection under a fleet-shared peers list (URL guessing
        # can't know this replica's external address).
        import uuid
        self.instance_id = uuid.uuid4().hex
        self._prefix_fetch_fn = (
            prefix_fetch_fn if prefix_fetch_fn is not None
            else functools.partial(prefix_transfer.http_fetch,
                                   instance=self.instance_id))
        # Dead-peer memory: a peer whose fetch failed is skipped until
        # its backoff expires — one dead/unreachable peer must not cost
        # every cold admission an engine-loop stall forever. A
        # successful fetch clears the peer's backoff.
        self._prefix_fetch_backoff = common_utils.env_float(
            prefix_transfer.FETCH_BACKOFF_ENV,
            prefix_transfer.DEFAULT_FETCH_BACKOFF_SECONDS)
        self._peer_backoff_until: dict = {}
        # URLs this replica knows refer to ITSELF (the model server
        # registers its bound addresses): fetching from self would
        # stall the loop for a budget — the export queue is serviced by
        # the very thread doing the fetch.
        self._prefix_self_urls: set = set()
        self._prefix_fetch_hits = 0
        self._prefix_fetch_misses = 0
        self._prefix_fetch_tokens = 0
        self._prefix_evictions = 0
        # Disaggregated prefill/decode handoff counters: the prefill
        # side counts completed/degraded handoffs and tokens pushed,
        # the decode side counts injections and tokens adopted (one
        # engine can play both roles under role=mixed).
        self._handoffs_completed = 0
        self._handoffs_degraded = 0
        self._handoff_tokens_pushed = 0
        self._handoff_injections = 0
        self._handoff_tokens_injected = 0
        # Outbound handoff pushes ride a lazy executor so the wire
        # transfer of chunk k overlaps the compute of chunk k+1 (the
        # exported payload is a host-side snapshot; the loop thread
        # only awaits the PREVIOUS push before exporting the next).
        self._handoff_pool: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        # Durable block-store tier (paged only): the second level of
        # the cold-miss lookup (peer first, store second) and the
        # write-behind spill target for newly published radix runs.
        # Both directions share one backoff: a dead store must cost at
        # most one budget per window, across fetch AND spill.
        if store_url is None:
            store_url = os.environ.get(block_store.STORE_URL_ENV,
                                       '').strip() or None
        self.store_url: Optional[str] = store_url if paged else None
        self._store_fetch_fn = (
            store_fetch_fn if store_fetch_fn is not None
            else functools.partial(block_store.http_store_fetch,
                                   instance=self.instance_id))
        self._store_spill_fn = (store_spill_fn if store_spill_fn
                                is not None
                                else block_store.http_store_spill)
        self._store_fetch_budget = common_utils.env_float(
            block_store.FETCH_BUDGET_ENV,
            block_store.DEFAULT_FETCH_BUDGET_SECONDS)
        self._store_spill_budget = common_utils.env_float(
            block_store.SPILL_BUDGET_ENV,
            block_store.DEFAULT_SPILL_BUDGET_SECONDS)
        self._store_backoff = common_utils.env_float(
            block_store.BACKOFF_ENV,
            block_store.DEFAULT_BACKOFF_SECONDS)
        self._store_backoff_until = 0.0
        self._store_spill_min_tokens = common_utils.env_int(
            block_store.SPILL_MIN_TOKENS_ENV, 0) or self._block_k
        # Write-behind spill state (loop-confined): published runs
        # queue here; one export + one in-flight POST at a time, so
        # spill never costs the loop more than one host-side gather
        # per step and the wire time rides the worker thread.
        self._spill_pending: List[List[int]] = []
        self._spill_inflight: Optional[Tuple] = None
        self._spill_seen: set = set()
        self._spill_pool: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._store_fetch_hits = 0
        self._store_fetch_misses = 0
        self._store_fetch_tokens = 0
        self._store_spills = 0
        self._store_spill_tokens = 0
        self._store_spill_failures = 0
        self._store_spill_drops = 0
        # Prefix-export jobs: peers' /prefix_blocks requests queue here
        # (any thread) and are serviced by the engine loop at the top of
        # each step — radix/pool reads are loop-confined, so the HTTP
        # thread must never touch them directly.
        self._export_lock = threading.Lock()
        self._export_jobs: List[dict] = []
        # Speculative-decoding counters (cumulative; survive restarts).
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._prefill_chunks = 0
        self._chunked_admissions = 0
        # engine.compile dedupe: jitted-dispatch shapes already noted
        # (the jit cache is process-global, so restarts do NOT reset
        # this — a rebuild does not retrace old shapes).
        self._traced_shapes: set = set()
        self._init_runtime_state()
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        # Greedy decoding ignores sampling keys; reuse one zero buffer
        # instead of allocating [step_chunk, 2] on every tick.
        self._zero_keys = jnp.zeros((step_chunk, 2), jnp.uint32)
        # Admission queues: one FIFO per tenant, appended by any thread,
        # drained round-robin by the loop (per-tenant fairness — one
        # tenant's burst queues behind its own requests, not everyone's).
        self._queue_lock = threading.Lock()
        self._queues: 'collections.OrderedDict[str, collections.deque]' \
            = collections.OrderedDict()
        self._rr_offset = 0
        # Occupancy accounting: tokens delivered from decode steps vs
        # lane-steps executed (prefill-sampled first tokens excluded —
        # they cost a prefill, not a decode lane-step).
        self._decode_steps = 0
        self._decode_emitted = 0
        self._admitted = 0
        self._evicted = 0
        # Flight-recorder buffer: admit/evict events batch into ONE
        # sqlite transaction per tick (journal.JournalBuffer) — a
        # per-event commit costs an fsync, which at token-loop rates
        # would dominate the decode step itself on slow filesystems.
        # stats() flushes from the HTTP thread while the loop appends.
        # ``journal_db`` pins this engine to its own journal file (the
        # federated flight-recorder e2e runs several replicas
        # in-process); None = the host journal.
        self.journal_db = journal_db
        self._jbuf = journal.JournalBuffer(db_path=journal_db,
                                           entity=f'engine:{name}')
        # Request-telemetry plane: per-request phase records assembled
        # at the admit/evict/reject choke points (the per-token hot path
        # stays untouched) + the per-step profiler behind /debug/engine.
        self.telemetry = request_trace.RequestTelemetry(name=name)
        self.profiler = request_trace.EngineStepProfiler(name=name)
        # Supervisor state (run_forever): crash timestamps for the
        # rolling restart budget; `failed` flips once the budget is
        # exhausted and never flips back (/healthz reads it).
        self.failed = False
        self.fail_reason: Optional[str] = None
        self._restarts = 0
        self._crash_times: List[float] = []
        self._m = metrics_lib
        self._m.gauge('skytpu_engine_num_slots',
                      'Configured KV-cache lanes.').set(num_slots)
        self._publish_slot_gauges()
        self._m.gauge(
            'skytpu_engine_tp_degree',
            'Tensor-parallel degree of the serving mesh (1 = '
            'unsharded).').set(tp)
        mesh_devices = ([d for d in self.mesh.devices.flat]
                        if self.mesh is not None else [jax.devices()[0]])
        self._m.gauge(
            'skytpu_engine_mesh_devices',
            'Devices in the engine serving mesh.').set(len(mesh_devices))
        # engine.mesh: journaled ONCE at engine start (not per restart —
        # the mesh is construction-time state) so perf rounds and
        # postmortems can attribute throughput to the topology that
        # served it.
        self._journal_raw(journal.EventKind.ENGINE_MESH, {
            'tp': tp,
            'mesh_shape': (dict(self.mesh.shape)
                           if self.mesh is not None else {'model': 1}),
            'devices': len(mesh_devices),
            'device_kinds': sorted({d.device_kind for d in mesh_devices}),
            'platform': mesh_devices[0].platform,
            'process_count': jax.process_count(),
            'paged': self.paged,
        })
        # HBM accounting: per-device weights vs KV-pool vs workspace
        # bytes on the serving mesh, journaled once beside engine.mesh
        # (construction-time state like the mesh itself — the
        # supervisor rebuild re-creates an identically-sized pool) and
        # published as skytpu_engine_hbm_bytes{kind}.
        hbm = self._hbm_accounting(mesh_devices[0])
        hbm_g = self._m.gauge(
            'skytpu_engine_hbm_bytes',
            'Per-device HBM bytes by consumer: sharded weights, the '
            'paged KV pool (or dense cache), and measured workspace '
            'residual.', labels=('kind',))
        for kind, nbytes in hbm['per_device_bytes'].items():
            hbm_g.set(nbytes, labels=(kind,))
        self._journal_raw(journal.EventKind.ENGINE_HBM,
                          {'tp': tp, **hbm})
        self.flush_journal()

    def _hbm_accounting(self, device) -> dict:
        """Per-device byte split of this engine's HBM footprint.

        Weights and KV pool are EXACT: each pytree leaf contributes its
        first addressable shard's bytes (under a TP mesh that is the
        per-device shard; replicated leaves contribute their full
        size). Workspace is the measured residual —
        ``device.memory_stats()`` bytes_in_use minus weights and pool —
        when the backend reports memory stats (TPU), else 0 with
        ``workspace_measured: false`` (the CPU tier has no HBM to
        meter; the split still proves the accounting dark)."""

        def per_device_bytes(tree) -> int:
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                shards = getattr(leaf, 'addressable_shards', None)
                if shards:
                    total += shards[0].data.nbytes
                else:
                    total += int(getattr(leaf, 'nbytes', 0))
            return total

        weights = per_device_bytes(self.params)
        pool = per_device_bytes(self._cache)
        pool_kind = 'paged_pool' if self.paged else 'kv_cache'
        workspace = 0
        measured = False
        try:
            stats = device.memory_stats()
            if stats and 'bytes_in_use' in stats:
                workspace = max(
                    0, int(stats['bytes_in_use']) - weights - pool)
                measured = True
        except (RuntimeError, AttributeError, TypeError):
            pass  # backend without memory stats (CPU) — estimate-free 0
        return {
            'per_device_bytes': {'weights': weights,
                                 pool_kind: pool,
                                 'workspace': workspace},
            'workspace_measured': measured,
            'pool_kind': pool_kind,
        }

    def _init_runtime_state(self) -> None:
        """(Re)build everything a crashed step may have corrupted: the
        device cache/block pool, the allocator + radix prefix cache
        (dropped — its blocks lived in the old pool), block tables, and
        the per-slot host mirrors. Called at construction and by the
        supervisor's restart path; cumulative stats and the admission
        queues are deliberately NOT touched — queued requests survive a
        restart and re-prefill against the fresh pool."""
        num_slots = self.num_slots
        if self.paged:
            bk = self._block_k
            self._cache = decode.init_block_pool(
                self.cfg, self.num_blocks, bk, self.dcfg.kv_cache_dtype)
            if self.mesh is not None:
                # Shard the pool over the 'model' axis by KV head: each
                # device holds [L, n_blocks, block_k, Hkv/tp, hd] — the
                # heads its wk/wv shard produces — so per-step writes
                # and attention reads are all-local. The allocator,
                # radix cache and block tables below stay HOST-side and
                # unsharded: paging is a global concern, only the head
                # axis splits.
                shardings = mesh_lib.kv_cache_shardings(self.mesh,
                                                       self._cache)
                self._cache = {
                    name: jax.device_put(arr, shardings[name])
                    for name, arr in self._cache.items()}
            self._allocator = BlockAllocator(self.num_blocks)
            self._radix = RadixPrefixCache(bk, self._allocator)
            # Per-slot block-table mirror; rows of freed slots point at
            # SCRATCH_BLOCK (0). The device copy is cached and
            # invalidated only on admission/eviction, so steady-state
            # ticks skip the host→device upload.
            self._block_table_np = np.zeros(
                (num_slots, self._max_blocks), np.int32)
            self._block_table_dev = None
            # Per-slot allocator refs to drop at eviction + radix path
            # locks to release.
            self._slot_refs: List[List[int]] = [[] for _ in
                                                range(num_slots)]
            self._slot_nodes: List[list] = [[] for _ in range(num_slots)]
        else:
            self._cache = decode.init_kv_cache(self.cfg, num_slots,
                                               self.dcfg.max_len,
                                               self.dcfg.kv_cache_dtype)
        # Chunked-prefill resume state: slot → {'req', 'table', 'p',
        # 'm', 'next'} while an admission is mid-prefill (its device
        # table rows stay scratch-pointed until the last chunk).
        self._prefill_state: List[Optional[dict]] = [None] * num_slots
        # Host mirrors of per-slot device state.
        self._slots: List[Optional[Request]] = [None] * num_slots
        self._token = np.zeros((num_slots,), np.int32)
        self._pos = np.zeros((num_slots,), np.int32)
        self._done = np.ones((num_slots,), bool)
        self._remaining = np.zeros((num_slots,), np.int32)

    # ------------------------------------------------------------ intake

    def submit(self, request: Request) -> Request:
        """Enqueue a request for admission (thread-safe)."""
        request.enqueue_ts = time.perf_counter()
        self.telemetry.on_enqueue(request)
        with self._queue_lock:
            q = self._queues.get(request.tenant)
            if q is None:
                q = self._queues[request.tenant] = collections.deque()
            q.append(request)
            depth = sum(len(d) for d in self._queues.values())
        self._publish_queue_depth(depth)
        return request

    def queue_depth(self) -> int:
        with self._queue_lock:
            return sum(len(d) for d in self._queues.values())

    def _pop_next(self) -> Optional[Request]:
        """Round-robin pop across tenant queues (call without lock)."""
        with self._queue_lock:
            tenants = list(self._queues)
            if not tenants:
                return None
            for i in range(len(tenants)):
                tenant = tenants[(self._rr_offset + i) % len(tenants)]
                q = self._queues[tenant]
                if q:
                    # Next round starts at the FOLLOWING tenant, so one
                    # deep queue cannot shadow the others.
                    self._rr_offset = \
                        (self._rr_offset + i + 1) % len(tenants)
                    req = q.popleft()
                    if not q:
                        del self._queues[tenant]
                    return req
            # Unreachable while the invariant "every dict entry is a
            # non-empty deque" holds (submit appends, pops delete
            # emptied queues); surface a violation instead of silently
            # dropping queued requests.
            assert not any(self._queues.values()), self._queues
            return None

    def _requeue_front(self, request: Request) -> None:
        """Put an un-admittable request back at the head of its tenant
        queue AND park the round-robin pointer on that tenant, so the
        next admission round retries it FIRST — without the pointer
        reset, other tenants' smaller requests would keep draining the
        freed pool ahead of the blocked head-of-line request and starve
        it indefinitely."""
        with self._queue_lock:
            q = self._queues.get(request.tenant)
            if q is None:
                q = self._queues[request.tenant] = collections.deque()
                self._queues.move_to_end(request.tenant, last=False)
            q.appendleft(request)
            self._rr_offset = list(self._queues).index(request.tenant)
            depth = sum(len(d) for d in self._queues.values())
        # Restore the gauge _admit just decremented for the pop — the
        # request is back in the queue, and a starved head-of-line
        # request reading as depth 0 would hide exactly the backlog
        # the pool-pressure runbook tells operators to look for.
        self._publish_queue_depth(depth)

    def _publish_queue_depth(self, depth: Optional[int] = None) -> int:
        """The ONE writer of the queue-depth gauge (submit, requeue,
        admission, and the step profiler all read/publish through
        here)."""
        if depth is None:
            depth = self.queue_depth()
        self._m.gauge('skytpu_engine_queue_depth',
                      'Requests waiting for a free slot.').set(depth)
        return depth

    def free_slots(self) -> int:
        # Cross-thread snapshot over the slot mirror: list length is
        # fixed, entries are GIL-atomic refs — worst case a one-tick-
        # stale count in /healthz-adjacent surfaces.
        return sum(1 for r in self._slots if r is None)  # lint: disable=lock-discipline

    def active_slots(self) -> int:
        return self.num_slots - self.free_slots()

    # --------------------------------------------------------- admission

    def insert(self, request: Request) -> int:
        """Prefill one request and scatter its K/V prefix into a free
        slot; the first token samples from the prefill logits. Returns
        the slot index. Raises RuntimeError when no slot is free (use
        ``submit`` + the engine loop for queued admission) and
        PoolExhausted when the paged pool cannot cover the request even
        after prefix-cache eviction (nothing is mutated; requeue)."""
        slot = next((i for i, r in enumerate(self._slots) if r is None),
                    None)
        if slot is None:
            raise RuntimeError('no free slot')
        p = len(request.prompt)
        if p + request.max_new_tokens > self.dcfg.max_len:
            raise ValueError(
                f'prompt ({p}) + max_new_tokens '
                f'({request.max_new_tokens}) exceeds max_len '
                f'{self.dcfg.max_len}')
        if request.enqueue_ts is None:
            request.enqueue_ts = time.perf_counter()
        admit_ts = time.perf_counter()
        if self.paged:
            first, shared_tokens = self._prefill_paged(slot, request)
            if first is None:
                # Chunked admission: blocks are reserved and the resume
                # state parked; step() runs one chunk per tick and the
                # LAST chunk's logits deliver the first token (TTFT is
                # observed there). The lane stays done=True with
                # scratch-pointed table rows until then.
                self._admitted += 1
                self._chunked_admissions += 1
                self._m.counter('skytpu_engine_admitted_total',
                                'Requests admitted into a slot.').inc()
                self.telemetry.on_admit(
                    request, slot, admit_ts=admit_ts,
                    prefix_hit_tokens=shared_tokens,
                    blocks_reserved=len(self._slot_refs[slot]))
                self._journal(journal.EventKind.ENGINE_ADMIT, request,
                              slot, prompt_len=p,
                              prefix_hit_tokens=shared_tokens,
                              max_new_tokens=request.max_new_tokens,
                              chunked=True,
                              prefill_chunk=self.prefill_chunk)
                self._slots[slot] = request
                self._done[slot] = True
                self._remaining[slot] = 0
                self._publish_slot_gauges()
                return slot
        else:
            shared_tokens = 0
            bucket = self._bucket_for(p)
            self._note_compile('prefill', bucket=bucket)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :p] = request.prompt
            if self.dcfg.temperature == 0.0:
                first_dev, self._cache = _prefill_greedy_impl(
                    self.params, jnp.asarray(padded), jnp.int32(p),
                    jnp.int32(slot), self._cache, cfg=self.cfg)
                first = int(first_dev)
            else:
                last, self._cache = decode.prefill_into_slot(
                    self.params, jnp.asarray(padded), jnp.int32(p),
                    jnp.int32(slot), self.cfg, self._cache)
                first = int(self._sample_first(last))
        self._admitted += 1
        self._m.counter('skytpu_engine_admitted_total',
                        'Requests admitted into a slot.').inc()
        self.telemetry.on_admit(
            request, slot, admit_ts=admit_ts,
            prefix_hit_tokens=shared_tokens,
            blocks_reserved=(len(self._slot_refs[slot]) if self.paged
                             else 0))
        self._journal(journal.EventKind.ENGINE_ADMIT, request, slot,
                      prompt_len=p, prefix_hit_tokens=shared_tokens,
                      max_new_tokens=request.max_new_tokens)
        self._deliver_first(slot, request, first)
        return slot

    def _deliver_first(self, slot: int, request: Request,
                       first: int) -> None:
        """First-token delivery + decode-lane init, shared by direct
        admission and the chunked-prefill finish: TTFT observation, the
        one-token/immediate-EOS fast path (never occupies a decode
        lane), and the per-slot mirror setup."""
        self._m.histogram(
            'skytpu_engine_ttft_seconds',
            'Time from enqueue to first token (includes queueing).',
            buckets=runtime_metrics.TTFT_BUCKETS).observe(
                time.perf_counter() - request.enqueue_ts)
        self._m.counter('skytpu_engine_tokens_total',
                        'Tokens generated by the engine.').inc()
        hit_eos = (self.dcfg.eos_id is not None and
                   first == self.dcfg.eos_id)
        first_done = hit_eos or request.max_new_tokens == 1
        if first_done:
            # Same pre-publication as _deliver_run: done=True must
            # never be observable before the finish reason is.
            request.finish_reason = 'eos' if hit_eos else 'length'
        request._deliver(first, done=first_done)  # pylint: disable=protected-access
        self._slots[slot] = request
        if first_done:
            self._evict(slot, 'eos' if hit_eos else 'length')
            return
        self._token[slot] = first
        self._pos[slot] = len(request.prompt)
        self._done[slot] = False
        self._remaining[slot] = request.max_new_tokens - 1
        self._publish_slot_gauges()

    def _prefill_paged(self, slot: int, request: Request
                       ) -> Tuple[Optional[int], int]:
        """Paged admission: radix-match the prompt, reserve blocks,
        copy-on-write the boundary block of a full hit, prefill only
        the un-cached suffix, then publish the prompt's full blocks to
        the prefix cache. Returns (first token, shared prefix tokens) —
        or (None, shared) when the suffix exceeds ``prefill_chunk``:
        the reservation is made, the resume state parked, and
        :meth:`_advance_prefill` runs one chunk per step.

        Raises PoolExhausted with NO state mutated when the
        reservation cannot be met (caller requeues the request)."""
        bk = self._block_k
        p = len(request.prompt)
        handoff = request.handoff_push is not None
        if handoff and p < bk:
            # Nothing block-aligned to hand off: decode in place (the
            # model server normally filters these before arming, but a
            # direct-to-engine caller must degrade, not wedge).
            request.handoff_push = None
            handoff = False
            self._handoff_degrade(request, 'short_prompt', prompt_len=p)
        blocks, path = self._radix.match(request.prompt)
        m_full = len(blocks) * bk
        if self._should_prefix_fetch(p, m_full, request):
            if self._prefix_fetch_into_cache(request, blocks, m_full):
                # The fetched blocks now live in the pool AND the radix
                # tree: release the stale match and re-match, which
                # picks up the extended prefix with proper refs/locks —
                # from here on a remote hit is indistinguishable from a
                # local one (same COW/reservation/publish invariants).
                self._allocator.decref(blocks)
                self._radix.release(path)
                blocks, path = self._radix.match(request.prompt)
                m_full = len(blocks) * bk
        # Second level of the cold-miss lookup: whatever the peers
        # (or an empty peer set) left uncovered, the durable block
        # store may hold — cold starts, scale-ups and full-fleet
        # restarts warm from disk instead of recomputing. Same
        # re-match contract as the peer path above.
        if self._should_store_fetch(p, m_full):
            if self._store_fetch_into_cache(request, blocks, m_full):
                self._allocator.decref(blocks)
                self._radix.release(path)
                blocks, path = self._radix.match(request.prompt)
                m_full = len(blocks) * bk
        # Keep >= 1 suffix token: the first generated token samples from
        # the last prompt position's logits, which only a forward pass
        # produces (a full-prompt hit caches K/V, not logits).
        m = min(m_full, p - 1)
        first_owned = m // bk
        n_total = -(-(p + request.max_new_tokens) // bk)
        need = n_total - first_owned
        short = need - self._allocator.available()
        if short > 0:
            self._radix_evict(short)
        cow_dst = cow_src = None
        try:
            if m < m_full:
                # Full-prompt hit, m snapped back mid-block: the suffix
                # rewrite lands inside a SHARED block (the tree and our
                # own match ref pin it) — the allocator's copy-on-write
                # hands us a writable clone target.
                cow_src = blocks[first_owned]
                cow_dst, needs_copy = self._allocator.cow(cow_src)
                # The source is pinned by the tree AND our match ref,
                # so cow() always cloned; an in-place grant would alias
                # cow_dst into `blocks` and double-release at evict.
                assert needs_copy, (cow_src, cow_dst)
                owned = ([cow_dst] +
                         self._allocator.alloc(need - 1))
            else:
                needs_copy = False
                owned = self._allocator.alloc(need)
        except PoolExhausted:
            if cow_dst is not None and cow_dst != cow_src:
                self._allocator.decref([cow_dst])
            self._allocator.decref(blocks)
            self._radix.release(path)
            raise
        table = blocks[:first_owned] + owned
        if needs_copy:
            # The boundary COW happens before the chunked/single-shot
            # split — ONE copy of the copy-or-rollback contract.
            try:
                self._cache = decode.copy_block(
                    self._cache, jnp.int32(cow_src), jnp.int32(cow_dst))
            except Exception:
                self._allocator.decref(blocks + owned)
                self._radix.release(path)
                raise
        if handoff or (self.prefill_chunk and
                       (p - m) > self.prefill_chunk):
            # Chunked admission: the reservation (and the boundary COW)
            # happen now — cheap and atomic wrt the pool — but the
            # suffix forward runs one chunk per engine step. The slot's
            # device table rows stay scratch-pointed until the last
            # chunk, so frozen-lane writes from the decode/verify
            # dispatch cannot land in a half-prefilled block (the chunk
            # calls take their block rows explicitly). The radix
            # publish also waits: a prefix is only shareable once its
            # blocks hold real K/V. Handoff requests ride this path
            # even with chunking off (one chunk covering the whole
            # suffix): the per-chunk hook is where completed blocks
            # stream to the decode peer.
            self._slot_refs[slot] = blocks + owned
            self._slot_nodes[slot] = path
            self._prefill_state[slot] = {
                'req': request, 'table': table, 'p': p, 'm': m,
                'next': m, 'chunk': self.prefill_chunk or (p - m),
                'hand': handoff, 'pushed': 0, 'hand_failed': False}
            self._publish_block_gauges()
            return None, m
        try:
            if m == 0:
                bucket = self._bucket_for(p)
                self._note_compile('paged_prefill', bucket=bucket)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :p] = request.prompt
                row = np.full((bucket // bk,), SCRATCH_BLOCK, np.int32)
                nrow = min(len(table), len(row))
                row[:nrow] = table[:nrow]
                last, self._cache = decode.paged_prefill(
                    self.params, jnp.asarray(padded), jnp.int32(p),
                    jnp.asarray(row), self.cfg, self._cache)
            else:
                suf = p - m
                bucket = self._bucket_for(suf)
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :suf] = request.prompt[m:]
                # Gathered-prefix block count buckets to powers of two
                # so compiles stay bounded; padding rows point at
                # scratch and are masked out by prefix_len.
                npb = -(-m // bk)
                npb_bucket = 1
                while npb_bucket < npb:
                    npb_bucket *= 2
                self._note_compile('paged_prefill_with_prefix',
                                   bucket=bucket, npb_bucket=npb_bucket)
                pref = np.full((npb_bucket,), SCRATCH_BLOCK, np.int32)
                pref[:npb] = table[:npb]
                # Suffix writes start inside block m // bk at offset
                # m % bk (the COW clone on a full hit, a fresh block
                # otherwise).
                start = m // bk
                row = np.full((bucket // bk + 1,), SCRATCH_BLOCK,
                              np.int32)
                avail = table[start:start + len(row)]
                row[:len(avail)] = avail
                last, self._cache = decode.paged_prefill_with_prefix(
                    self.params, jnp.asarray(padded), jnp.int32(suf),
                    jnp.int32(m), jnp.asarray(pref), jnp.asarray(row),
                    self.cfg, self._cache)
                self._prompt_tokens_saved += m
                self._m.counter(
                    'skytpu_engine_prefill_tokens_saved_total',
                    'Prompt tokens NOT prefilled thanks to prefix-'
                    'cache hits.').inc(m)
            self._prompt_tokens_total += p
            # Publish the prompt's whole blocks to the prefix cache
            # (the partial tail block — and COW clones — stay
            # private).
            full = p // bk
            if full:
                self._radix.insert(request.prompt[:full * bk],
                                   table[:full])
                self._queue_store_spill(request.prompt[:full * bk])
        except Exception:
            # ANY failure past allocation (device prefill, tracing,
            # bucket lookup) must return the reservation — leaking the
            # refs would shrink the pool forever while _admit's
            # reject path keeps the loop alive. The tree keeps refs it
            # took in insert(); we only return the request's own.
            self._allocator.decref(blocks + owned)
            self._radix.release(path)
            raise
        self._slot_refs[slot] = blocks + owned
        self._slot_nodes[slot] = path
        self._block_table_np[slot, :] = SCRATCH_BLOCK
        self._block_table_np[slot, :n_total] = table
        self._block_table_dev = None
        self._publish_block_gauges()
        if self.dcfg.temperature == 0.0:
            # Device-side argmax: ship 4 bytes, not the vocab-sized
            # logits row (the dense fast path fuses this into the
            # prefill dispatch; one extra tiny dispatch is fine here).
            first = int(jnp.argmax(last))
        else:
            first = int(self._sample_first(last))
        return first, m

    def _bucket_for(self, n: int) -> int:
        """Smallest prefill bucket covering ``n`` tokens; a proper
        ValueError (→ journaled reject) instead of a loop-killing
        StopIteration when custom buckets don't reach max_len."""
        for b in self._buckets:
            if b >= n:
                return b
        raise ValueError(f'no prefill bucket >= {n} '
                         f'(buckets: {self._buckets})')

    def _sample_first(self, last_logits: jax.Array) -> int:
        self._rng, key = jax.random.split(self._rng)
        return int(decode._sample(last_logits[None], key,  # pylint: disable=protected-access
                                  self.dcfg.temperature)[0])

    def _admit(self) -> int:
        """Fill free slots from the tenant queues (round-robin);
        returns admissions made. Over-budget requests are clamped or
        rejected with a journaled ``engine.reject`` — the serve loop
        never dies on one bad request."""
        n = 0
        while True:
            if self.free_slots() == 0:
                break
            req = self._pop_next()
            if req is None:
                break
            self._publish_queue_depth()
            p = len(req.prompt)
            budget = self.dcfg.max_len - p
            if self.paged:
                # An undersized pool caps the servable length below
                # max_len: a reservation larger than the whole pool
                # would otherwise requeue forever (livelock, not
                # backpressure).
                budget = min(budget,
                             (self.num_blocks - 1) * self._block_k - p)
            if budget < 1:
                self._reject(req, 'prompt_too_long', prompt_len=p,
                             max_len=self.dcfg.max_len)
                continue
            if req.max_new_tokens > budget:
                # Clamp rather than kill: the prompt fits, only the
                # generation budget overshoots. Journaled so the
                # truncation is attributable after the fact.
                self._journal(journal.EventKind.ENGINE_REJECT, req, -1,
                              action='clamp', prompt_len=p,
                              requested=req.max_new_tokens,
                              clamped_to=budget)
                req.max_new_tokens = budget
            try:
                self.insert(req)
                n += 1
            except PoolExhausted:
                # Not an error: blocks are busy. Head-of-line blocks
                # until eviction frees pool space (admission order is
                # preserved — skipping ahead would starve big
                # requests).
                self._requeue_front(req)
                break
            except ValueError as e:
                self._reject(req, f'error: {e}')
            except Exception as e:
                # Crash mid-admission: the request was already popped
                # from its queue and is not yet slotted — finish it NOW
                # (its client learns instantly via on_finish) before
                # re-raising to the supervisor, or it would be the one
                # request neither the fail-fast sweep nor the queue
                # replay covers, silently riding out the full request
                # timeout.
                self._fail_request(req, f'admission crashed: {e}')
                raise
        return n

    def _reject(self, req: Request, reason: str, **payload) -> None:
        """Terminal rejection (the request's fault: unservable prompt,
        bad budget) — clients see a 4xx."""
        self._finish_unadmitted(req, f'rejected: {reason}',
                                action='reject', reason=reason, **payload)

    def _fail_request(self, req: Request, reason: str, **payload) -> None:
        """Terminal server-side failure (engine crash, permanent fail)
        for a request that never got a slot — finish as 'error: ...' so
        the model server answers 500, not the 422 a rejection earns."""
        self._finish_unadmitted(req, f'error: {reason}',
                                action='error', reason=reason, **payload)

    def _finish_unadmitted(self, req: Request, finish_reason: str,
                           **payload) -> None:
        self._journal(journal.EventKind.ENGINE_REJECT, req, -1, **payload)
        self._m.counter('skytpu_engine_rejected_total',
                        'Requests rejected at admission.').inc()
        req._finish(finish_reason)  # pylint: disable=protected-access
        slow = self.telemetry.on_finish(req, req.finish_reason)
        if slow is not None:
            self._journal(journal.EventKind.ENGINE_SLOW_REQUEST, req, -1,
                          **slow)

    # -------------------------------------------------- chunked prefill

    def _advance_prefill(self) -> int:
        """Advance every prefilling slot by ONE chunk; returns the
        total prefill tokens processed — the fairness half of chunked
        prefill: each long admission does a bounded ``prefill_chunk``
        of work per engine step while every decode lane keeps stepping,
        so ONE long prompt can no longer freeze TTFT for every other
        lane. The bound is per prompt, not global: serializing all
        admissions through a single chunk per step would collapse
        admission bandwidth exactly when a burst of long prompts fills
        the slots (lanes parked prefilling deliver nothing)."""
        if not self.paged:
            return 0
        total = 0
        for slot, st in enumerate(self._prefill_state):
            if st is not None:
                total += self._advance_prefill_slot(slot)
        return total

    def _advance_prefill_slot(self, slot: int) -> int:
        """Run one chunk of one slot's pending prefill; returns its
        token count."""
        st = self._prefill_state[slot]
        req = st['req']
        bk = self._block_k
        p, table = st['p'], st['table']
        start = st['next']
        # Per-slot chunk: handoff admissions park the whole suffix as
        # one chunk when global chunking is off.
        chunk = st.get('chunk') or self.prefill_chunk
        end = min(start + chunk, p)
        suf = end - start
        bucket = self._bucket_for(suf)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :suf] = req.prompt[start:end]
        if start == 0:
            # First chunk of a cold prompt: plain block-scatter prefill
            # over the chunk (in-bucket padding spills into blocks later
            # chunks overwrite; never attended — prefix_len masks it).
            self._note_compile('paged_prefill', bucket=bucket,
                               chunk=chunk)
            row = np.full((bucket // bk,), SCRATCH_BLOCK, np.int32)
            nrow = min(len(table), len(row))
            row[:nrow] = table[:nrow]
            last, self._cache = decode.paged_prefill(
                self.params, jnp.asarray(padded), jnp.int32(suf),
                jnp.asarray(row), self.cfg, self._cache)
        else:
            # Resume chunk: the already-written positions [0, start)
            # ARE a prefix in the pool — the radix-hit prefill path
            # handles arbitrary offsets, so it is reused verbatim.
            npb = -(-start // bk)
            npb_bucket = 1
            while npb_bucket < npb:
                npb_bucket *= 2
            self._note_compile('paged_prefill_with_prefix',
                               bucket=bucket, npb_bucket=npb_bucket,
                               chunk=chunk)
            pref = np.full((npb_bucket,), SCRATCH_BLOCK, np.int32)
            pref[:npb] = table[:npb]
            srow = start // bk
            row = np.full((bucket // bk + 1,), SCRATCH_BLOCK, np.int32)
            avail = table[srow:srow + len(row)]
            row[:len(avail)] = avail
            last, self._cache = decode.paged_prefill_with_prefix(
                self.params, jnp.asarray(padded), jnp.int32(suf),
                jnp.int32(start), jnp.asarray(pref), jnp.asarray(row),
                self.cfg, self._cache)
        st['next'] = end
        self._prefill_chunks += 1
        self._m.counter(
            'skytpu_engine_prefill_chunks_total',
            'Prefill chunks executed by chunked admissions.').inc()
        if st.get('hand') and not st.get('hand_failed'):
            # Stream the chunk's newly-completed full blocks to the
            # decode peer BEFORE the finish check: by the time
            # _finish_prefill runs, either every aligned block was
            # acked (handoff completes, slot frees) or the slot flipped
            # to degraded decode-in-place.
            self._push_handoff_chunk(st)
        if end >= p:
            self._finish_prefill(slot, st, last)
        return suf

    def _finish_prefill(self, slot: int, st: dict, last) -> None:
        """Last chunk done: publish the prompt to the prefix cache,
        install the real table (the lane's frozen writes may touch its
        own blocks from now on — they hold real K/V), deliver the first
        token, and join the decode lanes."""
        req = st['req']
        p, m, table = st['p'], st['m'], st['table']
        bk = self._block_k
        if m:
            self._prompt_tokens_saved += m
            self._m.counter(
                'skytpu_engine_prefill_tokens_saved_total',
                'Prompt tokens NOT prefilled thanks to prefix-'
                'cache hits.').inc(m)
        self._prompt_tokens_total += p
        if st.get('hand') and not st.get('hand_failed'):
            # Resolve the final in-flight push before judging the
            # handoff: an unacked tail would hand the stream to a peer
            # that never got it. A failure here degrades and falls
            # through to the normal decode-in-place finish below.
            self._await_handoff_ack(st)
        if st.get('hand') and not st.get('hand_failed'):
            # Every aligned block was pushed and acked: the decode peer
            # owns the stream from here. NO radix publish and NO first
            # token on this side — evicting returns every reserved
            # block to the pool, so the prefill tier's pool turns over
            # per burst instead of accreting a cache nobody decodes
            # against.
            self._handoffs_completed += 1
            self._m.counter(
                'skytpu_engine_handoffs_total',
                'Full-request KV handoff attempts by outcome.',
                labels=('result',)).inc(labels=('complete',))
            self._journal(journal.EventKind.ENGINE_HANDOFF, req, slot,
                          outcome='complete',
                          tokens_pushed=st['pushed'] * bk,
                          peer=req.handoff_peer)
            self._evict(slot, 'handoff')
            return
        full = p // bk
        if full:
            self._radix.insert(req.prompt[:full * bk], table[:full])
            self._queue_store_spill(req.prompt[:full * bk])
        self._block_table_np[slot, :] = SCRATCH_BLOCK
        self._block_table_np[slot, :len(table)] = table
        self._block_table_dev = None
        self._prefill_state[slot] = None
        self._publish_block_gauges()
        if self.dcfg.temperature == 0.0:
            first = int(jnp.argmax(last))
        else:
            first = int(self._sample_first(last))
        self._deliver_first(slot, req, first)

    # ------------------------------------- disaggregated P/D handoff

    def _handoff_degrade(self, req: Request, reason: str,
                         **payload) -> None:
        """One degraded handoff: the request decodes in place on this
        (prefill) replica — journaled and counted, the stream is still
        answered. Degrade is the ONLY failure mode: a handoff must
        never turn into a hung stream."""
        self._handoffs_degraded += 1
        self._m.counter(
            'skytpu_engine_handoffs_total',
            'Full-request KV handoff attempts by outcome.',
            labels=('result',)).inc(labels=('degraded',))
        self._journal(journal.EventKind.ENGINE_HANDOFF, req, -1,
                      outcome='degraded', reason=reason, **payload)

    def _export_slot_blocks(self, send: List[int],
                            from_tokens: int) -> dict:
        """LOOP-THREAD ONLY: device-read an explicit block list from
        the pool into the PR 15 wire format — the handoff twin of
        :meth:`_export_prefix_now` (same bucketed gather, same
        owner-side TP assembly), except the blocks come from a
        still-prefilling slot's reserved table instead of a radix
        match: mid-prefill blocks are not in the tree yet, and
        ``_slot_refs`` already pins them for the read."""
        bk = self._block_k
        bucket = 1
        while bucket < len(send):
            bucket *= 2
        self._note_compile('prefix_export', blocks=bucket)
        idx_np = np.full((bucket,), SCRATCH_BLOCK, np.int32)
        idx_np[:len(send)] = send
        idx = jnp.asarray(idx_np)
        arrays = {
            name: np.asarray(
                jax.device_get(arr[:, idx]))[:, :len(send)]
            for name, arr in self._cache.items()}
        return {
            'matched_tokens': from_tokens + len(send) * bk,
            'from_tokens': from_tokens,
            'block_k': bk,
            'kv_cache_dtype': self.dcfg.kv_cache_dtype,
            'arrays': arrays,
        }

    def _handoff_executor(self) -> concurrent.futures.ThreadPoolExecutor:
        """Lazy: engines that never hand off never pay the threads."""
        if self._handoff_pool is None:
            self._handoff_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=max(2, min(self.num_slots, 8)),
                thread_name_prefix=f'{self.name}-handoff')
        return self._handoff_pool

    def _await_handoff_ack(self, st: dict) -> bool:
        """Resolve the slot's in-flight handoff push, if any. On ack
        the pushed watermark advances and the peer's backoff clears; on
        failure/timeout the slot flips to degraded decode-in-place and
        the peer backs off. Returns whether the handoff is still
        live."""
        pend = st.pop('hand_fut', None)
        if pend is None:
            return not st.get('hand_failed')
        fut, end_blocks, prev = pend
        req = st['req']
        bk = self._block_k
        budget = common_utils.env_float(
            prefix_transfer.PUSH_BUDGET_ENV,
            prefix_transfer.DEFAULT_PUSH_BUDGET_SECONDS)
        try:
            # The transport's own budget bounds the push; this outer
            # timeout only catches a wedged transport (an abandoned
            # future is harmless: the payload is a snapshot).
            ok = bool(fut.result(timeout=max(2.0 * budget, 1.0)))
            err = None
        except Exception as e:  # pylint: disable=broad-except
            ok = False
            err = f'{type(e).__name__}: {e}'
        if ok:
            st['pushed'] = end_blocks
            self._handoff_tokens_pushed += (end_blocks - prev) * bk
            if req.handoff_peer:
                self._peer_backoff_until.pop(req.handoff_peer, None)
            return True
        st['hand_failed'] = True
        if req.handoff_peer:
            self._note_peer_failure(req.handoff_peer)
        self._handoff_degrade(req, 'push_failed', error=err,
                              peer=req.handoff_peer,
                              tokens_pushed=st['pushed'] * bk)
        return False

    def _push_handoff_chunk(self, st: dict) -> None:
        """Prefill side of a handoff: stream the slot's newly-completed
        FULL blocks to the decode peer. The partial tail block never
        ships — the decode replica re-prefills the unaligned suffix
        itself, which is also what makes the handed-off stream
        token-identical to monolithic serving: its first token samples
        from last-position logits the decode replica computed.

        The push is double-buffered: the device read happens here on
        the loop thread, but the wire transfer rides the handoff
        executor, so chunk k streams to the peer WHILE chunk k+1
        prefills. At most one push is in flight per slot — the
        previous ack is awaited before the next export — which keeps
        payloads ordered (the decode side rejects gaps) and bounds
        host memory to one chunk of blocks. Any failure flips the slot
        to degraded decode-in-place and backs the peer off; it never
        raises into the step loop."""
        req = st['req']
        bk = self._block_k
        end_blocks = min(st['next'], st['p']) // bk
        if not self._await_handoff_ack(st):
            return
        pushed = st['pushed']
        if end_blocks <= pushed:
            return
        send = st['table'][pushed:end_blocks]
        try:
            payload = self._export_slot_blocks(send, pushed * bk)
        except Exception as e:  # pylint: disable=broad-except
            st['hand_failed'] = True
            if req.handoff_peer:
                self._note_peer_failure(req.handoff_peer)
            self._handoff_degrade(req, 'push_failed',
                                  error=f'{type(e).__name__}: {e}',
                                  peer=req.handoff_peer,
                                  tokens_pushed=pushed * bk)
            return
        st['hand_fut'] = (
            self._handoff_executor().submit(
                req.handoff_push, req.prompt[:end_blocks * bk],
                payload),
            end_blocks, pushed)

    def inject_handoff_blocks(self, tokens: Sequence[int],
                              payload: dict,
                              timeout: float = 5.0) -> dict:
        """Cross-thread handoff injection (the model server's
        ``/handoff_blocks`` handler and in-process benches): enqueue
        the pushed blocks for the engine loop to install at its next
        tick and wait bounded. Returns ``{'ok': bool, ...}`` — a
        non-ok reply makes the prefill side degrade. The
        ``handoff_decode_death`` chaos point fires HERE (the receiving
        replica dies mid-handoff) so both the HTTP and in-process
        paths exercise the prefill side's degrade."""
        chaos.maybe_raise('handoff_decode_death')
        if not self.paged:
            return {'ok': False, 'error': 'not_paged'}
        job = {'kind': 'inject', 'tokens': list(tokens),
               'payload': payload, 'event': threading.Event(),
               'result': None,
               'deadline': time.monotonic() + timeout}
        with self._export_lock:
            self._export_jobs.append(job)
        if job['event'].wait(timeout):
            res = job['result']
            if res is None:
                return {'ok': False, 'error': 'inject_failed'}
            return res
        return {'ok': False, 'error': 'timeout'}

    def _inject_handoff_now(self, tokens: List[int],
                            payload: dict) -> dict:
        """LOOP-THREAD ONLY: install one pushed handoff chunk.
        Incremental and idempotent against the radix tree: the pushed
        blocks extend whatever prefix is already cached for these
        tokens (an already-covered push is an ok no-op; a push whose
        ``from_tokens`` is past our coverage would leave a hole and is
        refused). Validation — dtype/shape/block_k-exact — is shared
        with the PR 15 fetch path via :meth:`_install_remote_blocks`.
        """
        bk = self._block_k
        tokens = [int(t) for t in tokens]
        try:
            matched = int(payload.get('matched_tokens', 0))
            from_tokens = int(payload.get('from_tokens', 0))
        except (TypeError, ValueError):
            matched = from_tokens = -1
        if (matched <= 0 or matched % bk or from_tokens < 0
                or from_tokens % bk or matched > len(tokens)):
            return self._handoff_inject_result(
                {'ok': False, 'error': 'malformed'})
        blocks, path = self._radix.match(tokens[:matched])
        m_d = len(blocks) * bk
        try:
            if matched <= m_d:
                # Already covered (an earlier push, or a warm cache):
                # idempotent ok — the prefill side keeps streaming.
                return self._handoff_inject_result(
                    {'ok': True, 'gained': 0})
            if from_tokens > m_d:
                # The push assumes blocks that were never installed (a
                # lost earlier chunk): refusing keeps the tree
                # hole-free.
                return self._handoff_inject_result(
                    {'ok': False, 'error': 'gap'})
            skip = (m_d - from_tokens) // bk
            arrays = payload.get('arrays') or {}
            if skip:
                arrays = {name: a[:, skip:]
                          for name, a in arrays.items()}
            adj = dict(payload, arrays=arrays, from_tokens=m_d)
            gained = self._install_remote_blocks(
                tokens[:matched], adj, blocks, m_d)
        finally:
            self._allocator.decref(blocks)
            self._radix.release(path)
        if gained == 'empty':
            return self._handoff_inject_result({'ok': True, 'gained': 0})
        if gained == 'pool_exhausted' or gained is None:
            return self._handoff_inject_result(
                {'ok': False, 'error': (gained or 'mismatch')})
        self._handoff_injections += 1
        self._handoff_tokens_injected += gained
        return self._handoff_inject_result(
            {'ok': True, 'gained': gained})

    def _handoff_inject_result(self, res: dict) -> dict:
        result = 'inject' if res.get('ok') else 'inject_error'
        self._m.counter(
            'skytpu_engine_handoffs_total',
            'Full-request KV handoff attempts by outcome.',
            labels=('result',)).inc(labels=(result,))
        self._journal_raw(
            journal.EventKind.ENGINE_HANDOFF,
            {'outcome': result,
             **{k: v for k, v in res.items() if k != 'ok'}})
        return res

    def handoff_stats(self) -> dict:
        """The ``/slo`` ``handoff`` block: disaggregated
        prefill/decode counters for one engine, both directions.
        Snapshot reads of loop-owned ints (stale-by-one-tick at worst,
        never torn)."""
        return {
            'completed': self._handoffs_completed,
            'degraded': self._handoffs_degraded,
            'tokens_pushed': self._handoff_tokens_pushed,
            'injections': self._handoff_injections,
            'tokens_injected': self._handoff_tokens_injected,
        }

    # ---------------------------------------- cross-replica prefix tier

    def _radix_evict(self, need: int) -> int:
        """The ONE gateway to radix LRU eviction: counts freed blocks
        so locality numbers can be read against cache pressure."""
        freed = self._radix.evict(need)
        if freed:
            self._prefix_evictions += freed
            self._m.counter(
                'skytpu_engine_prefix_evictions_total',
                'Prefix-cache blocks LRU-evicted under pool '
                'pressure.').inc(freed)
        return freed

    def _should_prefix_fetch(self, p: int, m_full: int,
                             request: Request) -> bool:
        """Consult peers only when a fetch could actually help: peers
        (or an LB owner hint) exist, and the local radix miss leaves at
        least the minimum block-aligned gain on the table."""
        if not self.paged:
            return False
        if not self.prefix_peers:
            # No configured trust set: nothing to fetch from (the LB
            # hint alone cannot introduce URLs — see
            # _prefix_fetch_peers).
            return False
        aligned = (p // self._block_k) * self._block_k
        return aligned - m_full >= max(self._prefix_fetch_min_tokens,
                                       self._block_k)

    def register_self_url(self, url: str) -> None:
        """Model-server hook: URLs that address THIS replica are never
        fetched from (a self-fetch stalls the loop for a whole budget —
        the export queue is serviced by the fetching thread itself)."""
        self._prefix_self_urls.add(url.rstrip('/'))

    def _prefix_fetch_peers(self, request: Request) -> List[str]:
        """The configured peer list, minus self and peers in failure
        backoff. The LB-advertised owner hint only REORDERS the
        configured set (a matching peer moves to the front) — it can
        never introduce a new URL, because the hint rides an HTTP
        header any direct-to-replica client can set, and fetching from
        (= injecting KV blocks published to every tenant from) an
        unvetted URL is prompt exfiltration + cache poisoning. The
        peer list is the trust set."""
        now = time.perf_counter()
        peers = []
        hint = (request.prefix_hint or '').rstrip('/')
        candidates = sorted(
            self.prefix_peers,
            key=lambda u: 0 if u.rstrip('/') == hint else 1)
        for u in candidates:
            if (u and u not in peers
                    and u.rstrip('/') not in self._prefix_self_urls
                    and self._peer_backoff_until.get(u, 0.0) <= now):
                peers.append(u)
        return peers

    def _note_peer_failure(self, peer: str) -> None:
        self._peer_backoff_until[peer] = (time.perf_counter() +
                                          self._prefix_fetch_backoff)

    def peer_in_backoff(self, peer: str) -> bool:
        """Model-server hook: is this peer inside its failure-backoff
        window? Arming a handoff at a peer that just failed would burn
        a push budget per chunk only to degrade — the server degrades
        up front instead."""
        return self._peer_backoff_until.get(peer,
                                            0.0) > time.perf_counter()

    def _prefix_fetch_into_cache(self, request: Request,
                                 local_blocks: List[int],
                                 m_full: int) -> bool:
        """Try to pull the prompt's missing prefix blocks from a peer
        and install them in the pool + radix tree. Returns True when
        the tree now holds a longer prefix (caller re-matches). Bounded
        by the fetch budget; ANY failure — timeout, malformed payload,
        dtype/shape mismatch, pool exhaustion — degrades to plain
        prefill with the outcome journaled as ``engine.prefix_fetch``.
        """
        bk = self._block_k
        p = len(request.prompt)
        aligned = (p // bk) * bk
        deadline = time.perf_counter() + self.prefix_fetch_budget
        t0 = time.perf_counter()
        outcome = 'miss'
        peers_tried = self._prefix_fetch_peers(request)
        for peer in peers_tried:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                outcome = 'budget_exhausted'
                break
            try:
                payload = self._prefix_fetch_fn(
                    peer, request.prompt[:aligned], m_full, remaining)
            except Exception as e:  # pylint: disable=broad-except
                # A misbehaving peer/transport must never crash
                # admission; the outcome is journaled below and the
                # peer sits out a backoff window.
                self._note_peer_failure(peer)
                outcome = 'error'
                self._journal(journal.EventKind.ENGINE_PREFIX_FETCH,
                              request, -1, outcome='error', peer=peer,
                              error=f'{type(e).__name__}: {e}')
                continue
            if payload is None:
                # Transport-level failure (timeout, non-200, garbage
                # bytes): back the peer off. An honest empty match is
                # a well-formed payload and lands in the 'empty' branch
                # below — it does NOT penalize the peer.
                self._note_peer_failure(peer)
                continue
            if payload.get('self'):
                # The peer answered "I am you" (instance-id echo): this
                # URL is one of OUR addresses under a fleet-shared
                # peers list — exclude it permanently, instantly.
                self.register_self_url(peer)
                continue
            try:
                gained = self._inject_fetched_prefix(
                    request, peer, payload, local_blocks, m_full)
            except Exception as e:  # pylint: disable=broad-except
                # Materialization failure past validation (host OOM on
                # the padded bucket, XLA resource exhaustion): the
                # contract is degrade-to-prefill, never crash the step
                # (the inner handler already returned the allocator
                # refs).
                self._note_peer_failure(peer)
                outcome = 'error'
                self._journal(journal.EventKind.ENGINE_PREFIX_FETCH,
                              request, -1, outcome='error', peer=peer,
                              error=f'{type(e).__name__}: {e}')
                continue
            if gained == 'empty':
                # Peer reachable, just cold: the admission-level
                # outcome is a MISS even if an earlier peer errored
                # (those failures were journaled per-peer) — dashboards
                # reading the result label as peer health must not see
                # a flaky peer masking a healthy fleet's honest cold.
                outcome = 'miss'
                continue
            if gained is None:
                # Version-skewed peer (wrong block_k / dtype / shapes):
                # back it off like a dead one — re-downloading and
                # discarding full KV payloads every cold admission is
                # the same per-admission stall class.
                self._note_peer_failure(peer)
                outcome = 'mismatch'
                continue
            if gained == 'pool_exhausted':
                outcome = 'pool_exhausted'
                break
            self._peer_backoff_until.pop(peer, None)
            self._prefix_fetch_hits += 1
            self._prefix_fetch_tokens += gained
            self._m.counter(
                'skytpu_engine_prefix_fetches_total',
                'Cross-replica prefix-block fetch attempts by '
                'outcome.', labels=('result',)).inc(labels=('hit',))
            self._journal(journal.EventKind.ENGINE_PREFIX_FETCH,
                          request, -1, outcome='hit', peer=peer,
                          tokens_gained=gained,
                          blocks_gained=gained // bk,
                          seconds=round(time.perf_counter() - t0, 6))
            return True
        self._prefix_fetch_misses += 1
        self._m.counter(
            'skytpu_engine_prefix_fetches_total',
            'Cross-replica prefix-block fetch attempts by outcome.',
            labels=('result',)).inc(labels=(outcome,))
        # peers_tried, not a recomputation: the failed peers were just
        # placed in backoff, and the row must record what was actually
        # consulted.
        self._journal(journal.EventKind.ENGINE_PREFIX_FETCH, request,
                      -1, outcome=outcome, peers=len(peers_tried),
                      seconds=round(time.perf_counter() - t0, 6))
        return False

    def _inject_fetched_prefix(self, request: Request, peer: str,
                               payload: dict, local_blocks: List[int],
                               m_full: int):
        """Fetch-path wrapper over :meth:`_install_remote_blocks` (the
        outcome journaling — with ``peer`` — happens in the caller)."""
        del peer
        return self._install_remote_blocks(request.prompt, payload,
                                           local_blocks, m_full)

    def _install_remote_blocks(self, prompt_tokens: Sequence[int],
                               payload: dict, local_blocks: List[int],
                               m_full: int):
        """Validate + install one peer payload: allocate pool blocks,
        scatter the fetched K/V (dtype-exact — int8 values and scale
        planes transfer verbatim), publish the extended prefix to the
        radix tree. Shared by the PR 15 prefix fetch and the handoff
        injection — SAME wire format, SAME validation. Returns tokens
        gained, ``'empty'`` (peer holds nothing past what we have — a
        miss, not a protocol error), ``'pool_exhausted'``, or None on
        a validation mismatch."""
        bk = self._block_k
        p = len(prompt_tokens)
        aligned = (p // bk) * bk
        matched = int(payload.get('matched_tokens', 0))
        arrays = payload.get('arrays') or {}
        if matched <= m_full or not arrays:
            return 'empty'
        if (payload.get('block_k') != bk or
                payload.get('kv_cache_dtype') != self.dcfg.kv_cache_dtype
                or payload.get('from_tokens') != m_full
                or matched % bk or matched > aligned):
            return None
        if set(arrays) != set(self._cache):
            return None
        n_new = (matched - m_full) // bk
        for name, pool_arr in self._cache.items():
            want = ((pool_arr.shape[0], n_new) + pool_arr.shape[2:])
            if tuple(arrays[name].shape) != want:
                return None
            # dtype must match EXACTLY: inject_pool_blocks' astype is a
            # VALUE cast, and bytes decoded under the wrong dtype (a
            # version-skewed peer) would cast into plausible-looking
            # garbage K/V instead of failing — silently wrong
            # generations for every future sharer of the prefix.
            if arrays[name].dtype != np.dtype(pool_arr.dtype):
                return None
        short = n_new - self._allocator.available()
        if short > 0:
            self._radix_evict(short)
        try:
            new_blocks = self._allocator.alloc(n_new)
        except PoolExhausted:
            return 'pool_exhausted'
        try:
            # Power-of-two bucket the scatter shape (pad the index row
            # with the scratch block — its writes are harmless by
            # design — and the values with zeros): one jit trace per
            # bucket instead of one per distinct fetched-block count,
            # and the trace is journaled like every other engine
            # dispatch shape.
            bucket = 1
            while bucket < n_new:
                bucket *= 2
            self._note_compile('prefix_inject', blocks=bucket)
            idx_np = np.full((bucket,), SCRATCH_BLOCK, np.int32)
            idx_np[:n_new] = new_blocks
            values = {}
            for name in self._cache:
                a = arrays[name]
                if bucket > n_new:
                    pad = np.zeros((a.shape[0], bucket - n_new) +
                                   a.shape[2:], a.dtype)
                    a = np.concatenate([a, pad], axis=1)
                values[name] = jnp.asarray(a)
            self._cache = decode.inject_pool_blocks(
                self._cache, jnp.asarray(idx_np), values)
            # Publish [0, matched) to the tree: the already-cached
            # branch dedupes, the fetched suffix is adopted (tree
            # takes its refs)...
            self._radix.insert(list(prompt_tokens[:matched]),
                               local_blocks[:m_full // bk] + new_blocks)
        except Exception:
            self._allocator.decref(new_blocks)
            raise
        # ...then drop OUR alloc refs: the blocks are tree-owned now,
        # and the caller's re-match takes the request's own refs.
        self._allocator.decref(new_blocks)
        self._publish_block_gauges()
        return matched - m_full

    # ------------------------------------------- durable block-store tier

    def _should_store_fetch(self, p: int, m_full: int) -> bool:
        """Second-level lookup gate: a store is configured, it is not
        in failure backoff, and the residual miss (after the local
        match AND any peer fetch) still leaves the minimum
        block-aligned gain on the table."""
        if not self.paged or not self.store_url:
            return False
        if self._store_backoff_until > time.perf_counter():
            return False
        aligned = (p // self._block_k) * self._block_k
        return aligned - m_full >= max(self._prefix_fetch_min_tokens,
                                       self._block_k)

    def _note_store_failure(self) -> None:
        self._store_backoff_until = (time.perf_counter() +
                                     self._store_backoff)

    def store_in_backoff(self) -> bool:
        """Snapshot: is the durable store inside its failure-backoff
        window (surfaced on /slo)?"""
        return self._store_backoff_until > time.perf_counter()

    def _store_fetch_into_cache(self, request: Request,
                                local_blocks: List[int],
                                m_full: int) -> bool:
        """Pull the prompt's missing prefix blocks from the DURABLE
        store and install them — the store twin of
        :meth:`_prefix_fetch_into_cache`, sharing
        :meth:`_install_remote_blocks` so a store-warmed decode
        inherits the exact validation that makes a peer-warmed one
        token-identical to local prefill. ANY failure (store down,
        torn entry served as miss, dtype/shape mismatch, pool
        exhaustion) degrades to plain prefill; transport failures put
        the store in backoff."""
        bk = self._block_k
        aligned = (len(request.prompt) // bk) * bk
        t0 = time.perf_counter()
        outcome = 'miss'
        gained = 0
        try:
            payload = self._store_fetch_fn(
                self.store_url, request.prompt[:aligned], m_full,
                self._store_fetch_budget)
        except Exception as e:  # pylint: disable=broad-except
            payload = None
            self._note_store_failure()
            outcome = 'error'
            self._journal(journal.EventKind.ENGINE_STORE_FETCH,
                          request, -1, outcome='error',
                          store=self.store_url,
                          error=f'{type(e).__name__}: {e}')
        if outcome != 'error':
            if payload is None:
                # Transport failure (down, timeout, garbage): back the
                # store off — one dead store must not cost every cold
                # admission a budget-long loop stall.
                self._note_store_failure()
                outcome = 'down'
            elif payload.get('self'):
                # A store URL pointing at THIS replica (misconfig):
                # treat as no store at all.
                self.store_url = None
                outcome = 'miss'
            else:
                try:
                    gained = self._install_remote_blocks(
                        request.prompt, payload, local_blocks, m_full)
                except Exception as e:  # pylint: disable=broad-except
                    self._note_store_failure()
                    gained = 0
                    outcome = 'error'
                    self._journal(journal.EventKind.ENGINE_STORE_FETCH,
                                  request, -1, outcome='error',
                                  store=self.store_url,
                                  error=f'{type(e).__name__}: {e}')
                else:
                    if gained == 'empty':
                        outcome = 'miss'
                    elif gained is None:
                        # Version-skewed store entry (wrong block_k /
                        # dtype / shape): a mismatch, not an outage —
                        # other families may still be served, so no
                        # backoff, but the row records the evidence.
                        outcome = 'mismatch'
                    elif gained == 'pool_exhausted':
                        outcome = 'pool_exhausted'
                    else:
                        outcome = 'hit'
        hit = outcome == 'hit'
        if hit:
            self._store_fetch_hits += 1
            self._store_fetch_tokens += gained
        else:
            self._store_fetch_misses += 1
        self._m.counter(
            'skytpu_store_fetches_total',
            'Durable-store prefix-block fetch attempts by outcome.',
            labels=('result',)).inc(labels=(outcome,))
        if outcome != 'error':
            # (error outcomes journaled above, with the exception text)
            payload_kw = {'tokens_gained': gained,
                          'blocks_gained': gained // bk} if hit else {}
            self._journal(journal.EventKind.ENGINE_STORE_FETCH, request,
                          -1, outcome=outcome, store=self.store_url,
                          seconds=round(time.perf_counter() - t0, 6),
                          **payload_kw)
        return hit

    def _queue_store_spill(self, tokens: Sequence[int]) -> None:
        """LOOP-THREAD ONLY: remember one newly published radix run
        for write-behind spill to the durable store. Dedup'd (a shared
        system prompt publishes on every admission that extends it —
        one spill per distinct run), bounded (the queue holds the 16
        newest runs; older drops are counted, not silently lost)."""
        if not (self.paged and self.store_url
                and self._store_spill_fn is not None):
            return
        tokens = [int(t) for t in tokens]
        if len(tokens) < max(self._store_spill_min_tokens,
                             self._block_k):
            return
        key = (len(tokens), hash(tuple(tokens)))
        if key in self._spill_seen:
            return
        if len(self._spill_seen) > 4096:
            self._spill_seen.clear()
        self._spill_seen.add(key)
        self._spill_pending.append(tokens)
        if len(self._spill_pending) > 16:
            self._spill_pending.pop(0)
            self._store_spill_drops += 1

    def _spill_executor(self) -> concurrent.futures.ThreadPoolExecutor:
        if self._spill_pool is None:
            self._spill_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f'skytpu-spill-{self.name}')
        return self._spill_pool

    def _service_store_spills(self) -> None:
        """LOOP-THREAD ONLY (top of step): resolve the previous spill
        POST's outcome, then launch at most one new spill — the loop
        pays one radix match + device gather per spill; the wire time
        rides the worker thread. A failed POST puts the store in the
        shared fetch/spill backoff."""
        if self._spill_inflight is not None:
            fut, tokens = self._spill_inflight
            if not fut.done():
                return
            self._spill_inflight = None
            try:
                ok = bool(fut.result())
            except Exception:  # pylint: disable=broad-except
                ok = False
            if ok:
                self._store_spills += 1
                self._store_spill_tokens += len(tokens)
                self._m.counter(
                    'skytpu_store_spills_total',
                    'Write-behind spills to the durable store by '
                    'outcome.', labels=('result',)).inc(labels=('ok',))
                self._journal_raw(journal.EventKind.STORE_SPILL,
                                  {'outcome': 'ok',
                                   'tokens': len(tokens),
                                   'store': self.store_url})
            else:
                self._store_spill_failures += 1
                self._note_store_failure()
                # Re-queue once the backoff clears? No: the run is
                # still in the radix tree and republish-on-extend will
                # re-offer it; retrying here would hammer a dead store.
                self._m.counter(
                    'skytpu_store_spills_total',
                    'Write-behind spills to the durable store by '
                    'outcome.',
                    labels=('result',)).inc(labels=('failed',))
                self._journal_raw(journal.EventKind.STORE_SPILL,
                                  {'outcome': 'failed',
                                   'tokens': len(tokens),
                                   'store': self.store_url})
        if (not self._spill_pending or not self.store_url
                or self._store_backoff_until > time.perf_counter()):
            return
        tokens = self._spill_pending.pop(0)
        raw = self._export_prefix_now(tokens, 0)
        if raw is None:
            # Evicted between publish and spill: nothing to persist.
            return
        fut = self._spill_executor().submit(
            self._store_spill_fn, self.store_url, tokens, raw,
            self._store_spill_budget)
        self._spill_inflight = (fut, tokens)

    def _export_prefix_now(self, tokens: Sequence[int],
                           from_tokens: int = 0) -> Optional[dict]:
        """LOOP-THREAD ONLY: radix-match ``tokens`` and read the
        matched pool blocks past ``from_tokens`` off the device. Under
        a TP mesh the gather assembles the full logical block from
        every shard (owner-side gather), so the payload is always the
        unsharded ``[L, n, block_k, ...]`` view. Returns None when
        nothing past ``from_tokens`` is cached."""
        if not self.paged:
            return None
        bk = self._block_k
        tokens = [int(t) for t in tokens]
        blocks, path = self._radix.match(tokens)
        try:
            matched = len(blocks) * bk
            start = from_tokens // bk
            if matched <= from_tokens or start >= len(blocks):
                return None
            send = blocks[start:]
            # Bucketed gather (padding rows read the scratch block and
            # are sliced off host-side): one trace per power-of-two
            # block count, not one per export size.
            bucket = 1
            while bucket < len(send):
                bucket *= 2
            self._note_compile('prefix_export', blocks=bucket)
            idx_np = np.full((bucket,), SCRATCH_BLOCK, np.int32)
            idx_np[:len(send)] = send
            idx = jnp.asarray(idx_np)
            # The match's increfs pin these blocks for the read; the
            # gather output is a fresh buffer, safe to ship after the
            # refs drop.
            arrays = {
                name: np.asarray(
                    jax.device_get(arr[:, idx]))[:, :len(send)]
                for name, arr in self._cache.items()}
            return {
                'matched_tokens': matched,
                'from_tokens': start * bk,
                'block_k': bk,
                'kv_cache_dtype': self.dcfg.kv_cache_dtype,
                'arrays': arrays,
            }
        finally:
            if blocks:
                self._allocator.decref(blocks)
            self._radix.release(path)

    def export_prefix_blocks(self, tokens: Sequence[int],
                             from_tokens: int = 0,
                             timeout: float = 2.0) -> Optional[dict]:
        """Cross-thread prefix export (the model server's
        ``/prefix_blocks`` handler): enqueue a job the engine loop
        services at its next tick and wait bounded. None on timeout or
        no match — the peer degrades to plain prefill either way."""
        job = {'tokens': list(tokens), 'from': int(from_tokens),
               'event': threading.Event(), 'result': None,
               # Past this the waiter is gone: the loop must not burn
               # a radix match + device gather on an unread reply.
               'deadline': time.monotonic() + timeout}
        with self._export_lock:
            self._export_jobs.append(job)
        if job['event'].wait(timeout):
            return job['result']
        return None

    def _service_prefix_exports(self) -> None:
        """Drain queued export/inject jobs (loop thread, top of every
        step): peers' ``/prefix_blocks`` exports and pushed
        ``/handoff_blocks`` injections both queue here — radix/pool
        mutation is loop-confined either way."""
        with self._export_lock:
            if not self._export_jobs:
                return
            jobs = self._export_jobs
            self._export_jobs = []
        for job in jobs:
            if job['deadline'] < time.monotonic():
                # Waiter already timed out: skip the (match + gather)
                # work — nobody reads the result.
                job['event'].set()
                continue
            try:
                if job.get('kind') == 'inject':
                    job['result'] = self._inject_handoff_now(
                        job['tokens'], job['payload'])
                else:
                    job['result'] = self._export_prefix_now(
                        job['tokens'], job['from'])
            except Exception as e:  # pylint: disable=broad-except
                # Export/inject is best-effort for the PEER; this
                # engine's loop must not crash over a read that raced
                # an evict (the pushing side degrades on a None).
                self._journal_raw(journal.EventKind.ENGINE_PREFIX_FETCH,
                                  {'outcome': 'export_error',
                                   'error': f'{type(e).__name__}: {e}'})
                job['result'] = None
            job['event'].set()

    # ------------------------------------------------------------- step

    def step(self) -> int:
        """Admit, then run one chunk of fused decode steps across all
        slots. Returns the number of slots that were active (0 = idle:
        nothing queued, nothing decoding)."""
        # Chaos points (default off: two dict lookups): an injected
        # raise exercises the run_forever supervisor; slow_step widens
        # decode windows for drain/stall tests.
        chaos.maybe_raise('engine_step_raise')
        chaos.maybe_slow_step()
        # Peer /prefix_blocks exports queue cross-thread and are
        # serviced here (radix/pool are loop-confined) — before
        # admission so a just-published prefix is immediately
        # exportable.
        self._service_prefix_exports()
        # Write-behind spill to the durable store rides the same slot:
        # harvest the previous POST's outcome, launch at most one new
        # export per step.
        self._service_store_spills()
        self._admit()
        active = self.active_slots()
        if active == 0:
            return 0
        t0 = time.perf_counter()
        # One bounded prefill chunk BEFORE the decode dispatch: the
        # fairness contract of chunked admission. When NO lane is
        # decoding there is nothing to stall — keep draining chunks
        # until a prefill completes (a lane becomes ready) instead of
        # burning one idle step per chunk.
        pf_tokens = self._advance_prefill()
        decode_lanes = int(np.count_nonzero(~self._done))
        while (decode_lanes == 0 and
               any(st is not None for st in self._prefill_state)):
            pf_tokens += self._advance_prefill()
            decode_lanes = int(np.count_nonzero(~self._done))
        emitted_before = self._decode_emitted
        n = 0
        if decode_lanes:
            # Token latency is observed over the decode dispatch ONLY —
            # the chunked-prefill share of the step is admission work,
            # attributed separately (profiler ring + stall payloads),
            # and must not read as a per-token regression.
            t_dec = time.perf_counter()
            if self.dcfg.spec_k:
                n = 1
                self._spec_round()
                # One spec round replaces a VARIABLE number of per-lane
                # decode steps: normalize per-token latency by the mean
                # tokens delivered per live lane, not the single
                # dispatch — else enabling speculation would read as a
                # per-token regression exactly when it is winning.
                per_token_div = max(
                    (self._decode_emitted - emitted_before)
                    / decode_lanes, 1.0)
            else:
                n = self._decode_round()
                per_token_div = float(n)
            self._decode_steps += n
            self._m.counter('skytpu_engine_steps_total',
                            'Batched decode steps executed.').inc(n)
            self._m.histogram(
                'skytpu_engine_token_seconds',
                'Per-token decode step latency.',
                buckets=runtime_metrics.TOKEN_LATENCY_BUCKETS
            ).observe((time.perf_counter() - t_dec) / per_token_div)
        dt = time.perf_counter() - t0
        stall = self.profiler.record(
            dt, chunk=n, active=active,
            delivered=self._decode_emitted - emitted_before,
            queue_depth=self._publish_queue_depth(),
            blocks_used=self._allocator.used() if self.paged else 0,
            blocks_total=(self.num_blocks - 1) if self.paged else 0,
            prefill_tokens=pf_tokens)
        if stall is not None:
            self._journal_raw(journal.EventKind.ENGINE_STALL, stall)
        # Refill freed lanes NOW so the next chunk runs full. The
        # journal write rides a background thread (wait=False): a
        # stalled journal disk must never block the step loop.
        self._admit()
        self.flush_journal(wait=False)
        return active

    def _tables_dev(self) -> jax.Array:
        """The cached device copy of the block tables, uploaded lazily
        after admission/eviction invalidates it. Under a TP mesh the
        tables are device_put REPLICATED — every shard indirects
        through the same int32 table (allocation/COW/prefix-match stay
        host-global; only the KV-head axis shards)."""
        if self._block_table_dev is None:
            if self.mesh is not None:
                self._block_table_dev = jax.device_put(
                    self._block_table_np,
                    jax.sharding.NamedSharding(
                        self.mesh, jax.sharding.PartitionSpec()))
            else:
                self._block_table_dev = jnp.asarray(self._block_table_np)
        return self._block_table_dev

    def _decode_round(self) -> int:
        """The non-speculative decode dispatch: ``step_chunk`` fused
        single-token steps over every slot, then host delivery. Returns
        the number of decode steps executed."""
        n = self.step_chunk
        if self.dcfg.temperature > 0.0:
            self._rng, sub = jax.random.split(self._rng)
            keys = jax.random.split(sub, n)
        else:
            keys = self._zero_keys
        self._note_compile('decode_steps', n_steps=n, paged=self.paged)
        if self.paged:
            toks, token, pos, done, remaining, self._cache = \
                _engine_paged_steps_impl(
                    self.params, jnp.asarray(self._token),
                    jnp.asarray(self._pos), jnp.asarray(self._done),
                    jnp.asarray(self._remaining), keys,
                    self._tables_dev(), self._cache,
                    cfg=self.cfg, dcfg=self.dcfg, n_steps=n,
                    mesh=self.mesh)
        else:
            toks, token, pos, done, remaining, self._cache = \
                _engine_steps_impl(
                    self.params, jnp.asarray(self._token),
                    jnp.asarray(self._pos), jnp.asarray(self._done),
                    jnp.asarray(self._remaining), keys,
                    self._cache, cfg=self.cfg, dcfg=self.dcfg,
                    n_steps=n)
        # One fused host fetch (the sync point); np.array copies because
        # the transferred buffers are read-only and the slot mirrors are
        # mutated by eviction/refill.
        toks_np, token, pos, done, remaining = jax.device_get(
            (toks, token, pos, done, remaining))
        self._token = np.array(token)
        self._pos = np.array(pos)
        self._done = np.array(done)
        self._remaining = np.array(remaining)
        self._deliver_chunk(toks_np)
        return n

    def _spec_round(self) -> None:
        """One speculative draft + batched-verify round across all
        decoding lanes, with host-side accept/rollback.

        Acceptance is the standard chain rule: verify token ``i`` is
        the full model's argmax GIVEN the drafted context up to ``i``,
        which matches the true greedy context exactly while every
        earlier draft was accepted — so committing ``a_0..a_n`` (the
        accepted run plus the one correction/bonus token) emits
        precisely the tokens the non-speculative path would have, one
        dispatch instead of up to ``spec_k+1``. Rollback is positional:
        ``pos`` advances by the delivered count only; the rejected
        tail's cache entries sit in lane-private blocks past ``pos``,
        are never attended, and are overwritten when a real token
        reaches that position."""
        tables = self._tables_dev()
        k = self.dcfg.spec_k
        # Bound the drafter's per-round history gather to the LIVE
        # block count: the drafter only ever attends positions < pos,
        # so gathering the full table width materializes dead pool
        # blocks for nothing (the PR 11 follow-up noted at
        # decode._gather_layer_kv). Power-of-two bucketing keeps the
        # dispatch-shape count logarithmic; masked entries are exact
        # zeros in the drafter softmax, so narrowing is
        # numerics-invisible. Verify keeps the full tables — its
        # writes land at pos..pos+spec_k, past the live prefix.
        live = ~self._done
        max_pos = int(self._pos[live].max()) if live.any() else 1
        npb = max(1, -(-max_pos // self._block_k))
        nb_bucket = 1
        while nb_bucket < npb:
            nb_bucket *= 2
        nb_bucket = min(nb_bucket, self._max_blocks)
        self._note_compile('spec_step', spec_k=k,
                           drafter_layers=self.dcfg.spec_drafter_layers,
                           draft_blocks=nb_bucket)
        drafts, vtok, self._cache = _engine_spec_step_impl(
            self.params, jnp.asarray(self._token),
            jnp.asarray(self._pos), tables[:, :nb_bucket], tables,
            self._cache, cfg=self.cfg, dcfg=self.dcfg, mesh=self.mesh)
        drafts, vtok = jax.device_get((drafts, vtok))
        emitted_total = 0
        round_drafted = 0
        round_accepted = 0
        for slot, req in enumerate(self._slots):
            if req is None or self._done[slot]:
                continue
            n_acc = 0
            while (n_acc < k and
                   int(drafts[slot, n_acc]) == int(vtok[slot, n_acc])):
                n_acc += 1
            round_drafted += k
            round_accepted += n_acc
            delivered, last_tok, evicted = self._deliver_run(
                slot, req, vtok[slot, :n_acc + 1])
            emitted_total += delivered
            if not evicted:
                self._token[slot] = last_tok
                self._pos[slot] += delivered
                self._remaining[slot] -= delivered
        self._spec_drafted += round_drafted
        self._spec_accepted += round_accepted
        self._decode_emitted += emitted_total
        self._m.counter('skytpu_engine_tokens_total',
                        'Tokens generated by the engine.').inc(
                            emitted_total)
        self._m.counter(
            'skytpu_engine_spec_drafted_total',
            'Tokens proposed by the speculative drafter.').inc(
                round_drafted)
        self._m.counter(
            'skytpu_engine_spec_accepted_total',
            'Drafted tokens accepted by the batched verify.').inc(
                round_accepted)
        self._m.gauge(
            'skytpu_engine_spec_accept_ratio',
            'Cumulative accepted/drafted ratio of the speculative '
            'path.').set(self.spec_accept_ratio())

    def _deliver_run(self, slot: int, req: Request,
                     tokens) -> Tuple[int, int, bool]:
        """Deliver a run of tokens to ONE lane with budget/EOS clipping
        — the single copy of the engine's finish semantics, shared by
        the plain chunked delivery and the speculative accept path (so
        a change to stop conditions cannot silently fork the two).
        Evicts on a terminal condition; returns (delivered tokens,
        last delivered token, evicted?)."""
        eos = self.dcfg.eos_id
        budget = req.max_new_tokens - len(req.tokens)
        reason = None
        delivered = 0
        last_tok = 0
        for t in tokens:
            t = int(t)
            budget -= 1
            delivered += 1
            last_tok = t
            hit_eos = eos is not None and t == eos
            if hit_eos:
                reason = 'eos'
            elif budget <= 0:
                reason = 'length'
            if reason is not None:
                # Publish the reason BEFORE the terminal token: the
                # HTTP thread wakes on done=True and reads
                # finish_reason immediately, while _finish() only runs
                # after _evict's allocator/radix/journal bookkeeping —
                # readers raced that gap and observed ''.
                req.finish_reason = reason
            req._deliver(t, done=reason is not None)  # pylint: disable=protected-access
            if reason is not None:
                break
        if reason is not None:
            self._evict(slot, reason)
        return delivered, last_tok, reason is not None

    def _deliver_chunk(self, toks_np: np.ndarray) -> None:
        emitted = 0
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            if self._prefill_state[slot] is not None:
                # Mid-chunked-prefill: the lane is slotted but not yet
                # decoding — the dispatch's frozen-lane outputs for it
                # are scratch noise, not tokens.
                continue
            delivered, _, _ = self._deliver_run(slot, req,
                                                toks_np[:, slot])
            emitted += delivered
        self._decode_emitted += emitted
        self._m.counter('skytpu_engine_tokens_total',
                        'Tokens generated by the engine.').inc(emitted)

    def _evict(self, slot: int, reason: str) -> None:
        req = self._slots[slot]
        assert req is not None
        self._slots[slot] = None
        self._done[slot] = True
        self._remaining[slot] = 0
        if self.paged:
            # Drop the request's block refs (prefix-cache-held blocks
            # survive; decode-only blocks free) and repoint the table
            # row at scratch so the frozen lane's writes can never land
            # in a block reallocated to someone else.
            self._allocator.decref(self._slot_refs[slot])
            self._radix.release(self._slot_nodes[slot])
            self._slot_refs[slot] = []
            self._slot_nodes[slot] = []
            self._prefill_state[slot] = None
            self._block_table_np[slot, :] = SCRATCH_BLOCK
            self._block_table_dev = None
            self._publish_block_gauges()
        self._evicted += 1
        self._m.counter('skytpu_engine_evicted_total',
                        'Requests evicted from a slot (finished).').inc()
        self._journal(journal.EventKind.ENGINE_EVICT, req, slot,
                      reason=reason, generated=len(req.tokens))
        req._finish(reason)  # pylint: disable=protected-access
        slow = self.telemetry.on_finish(req, reason)
        if slow is not None:
            self._journal(journal.EventKind.ENGINE_SLOW_REQUEST, req,
                          slot, **slow)
        self._publish_slot_gauges()

    # ------------------------------------------------------------- loop

    def run_forever(self, stop_event: threading.Event) -> None:
        """Supervised engine loop: step while there is work, sleep
        briefly when idle. Run on a dedicated thread; ``stop_event``
        ends it.

        A ``step()`` exception no longer kills the thread silently (one
        bad request or a flaky device call used to wedge the whole
        replica behind a live HTTP server): the supervisor journals an
        ``engine.crash`` with the traceback, fails every in-flight
        request fast (clients get an error via ``on_finish``, not a
        request timeout), rebuilds the device state, and restarts —
        bounded by ``SKYTPU_ENGINE_MAX_RESTARTS`` within a rolling
        ``SKYTPU_ENGINE_RESTART_WINDOW_SECONDS`` window, after which the
        engine is permanently ``failed`` and the loop exits (the model
        server's /healthz 503s for good and the replica manager's probe
        machinery replaces the replica). Queued (not-yet-admitted)
        requests survive a restart and re-prefill."""
        try:
            idle = float(os.environ.get(IDLE_SLEEP_ENV, '0.02'))
        except ValueError:
            idle = 0.02
        while not stop_event.is_set():
            # Liveness beat every iteration (idle included): the model
            # server's /healthz staleness reads this, and an idle-but-
            # alive engine must not decay into a 503.
            self.profiler.beat()
            try:
                active = self.step()
            except Exception as exc:  # pylint: disable=broad-except
                if not self._recover_from_crash(exc):
                    return  # restart budget exhausted: permanent fail
                continue
            if active == 0:
                # one-token admissions while idle (non-blocking: the
                # idle loop must keep beating through a journal stall)
                self.flush_journal(wait=False)
                time.sleep(idle)

    # ------------------------------------------------------- supervision

    def restart_count(self) -> int:
        return self._restarts

    def _recover_from_crash(self, exc: BaseException) -> bool:
        """One supervisor round: journal the crash (with traceback),
        fail in-flight requests fast, then either rebuild + restart
        (returns True) or — budget exhausted — fail the queued requests
        too and flip the engine permanently ``failed`` (returns
        False)."""
        now = time.time()
        window = common_utils.env_float(RESTART_WINDOW_ENV,
                                        DEFAULT_RESTART_WINDOW_SECONDS)
        budget = common_utils.env_int(MAX_RESTARTS_ENV,
                                      DEFAULT_MAX_RESTARTS)
        self._crash_times = [t for t in self._crash_times
                             if now - t <= window]
        self._crash_times.append(now)
        permanent = len(self._crash_times) > budget
        self._journal_raw(journal.EventKind.ENGINE_CRASH, {
            'error': str(exc) or type(exc).__name__,
            'traceback': traceback.format_exc(),
            'in_flight': self.active_slots(),
            'queued': self.queue_depth(),
            'crashes_in_window': len(self._crash_times),
            'max_restarts': budget,
            'permanent': permanent,
        })
        exc_text = str(exc) or type(exc).__name__
        self._fail_in_flight(f'error: engine crashed: {exc_text}')
        if permanent:
            self.failed = True
            self.fail_reason = (
                f'{len(self._crash_times)} crashes within {window:.0f}s '
                f'(budget {budget}); last: {exc_text}')
            self._fail_queued()
            self.flush_journal()
            return False
        # Fresh cache/pool, radix cache dropped, slot mirrors cleared;
        # the tenant queues are untouched — their requests re-prefill.
        self._init_runtime_state()
        self._restarts += 1
        self._m.counter(
            'skytpu_engine_restarts_total',
            'Engine supervisor restarts after a step() crash.').inc()
        self._journal_raw(journal.EventKind.ENGINE_RESTART, {
            'restarts': self._restarts,
            'queued': self.queue_depth(),
        })
        self.flush_journal()
        self._publish_slot_gauges()
        if self.paged:
            self._publish_block_gauges()
        return True

    def _fail_in_flight(self, reason: str) -> None:
        """Finish every slotted request with an error — clients learn
        NOW (500 via on_finish), not at the request timeout. Does NOT
        touch the allocator/radix (the crash may have left them
        inconsistent mid-admission); the caller rebuilds all device
        state afterwards."""
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            self._slots[slot] = None
            self._prefill_state[slot] = None
            self._evicted += 1
            self._m.counter(
                'skytpu_engine_evicted_total',
                'Requests evicted from a slot (finished).').inc()
            self._journal(journal.EventKind.ENGINE_EVICT, req, slot,
                          reason=reason, generated=len(req.tokens))
            req._finish(reason)  # pylint: disable=protected-access
            slow = self.telemetry.on_finish(req, reason)
            if slow is not None:
                self._journal(journal.EventKind.ENGINE_SLOW_REQUEST,
                              req, slot, **slow)
        self._publish_slot_gauges()

    def _fail_queued(self) -> None:
        """Permanent-failure path only: nothing will ever serve the
        queue again, so fail every queued request (server-side error →
        clients get 500) instead of letting them ride out the request
        timeout."""
        while True:
            req = self._pop_next()
            if req is None:
                break
            self._fail_request(req, 'engine failed permanently')
        self._publish_queue_depth()

    # ------------------------------------------------------------ stats

    def mean_occupancy(self) -> float:
        """Delivered decode tokens / executed lane-steps: the measured
        fraction of batch lanes doing useful work."""
        lane_steps = self._decode_steps * self.num_slots
        return self._decode_emitted / lane_steps if lane_steps else 0.0

    def spec_accept_ratio(self) -> float:
        """Cumulative accepted/drafted ratio of the speculative path
        (0.0 while nothing was drafted)."""
        if not self._spec_drafted:
            return 0.0
        return self._spec_accepted / self._spec_drafted

    def spec_stats(self) -> dict:
        """The ``/slo`` ``spec`` block: speculative-decoding and
        chunked-prefill counters for one engine."""
        return {
            'enabled': self.dcfg.spec_k > 0,
            'spec_k': self.dcfg.spec_k,
            'drafter_layers': self.dcfg.spec_drafter_layers,
            'drafted_total': self._spec_drafted,
            'accepted_total': self._spec_accepted,
            'accept_ratio': round(self.spec_accept_ratio(), 4),
            'prefill_chunk': self.prefill_chunk,
            'prefill_chunks_total': self._prefill_chunks,
            'chunked_admissions': self._chunked_admissions,
        }

    def prefix_hit_ratio(self) -> float:
        """Fraction of admitted prompt tokens served from the prefix
        cache (prefill skipped) — paged mode only."""
        if not self.paged or not self._prompt_tokens_total:
            return 0.0
        return self._prompt_tokens_saved / self._prompt_tokens_total

    def cache_stats(self) -> dict:
        """The ``/slo`` ``cache`` block: prefix-cache locality and
        pressure counters for one engine — what the LB's FleetSlo
        aggregates into the fleet hit ratio. Snapshot reads of
        loop-owned ints (stale-by-one-tick at worst, never torn)."""
        return {
            'paged': self.paged,
            'prefix_hit_ratio': round(self.prefix_hit_ratio(), 4),
            'prefill_tokens_saved': self._prompt_tokens_saved,
            'prompt_tokens_total': self._prompt_tokens_total,
            'prefix_cache_blocks': (self._radix.held_blocks()  # lint: disable=lock-discipline
                                    if self.paged else 0),
            'radix_nodes': (self._radix.node_count()  # lint: disable=lock-discipline
                            if self.paged else 0),
            'prefix_evictions': self._prefix_evictions,
            'prefix_fetch_hits': self._prefix_fetch_hits,
            'prefix_fetch_misses': self._prefix_fetch_misses,
            'prefix_fetch_tokens': self._prefix_fetch_tokens,
            'prefix_peers': len(self.prefix_peers),
            'store_configured': bool(self.store_url),
            'store_in_backoff': (self.store_in_backoff()
                                 if self.store_url else False),
            'store_fetch_hits': self._store_fetch_hits,
            'store_fetch_misses': self._store_fetch_misses,
            'store_fetch_tokens': self._store_fetch_tokens,
            'store_spills': self._store_spills,
            'store_spill_tokens': self._store_spill_tokens,
            'store_spill_failures': self._store_spill_failures,
            'store_spill_drops': self._store_spill_drops,
        }

    def stats(self) -> dict:
        self.flush_journal()
        out = {
            'num_slots': self.num_slots,
            'active_slots': self.active_slots(),
            'queue_depth': self.queue_depth(),
            'admitted': self._admitted,
            'evicted': self._evicted,
            'decode_steps': self._decode_steps,
            'decode_tokens': self._decode_emitted,
            'mean_occupancy': round(self.mean_occupancy(), 4),
            'stalls': self.profiler.stall_count(),
            'restarts': self._restarts,
            'failed': self.failed,
            'step_chunk': self.step_chunk,
            'kv_cache_dtype': self.dcfg.kv_cache_dtype,
            'max_len': self.dcfg.max_len,
            'paged': self.paged,
            'tp': self.tp,
        }
        if self.paged:
            out.update({
                'block_k': self._block_k,
                'blocks_total': self.num_blocks - 1,
                # Snapshot reads of loop-owned counters: worst case one
                # stale integer in a /slo body, never a torn structure.
                'blocks_used': self._allocator.used(),  # lint: disable=lock-discipline
                'prefix_cache_blocks': self._radix.held_blocks(),  # lint: disable=lock-discipline
                'prefix_hit_ratio': round(self.prefix_hit_ratio(), 4),
                'prefill_tokens_saved': self._prompt_tokens_saved,
                'prefill_chunk': self.prefill_chunk,
                'prefill_chunks': self._prefill_chunks,
                'chunked_admissions': self._chunked_admissions,
                'prefix_evictions': self._prefix_evictions,
                'prefix_fetch_hits': self._prefix_fetch_hits,
                'prefix_fetch_misses': self._prefix_fetch_misses,
                'handoffs_completed': self._handoffs_completed,
                'handoffs_degraded': self._handoffs_degraded,
                'handoff_injections': self._handoff_injections,
                'store_configured': bool(self.store_url),
                'store_fetch_hits': self._store_fetch_hits,
                'store_spills': self._store_spills,
            })
        if self.dcfg.spec_k:
            out.update({
                'spec_k': self.dcfg.spec_k,
                'spec_drafted': self._spec_drafted,
                'spec_accepted': self._spec_accepted,
                'spec_accept_ratio': round(self.spec_accept_ratio(), 4),
            })
        return out

    # ---------------------------------------------------------- plumbing

    def _publish_slot_gauges(self) -> None:
        self._m.gauge('skytpu_engine_active_slots',
                      'Slots currently decoding.').set(self.active_slots())
        self._m.gauge(
            'skytpu_engine_slot_occupancy',
            'Measured decode-lane occupancy (delivered tokens / '
            'lane-steps).').set(self.mean_occupancy())

    def _publish_block_gauges(self) -> None:
        self._m.gauge('skytpu_engine_blocks_total',
                      'Usable KV pool blocks (scratch excluded).').set(
                          self.num_blocks - 1)
        self._m.gauge('skytpu_engine_blocks_used',
                      'KV pool blocks currently referenced (slots or '
                      'prefix cache).').set(self._allocator.used())
        self._m.gauge(
            'skytpu_engine_prefix_hit_ratio',
            'Cumulative fraction of prompt tokens served from the '
            'prefix cache.').set(self.prefix_hit_ratio())
        # Cache-pressure context for the locality numbers: how big the
        # radix tree actually is, in edges and in held blocks.
        self._m.gauge(
            'skytpu_engine_radix_nodes',
            'Edges in the radix prefix tree.').set(
                self._radix.node_count())
        self._m.gauge(
            'skytpu_engine_prefix_cache_blocks',
            'KV pool blocks held by the radix prefix cache.').set(
                self._radix.held_blocks())

    def _note_compile(self, kind: str, **shape) -> None:
        """Journal ``engine.compile`` ONCE per distinct jitted dispatch
        shape, just before the dispatch that would trace it — recompile
        churn from new (bucket, chunk, spec_k) shapes shows up in
        ``skytpu events`` instead of silently eating p99. The dedupe
        set survives supervisor restarts (the jit cache is
        process-global: a rebuilt engine does not retrace old
        shapes)."""
        key = (kind, tuple(sorted(shape.items())))
        if key in self._traced_shapes:
            return
        self._traced_shapes.add(key)
        self._m.counter(
            'skytpu_engine_compiles_total',
            'Distinct engine dispatch shapes traced (journaled as '
            'engine.compile).').inc()
        self._journal_raw(journal.EventKind.ENGINE_COMPILE,
                          {'compile_kind': kind, **shape})

    def _journal(self, kind, request: Request, slot: int,
                 **payload) -> None:
        self._journal_raw(kind,
                          {'request': request.id, 'slot': slot, **payload},
                          trace_id=request.trace_id,
                          span_id=getattr(request, 'span_id', None))

    def _journal_raw(self, kind, payload: dict,
                     trace_id: Optional[str] = None,
                     span_id: Optional[str] = None,
                     parent_span_id: Optional[str] = None,
                     entity: Optional[str] = None) -> None:
        """Buffer one event; a per-request ``trace_id`` overrides the
        ambient trace for that row (the X-Request-Id join), and
        ``span_id``/``parent_span_id`` nest it under the HTTP span that
        carried the request (``span_id`` requires ``trace_id``)."""
        if trace_id is not None and span_id is not None:
            override = (trace_id, span_id, parent_span_id)
        else:
            override = trace_id
        self._jbuf.append(kind, entity or f'engine:{self.name}', payload,
                          override)

    def journal_buffered(self, kind, payload: dict,
                         trace_id: Optional[str] = None,
                         span_id: Optional[str] = None,
                         parent_span_id: Optional[str] = None,
                         entity: Optional[str] = None) -> None:
        """Public form of the batched journal buffer for co-located
        callers on the request hot path (the model server's per-request
        span rows): rows ride the engine tick's single transaction
        instead of paying a per-event sqlite commit."""
        self._journal_raw(kind, payload, trace_id=trace_id,
                          span_id=span_id,
                          parent_span_id=parent_span_id, entity=entity)

    def flush_journal(self, wait: bool = True) -> None:
        """Write buffered admit/evict events in one transaction.
        ``step()`` calls it per tick with ``wait=False`` (the write
        rides a short-lived background thread — a wedged journal disk
        never blocks the decode loop); direct ``insert()`` drivers
        (tests) call it, or ``stats()``, with the default synchronous
        form to see their rows."""
        self._jbuf.flush(wait=wait)

    def journal_stats(self) -> dict:
        """Journal-plane self-observability (buffered/dropped/flush
        p95) — surfaced in the sched-bench detail block and /slo."""
        return self._jbuf.stats()
