"""Continuous-batching decode engine: slot-scheduled serving on one cache.

``decode.generate`` is static-batch, run-to-completion: a batch is
admitted together, the scan runs every ``max_new_tokens`` step even after
all rows hit EOS, and a new request waits behind the longest sequence in
flight. On accelerators that is the dominant serving throughput loss —
the chip's batch lanes sit idle exactly when traffic is mixed-length,
which is always (the TPU concurrency-utilization problem of PAPERS.md's
"Exploring the limits of Concurrency in ML Training on Google TPUs",
applied to inference).

:class:`DecodeEngine` replaces run-to-completion with slot scheduling
(the vLLM/JetStream continuous-batching model, on the in-tree
flash-decode path):

* **One persistent KV cache** of ``num_slots`` lanes
  (``[L, num_slots, max_len, Hkv, hd]``, bf16 or int8+scales), donated
  through every jitted call so prefill scatters and per-step updates
  mutate the same HBM buffers for the life of the process.
* **insert()** prefills a single request ([1, S_bucket] — prompt lengths
  round up to a small set of bucket shapes so compiles stay bounded) and
  scatters its K/V prefix into a free lane via
  ``decode.prefill_into_slot``; the first token samples from the prefill
  logits, so TTFT does not wait for a decode step.
* **step()** runs ``step_chunk`` batched ``decode_step`` s across ALL
  slots with per-slot positions; per-slot EOS/budget masks freeze
  finished lanes (their emitted positions are forced to EOS exactly like
  ``generate``'s done mask, which is what makes greedy engine output
  token-identical to static ``generate``).
* Finished slots are **evicted and immediately refilled** from the
  admission queue, so a short request never waits for a long one and
  lane occupancy stays high. Occupancy is measured, not assumed:
  ``stats()['mean_occupancy']`` is delivered-tokens / lane-steps.

Host/device split: per-slot scheduling state (which request owns which
lane, budgets, done flags) lives in numpy mirrors; each ``step()`` makes
one jitted call of ``step_chunk`` fused decode steps and one host fetch.
``step_chunk`` amortizes dispatch overhead; 1 gives token-granular
streaming and exact occupancy accounting.

Telemetry: ``skytpu_engine_*`` metrics through the process registry
(queue depth, slot occupancy, admitted/evicted counters, TTFT and
per-token histograms) and ``engine.admit``/``engine.evict`` flight-
recorder events, so a serving replica's scheduling decisions are
reconstructable after the fact.
"""
import collections
import functools
import itertools
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import decode, llama
from skypilot_tpu.observability import journal
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import runtime_metrics

IDLE_SLEEP_ENV = 'SKYTPU_ENGINE_IDLE_SLEEP_SECONDS'


class Request:
    """One generation request tracked through the engine.

    ``on_token(token, done)`` (optional) fires from the engine loop
    thread per generated token — the model server bridges it onto its
    asyncio loop for SSE streaming. ``tokens`` accumulates the full
    generation; ``wait()`` blocks until eviction.
    """
    _ids = itertools.count()

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 on_token: Optional[Callable[[int, bool], None]] = None,
                 request_id: Optional[str] = None):
        if max_new_tokens < 1:
            raise ValueError(f'max_new_tokens must be >= 1, got '
                             f'{max_new_tokens}')
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError('empty prompt')
        self.max_new_tokens = int(max_new_tokens)
        self.on_token = on_token
        self.id = (request_id if request_id is not None
                   else f'r{next(self._ids)}')
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.enqueue_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    # ------------------------------------------------- engine-side hooks

    def _deliver(self, token: int, done: bool) -> None:
        self.tokens.append(token)
        if self.first_token_ts is None:
            self.first_token_ts = time.perf_counter()
        if self.on_token is not None:
            self.on_token(token, done)

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self.finish_ts = time.perf_counter()
        self._done.set()


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'dcfg', 'n_steps'),
                   donate_argnums=(6,))
def _engine_steps_impl(params, token, pos, done, remaining, keys, cache,
                       cfg: llama.LlamaConfig, dcfg: decode.DecodeConfig,
                       n_steps: int):
    """``n_steps`` fused decode steps over every slot.

    token/pos/remaining [num_slots] int32, done [num_slots] bool, keys
    [n_steps, 2] uint32 (sampling; unused for greedy), cache donated.
    Per-step semantics mirror ``decode._generate_impl.step`` exactly for
    live lanes (same sample → EOS-mask → done-fold order, so greedy
    output is token-identical); done lanes additionally FREEZE their
    position instead of advancing, bounding writes for lanes that idle
    across many chunks (emitted tokens are forced to EOS either way, so
    the freeze is unobservable in the output stream).

    Returns (tokens [n_steps, num_slots], token, pos, done, remaining,
    cache).
    """
    def step(carry, key):
        tok, p, dn, rem, cache_c = carry
        logits, cache_c = decode._decode_step(  # pylint: disable=protected-access
            params, tok, p, cfg, dcfg, cache_c)
        nxt = decode._sample(logits, key, dcfg.temperature)  # pylint: disable=protected-access
        if dcfg.eos_id is not None:
            nxt = jnp.where(dn, dcfg.eos_id, nxt)
            dn_new = dn | (nxt == dcfg.eos_id)
        else:
            nxt = jnp.where(dn, tok, nxt)
            dn_new = dn
        # One budget unit per live step; exhaustion folds into done.
        rem = rem - jnp.where(dn, 0, 1)
        dn_new = dn_new | (rem <= 0)
        p = jnp.where(dn, p, p + 1)
        return (nxt, p, dn_new, rem, cache_c), nxt

    (token, pos, done, remaining, cache), toks = jax.lax.scan(
        step, (token, pos, done, remaining, cache), keys)
    return toks, token, pos, done, remaining, cache


@functools.partial(jax.jit, static_argnames=('cfg',), donate_argnums=(4,))
def _prefill_greedy_impl(params, tokens, prompt_len, slot, cache,
                         cfg: llama.LlamaConfig):
    """Greedy insert fast path: prefill + first-token argmax in ONE
    dispatch. Sampling (temperature > 0) keeps the two-call path — it
    needs the raw logits on the engine side."""
    last, cache = decode._prefill_into_slot(  # pylint: disable=protected-access
        params, tokens, prompt_len, slot, cfg, cache)
    return jnp.argmax(last).astype(jnp.int32), cache


def _default_buckets(max_len: int) -> Tuple[int, ...]:
    """Prompt-length buckets: powers of two from 8 up to max_len (one
    prefill compile per bucket actually used)."""
    buckets = []
    b = 8
    while b < max_len:
        buckets.append(min(b, max_len))
        b *= 2
    if not buckets or buckets[-1] < max_len:
        buckets.append(max_len)
    return tuple(buckets)


class DecodeEngine:
    """Slot-based continuous-batching engine over ``models/decode``.

    Thread model: ``submit()`` is thread-safe (the server's request
    handlers call it); ``insert()``/``step()``/``run_forever()`` must run
    on ONE engine loop thread (they own the donated cache).
    """

    def __init__(self, params, cfg: llama.LlamaConfig,
                 dcfg: decode.DecodeConfig, num_slots: int,
                 step_chunk: int = 1,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 rng: Optional[jax.Array] = None,
                 name: str = 'engine'):
        if num_slots < 1:
            raise ValueError(f'num_slots must be >= 1, got {num_slots}')
        if step_chunk < 1:
            raise ValueError(f'step_chunk must be >= 1, got {step_chunk}')
        self.params = params
        self.cfg = cfg
        self.dcfg = dcfg
        self.num_slots = num_slots
        self.step_chunk = step_chunk
        self.name = name
        self._buckets = (tuple(sorted(int(b) for b in prefill_buckets))
                         if prefill_buckets
                         else _default_buckets(dcfg.max_len))
        assert self._buckets[-1] <= dcfg.max_len, self._buckets
        self._cache = decode.init_kv_cache(cfg, num_slots, dcfg.max_len,
                                           dcfg.kv_cache_dtype)
        # Host mirrors of per-slot device state.
        self._slots: List[Optional[Request]] = [None] * num_slots
        self._token = np.zeros((num_slots,), np.int32)
        self._pos = np.zeros((num_slots,), np.int32)
        self._done = np.ones((num_slots,), bool)
        self._remaining = np.zeros((num_slots,), np.int32)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        # Greedy decoding ignores sampling keys; reuse one zero buffer
        # instead of allocating [step_chunk, 2] on every tick.
        self._zero_keys = jnp.zeros((step_chunk, 2), jnp.uint32)
        # Admission queue: appended by any thread, drained by the loop.
        self._queue_lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        # Occupancy accounting: tokens delivered from decode steps vs
        # lane-steps executed (prefill-sampled first tokens excluded —
        # they cost a prefill, not a decode lane-step).
        self._decode_steps = 0
        self._decode_emitted = 0
        self._admitted = 0
        self._evicted = 0
        # Flight-recorder buffer: admit/evict events batch into ONE
        # sqlite transaction per tick (journal.event_batch) — a per-event
        # commit costs an fsync, which at token-loop rates would dominate
        # the decode step itself on slow filesystems. Locked: stats()
        # flushes from the HTTP thread while the loop appends.
        self._journal_lock = threading.Lock()
        self._journal_buf: List[tuple] = []
        self._m = metrics_lib
        self._m.gauge('skytpu_engine_num_slots',
                      'Configured KV-cache lanes.').set(num_slots)
        self._publish_slot_gauges()

    # ------------------------------------------------------------ intake

    def submit(self, request: Request) -> Request:
        """Enqueue a request for admission (thread-safe)."""
        request.enqueue_ts = time.perf_counter()
        with self._queue_lock:
            self._queue.append(request)
            depth = len(self._queue)
        self._m.gauge('skytpu_engine_queue_depth',
                      'Requests waiting for a free slot.').set(depth)
        return request

    def queue_depth(self) -> int:
        with self._queue_lock:
            return len(self._queue)

    def free_slots(self) -> int:
        return sum(1 for r in self._slots if r is None)

    def active_slots(self) -> int:
        return self.num_slots - self.free_slots()

    # --------------------------------------------------------- admission

    def insert(self, request: Request) -> int:
        """Prefill one request and scatter its K/V prefix into a free
        slot; the first token samples from the prefill logits. Returns
        the slot index. Raises RuntimeError when no slot is free (use
        ``submit`` + the engine loop for queued admission)."""
        slot = next((i for i, r in enumerate(self._slots) if r is None),
                    None)
        if slot is None:
            raise RuntimeError('no free slot')
        p = len(request.prompt)
        if p + request.max_new_tokens > self.dcfg.max_len:
            raise ValueError(
                f'prompt ({p}) + max_new_tokens '
                f'({request.max_new_tokens}) exceeds max_len '
                f'{self.dcfg.max_len}')
        bucket = next(b for b in self._buckets if b >= p)
        if request.enqueue_ts is None:
            request.enqueue_ts = time.perf_counter()
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = request.prompt
        if self.dcfg.temperature == 0.0:
            first_dev, self._cache = _prefill_greedy_impl(
                self.params, jnp.asarray(padded), jnp.int32(p),
                jnp.int32(slot), self._cache, cfg=self.cfg)
            first = int(first_dev)
        else:
            last, self._cache = decode.prefill_into_slot(
                self.params, jnp.asarray(padded), jnp.int32(p),
                jnp.int32(slot), self.cfg, self._cache)
            first = int(self._sample_first(last))
        self._m.histogram(
            'skytpu_engine_ttft_seconds',
            'Time from enqueue to first token (includes queueing).',
            buckets=runtime_metrics.TTFT_BUCKETS).observe(
                time.perf_counter() - request.enqueue_ts)
        self._admitted += 1
        self._m.counter('skytpu_engine_admitted_total',
                        'Requests admitted into a slot.').inc()
        self._m.counter('skytpu_engine_tokens_total',
                        'Tokens generated by the engine.').inc()
        self._journal(journal.EventKind.ENGINE_ADMIT, request, slot,
                      prompt_len=p, bucket=bucket,
                      max_new_tokens=request.max_new_tokens)
        hit_eos = (self.dcfg.eos_id is not None and
                   first == self.dcfg.eos_id)
        first_done = hit_eos or request.max_new_tokens == 1
        request._deliver(first, done=first_done)  # pylint: disable=protected-access
        self._slots[slot] = request
        if first_done:
            # One-token request (or immediate EOS): never occupies a
            # decode lane.
            self._evict(slot, 'eos' if hit_eos else 'length')
            return slot
        self._token[slot] = first
        self._pos[slot] = p
        self._done[slot] = False
        self._remaining[slot] = request.max_new_tokens - 1
        self._publish_slot_gauges()
        return slot

    def _sample_first(self, last_logits: jax.Array) -> int:
        self._rng, key = jax.random.split(self._rng)
        return int(decode._sample(last_logits[None], key,  # pylint: disable=protected-access
                                  self.dcfg.temperature)[0])

    def _admit(self) -> int:
        """Fill free slots from the queue; returns admissions made."""
        n = 0
        while True:
            if self.free_slots() == 0:
                break
            with self._queue_lock:
                if not self._queue:
                    break
                req = self._queue.popleft()
                depth = len(self._queue)
            self._m.gauge('skytpu_engine_queue_depth',
                          'Requests waiting for a free slot.').set(depth)
            try:
                self.insert(req)
                n += 1
            except ValueError as e:
                # Oversized request: fail it, keep serving the rest.
                req._finish(f'error: {e}')  # pylint: disable=protected-access
        return n

    # ------------------------------------------------------------- step

    def step(self) -> int:
        """Admit, then run one chunk of fused decode steps across all
        slots. Returns the number of slots that were active (0 = idle:
        nothing queued, nothing decoding)."""
        self._admit()
        active = self.active_slots()
        if active == 0:
            return 0
        n = self.step_chunk
        if self.dcfg.temperature > 0.0:
            self._rng, sub = jax.random.split(self._rng)
            keys = jax.random.split(sub, n)
        else:
            keys = self._zero_keys
        t0 = time.perf_counter()
        toks, token, pos, done, remaining, self._cache = \
            _engine_steps_impl(self.params, jnp.asarray(self._token),
                               jnp.asarray(self._pos),
                               jnp.asarray(self._done),
                               jnp.asarray(self._remaining), keys,
                               self._cache, cfg=self.cfg, dcfg=self.dcfg,
                               n_steps=n)
        # One fused host fetch (the sync point); np.array copies because
        # the transferred buffers are read-only and the slot mirrors are
        # mutated by eviction/refill.
        toks_np, token, pos, done, remaining = jax.device_get(
            (toks, token, pos, done, remaining))
        self._token = np.array(token)
        self._pos = np.array(pos)
        self._done = np.array(done)
        self._remaining = np.array(remaining)
        dt = time.perf_counter() - t0
        self._decode_steps += n
        self._m.counter('skytpu_engine_steps_total',
                        'Batched decode steps executed.').inc(n)
        self._m.histogram('skytpu_engine_token_seconds',
                          'Per-token decode step latency.',
                          buckets=runtime_metrics.TOKEN_LATENCY_BUCKETS
                          ).observe(dt / n)
        self._deliver_chunk(toks_np)
        # Refill freed lanes NOW so the next chunk runs full.
        self._admit()
        self.flush_journal()
        return active

    def _deliver_chunk(self, toks_np: np.ndarray) -> None:
        eos = self.dcfg.eos_id
        emitted = 0
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            budget = req.max_new_tokens - len(req.tokens)
            reason = None
            for j in range(toks_np.shape[0]):
                t = int(toks_np[j, slot])
                budget -= 1
                emitted += 1
                hit_eos = eos is not None and t == eos
                req._deliver(t, done=hit_eos or budget <= 0)  # pylint: disable=protected-access
                if hit_eos:
                    reason = 'eos'
                    break
                if budget <= 0:
                    reason = 'length'
                    break
            if reason is not None:
                self._evict(slot, reason)
        self._decode_emitted += emitted
        self._m.counter('skytpu_engine_tokens_total',
                        'Tokens generated by the engine.').inc(emitted)

    def _evict(self, slot: int, reason: str) -> None:
        req = self._slots[slot]
        assert req is not None
        self._slots[slot] = None
        self._done[slot] = True
        self._remaining[slot] = 0
        self._evicted += 1
        self._m.counter('skytpu_engine_evicted_total',
                        'Requests evicted from a slot (finished).').inc()
        self._journal(journal.EventKind.ENGINE_EVICT, req, slot,
                      reason=reason, generated=len(req.tokens))
        req._finish(reason)  # pylint: disable=protected-access
        self._publish_slot_gauges()

    # ------------------------------------------------------------- loop

    def run_forever(self, stop_event: threading.Event) -> None:
        """Engine loop: step while there is work, sleep briefly when
        idle. Run on a dedicated thread; ``stop_event`` ends it."""
        try:
            idle = float(os.environ.get(IDLE_SLEEP_ENV, '0.02'))
        except ValueError:
            idle = 0.02
        while not stop_event.is_set():
            if self.step() == 0:
                self.flush_journal()  # one-token admissions while idle
                time.sleep(idle)

    # ------------------------------------------------------------ stats

    def mean_occupancy(self) -> float:
        """Delivered decode tokens / executed lane-steps: the measured
        fraction of batch lanes doing useful work."""
        lane_steps = self._decode_steps * self.num_slots
        return self._decode_emitted / lane_steps if lane_steps else 0.0

    def stats(self) -> dict:
        self.flush_journal()
        return {
            'num_slots': self.num_slots,
            'active_slots': self.active_slots(),
            'queue_depth': self.queue_depth(),
            'admitted': self._admitted,
            'evicted': self._evicted,
            'decode_steps': self._decode_steps,
            'decode_tokens': self._decode_emitted,
            'mean_occupancy': round(self.mean_occupancy(), 4),
            'step_chunk': self.step_chunk,
            'kv_cache_dtype': self.dcfg.kv_cache_dtype,
            'max_len': self.dcfg.max_len,
        }

    # ---------------------------------------------------------- plumbing

    def _publish_slot_gauges(self) -> None:
        self._m.gauge('skytpu_engine_active_slots',
                      'Slots currently decoding.').set(self.active_slots())
        self._m.gauge(
            'skytpu_engine_slot_occupancy',
            'Measured decode-lane occupancy (delivered tokens / '
            'lane-steps).').set(self.mean_occupancy())

    def _journal(self, kind, request: Request, slot: int,
                 **payload) -> None:
        with self._journal_lock:
            self._journal_buf.append(
                (kind, f'engine:{self.name}',
                 {'request': request.id, 'slot': slot, **payload},
                 time.time()))

    def flush_journal(self) -> None:
        """Write buffered admit/evict events in one transaction. Called
        per tick by ``step()``; direct ``insert()`` drivers (tests) call
        it, or ``stats()``, to see their rows."""
        with self._journal_lock:
            buf, self._journal_buf = self._journal_buf, []
        if buf:
            journal.event_batch(buf)
