"""ResNet in pure functional JAX — the torch-DDP benchmark rewrite.

TPU-native counterpart of the reference's
``examples/torch_ddp_benchmark/torch_ddp_benchmark.yaml`` (501 ex/s on one
A100, 465 ex/s/GPU on 8, p50 — the BASELINE.md ResNet rows): instead of
torch DDP process groups, ONE jitted train step with the batch sharded
over a ``data``-axis mesh; XLA inserts the gradient all-reduce over ICI.

Keeps the MXU busy the TPU way: NHWC layout, bf16 convs with fp32
accumulation, BN folded to per-channel scale/shift (training-mode batch
stats computed in fp32), ``lax.conv_general_dilated`` everywhere.
"""
import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    # Stage widths and block counts; resnet50-style bottlenecks when
    # bottleneck=True, basic blocks (resnet18/34) otherwise.
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)
    width: int = 64
    bottleneck: bool = False
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @property
    def stage_widths(self) -> List[int]:
        return [self.width * (2**i) for i in range(len(self.stage_sizes))]


CONFIGS: Dict[str, ResNetConfig] = {
    'resnet18': ResNetConfig(),
    'resnet50': ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=True),
    'resnet101': ResNetConfig(stage_sizes=(3, 4, 23, 3), bottleneck=True),
    'debug': ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=10),
}


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout),
                             dtype) * (2.0 / fan_in)**0.5


def _conv(x, w, stride=1):
    # bf16 in/out; the TPU convolution accumulates in fp32 internally.
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), 'SAME',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))


def _norm_act(x, scale, shift, relu=True):
    # Training-mode batch norm without running stats (throughput bench
    # parity with the reference's train-loop benchmark).
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=(0, 1, 2), keepdims=True)
    var = x32.var(axis=(0, 1, 2), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + 1e-5) * scale + shift
    if relu:
        y = jax.nn.relu(y)
    return y.astype(x.dtype)


def init_params(key: jax.Array, cfg: ResNetConfig) -> Params:
    keys = iter(jax.random.split(key, 256))
    params: Params = {
        'stem': _conv_init(next(keys), 7, 7, 3, cfg.width, cfg.dtype),
        'stem_scale': jnp.ones((cfg.width,), jnp.float32),
        'stem_shift': jnp.zeros((cfg.width,), jnp.float32),
        'stages': [],
    }
    cin = cfg.width
    for stage, (blocks, width) in enumerate(
            zip(cfg.stage_sizes, cfg.stage_widths)):
        stage_params = []
        for b in range(blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            cout = width * (4 if cfg.bottleneck else 1)
            blk: Params = {}
            if cfg.bottleneck:
                blk['conv1'] = _conv_init(next(keys), 1, 1, cin, width,
                                          cfg.dtype)
                blk['conv2'] = _conv_init(next(keys), 3, 3, width, width,
                                          cfg.dtype)
                blk['conv3'] = _conv_init(next(keys), 1, 1, width, cout,
                                          cfg.dtype)
            else:
                blk['conv1'] = _conv_init(next(keys), 3, 3, cin, width,
                                          cfg.dtype)
                blk['conv2'] = _conv_init(next(keys), 3, 3, width, cout,
                                          cfg.dtype)
            for i in range(3 if cfg.bottleneck else 2):
                blk[f'scale{i+1}'] = jnp.ones((width if i < (
                    2 if cfg.bottleneck else 1) else cout,), jnp.float32)
                blk[f'shift{i+1}'] = jnp.zeros_like(blk[f'scale{i+1}'])
            if stride != 1 or cin != cout:
                blk['proj'] = _conv_init(next(keys), 1, 1, cin, cout,
                                         cfg.dtype)
            stage_params.append(blk)
            cin = cout
        params['stages'].append(stage_params)
    params['head'] = jax.random.normal(
        next(keys), (cin, cfg.num_classes), cfg.dtype) * (1.0 / cin)**0.5
    return params


def _block_forward(cfg: ResNetConfig, x, blk, stride: int):
    identity = x
    if cfg.bottleneck:
        y = _norm_act(_conv(x, blk['conv1']), blk['scale1'], blk['shift1'])
        y = _norm_act(_conv(y, blk['conv2'], stride), blk['scale2'],
                      blk['shift2'])
        y = _norm_act(_conv(y, blk['conv3']), blk['scale3'], blk['shift3'],
                      relu=False)
    else:
        y = _norm_act(_conv(x, blk['conv1'], stride), blk['scale1'],
                      blk['shift1'])
        y = _norm_act(_conv(y, blk['conv2']), blk['scale2'], blk['shift2'],
                      relu=False)
    if 'proj' in blk:
        identity = _conv(x, blk['proj'], stride)
    return jax.nn.relu((y + identity).astype(jnp.float32)).astype(x.dtype)


def forward(params: Params, images: jax.Array,
            cfg: ResNetConfig) -> jax.Array:
    """images [N, H, W, 3] → logits [N, num_classes] fp32."""
    x = images.astype(cfg.dtype)
    x = _norm_act(_conv(x, params['stem'], stride=2), params['stem_scale'],
                  params['stem_shift'])
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), 'SAME')
    for stage, stage_params in enumerate(params['stages']):
        for b, blk in enumerate(stage_params):
            stride = 2 if (b == 0 and stage > 0) else 1
            x = _block_forward(cfg, x, blk, stride)
    x = x.astype(jnp.float32).mean(axis=(1, 2))
    return (x @ params['head'].astype(jnp.float32))


def loss_fn(params: Params, images: jax.Array, labels: jax.Array,
            cfg: ResNetConfig) -> jax.Array:
    logits = forward(params, images, cfg)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - gold)


def make_train_step(cfg: ResNetConfig, lr: float = 0.1,
                    mesh: Optional[Mesh] = None):
    """SGD+momentum train step; with a mesh, batch sharded over 'data'."""

    def step(params, momentum, images, labels):
        if mesh is not None:
            sharding = NamedSharding(mesh, P('data'))
            images = jax.lax.with_sharding_constraint(images, sharding)
            labels = jax.lax.with_sharding_constraint(labels, sharding)
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels,
                                                  cfg)

        def upd(m, g):
            if not isinstance(g, jnp.ndarray) or g.dtype == jnp.int32:
                return m
            return 0.9 * m + g.astype(jnp.float32)

        new_momentum = jax.tree.map(upd, momentum, grads)

        def apply(p, m):
            if not isinstance(p, jnp.ndarray) or p.dtype == jnp.int32:
                return p
            return (p.astype(jnp.float32) - lr * m).astype(p.dtype)

        new_params = jax.tree.map(apply, params, new_momentum)
        return new_params, new_momentum, loss

    return jax.jit(step, donate_argnums=(0, 1))


def zeros_momentum(params: Params):
    return jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32)
        if isinstance(p, jnp.ndarray) and p.dtype != jnp.int32 else p,
        params)


def main() -> None:
    """Throughput bench CLI (images/sec) — the DDP benchmark counterpart."""
    import argparse
    import os
    import time
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='resnet101',
                        choices=sorted(CONFIGS))
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--image-size', type=int, default=224)
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--data-parallel', action='store_true',
                        help='shard the batch over all visible devices')
    parser.add_argument('--distributed', action='store_true')
    args = parser.parse_args()
    if os.environ.get('JAX_PLATFORMS'):
        jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
    if args.distributed:
        jax.distributed.initialize()
    cfg = CONFIGS[args.model]
    mesh = None
    if args.data_parallel:
        import numpy as np
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs.reshape(devs.size), ('data',))
    params = init_params(jax.random.PRNGKey(0), cfg)
    momentum = zeros_momentum(params)
    step = make_train_step(cfg, mesh=mesh)
    images = jax.random.normal(
        jax.random.PRNGKey(1),
        (args.batch_size, args.image_size, args.image_size, 3),
        jnp.float32)
    labels = jax.random.randint(jax.random.PRNGKey(2), (args.batch_size,),
                                0, cfg.num_classes)
    params, momentum, loss = step(params, momentum, images, labels)
    float(loss)  # sync
    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, momentum, loss = step(params, momentum, images, labels)
    final = float(loss)
    dt = time.perf_counter() - t0
    import json
    print(json.dumps({
        'metric': 'resnet_train_examples_per_sec',
        'model': args.model,
        'value': round(args.steps * args.batch_size / dt, 1),
        'unit': 'examples/s',
        'n_devices': len(jax.devices()),
        'loss': round(final, 4),
    }), flush=True)


if __name__ == '__main__':
    main()
