"""Llama-3.1-family decoder, pure functional JAX, TPU-first.

The flagship model for the north-star benchmark (BASELINE.md: Llama-3.1-8B
finetune on TPU vs the reference's ``llm/llama-3_1-finetuning/lora.yaml`` on
8xA100). Design:

* Pure pytree params + a jit-compiled forward: no framework object graph,
  so sharding is a same-structure pytree of ``PartitionSpec`` and XLA GSPMD
  handles all collectives.
* bf16 params/activations, fp32 RMSNorm accumulations and logits; matmuls
  hit the MXU with `preferred_element_type=float32` accumulation.
* ``lax.scan`` over decoder blocks (one compiled block body, fast compiles
  at any depth) + ``jax.checkpoint`` per block (remat: HBM is the usual
  bottleneck, recompute beats re-read).
* GQA + RoPE + SwiGLU, matching Llama-3/3.1 shapes.
* Sharding rules (scaling-book recipe): contraction dims over 'model'
  (tensor parallel within a host's ICI-adjacent chips), the other dim over
  'fsdp'; embeddings vocab-sharded over 'model'.
"""
import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, MODEL_AXIS, SEQ_AXIS

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # Remat each block's activations (trade FLOPs for HBM).
    remat: bool = True
    # Remat policy: 'full' recomputes everything in backward; 'dots' saves
    # every linear-layer GEMM output (no-batch-dim dots); 'ffn' saves only
    # the two big FFN GEMM outputs (w1/w3 — ~60% of block FLOPs) and
    # recomputes the cheap rest. Pick the richest policy HBM allows.
    remat_policy: str = 'full'
    # Use ring attention (sequence parallelism over the 'seq' mesh axis).
    ring_attention: bool = False
    # Use the Pallas flash-attention kernel (TPU; falls back to the XLA
    # path off-TPU). Wins at long sequence lengths where [S,S] logits
    # would pressure HBM.
    flash_attention: bool = False
    # Cross-entropy computed in sequence chunks so the [B, S, vocab]
    # logits tensor never materializes in HBM (1 = unchunked). The lm_head
    # matmul + softmax run per chunk under jax.checkpoint; backward
    # recomputes each chunk's logits instead of storing them.
    ce_chunks: int = 1

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        emb = self.vocab_size * self.dim
        per_layer = (
            self.dim * self.n_heads * self.head_dim +          # wq
            2 * self.dim * self.n_kv_heads * self.head_dim +   # wk, wv
            self.n_heads * self.head_dim * self.dim +          # wo
            3 * self.dim * self.ffn_dim +                      # w1, w2, w3
            2 * self.dim)                                      # norms
        return 2 * emb + self.n_layers * per_layer + self.dim

    def flops_per_token(self, seq_len: int) -> float:
        """Approx fwd+bwd FLOPs per token (6N + attention term)."""
        n = self.num_params() - self.vocab_size * self.dim  # non-embedding
        attn = 12 * self.n_layers * self.dim * seq_len  # causal ~ s/2 * 2
        return 6 * n + attn


# Published Llama-3.x shapes + small configs for tests/benches.
CONFIGS: Dict[str, LlamaConfig] = {
    'llama3-8b': LlamaConfig(),
    'llama3-70b': LlamaConfig(dim=8192, n_layers=80, n_heads=64,
                              n_kv_heads=8, ffn_dim=28672),
    'llama3-1b': LlamaConfig(dim=2048, n_layers=16, n_heads=32,
                             n_kv_heads=8, ffn_dim=8192,
                             vocab_size=128256),
    # ~160M-class model for single-chip benches (MXU-saturating dims).
    'bench-160m': LlamaConfig(vocab_size=32768, dim=1024, n_layers=12,
                              n_heads=16, n_kv_heads=8, ffn_dim=4096,
                              max_seq_len=2048),
    # ~1.1B-class model sized to fill a single v5e chip's HBM: d=2048
    # matmuls keep the MXU busy (the 160M model's d=1024 GEMMs are
    # bandwidth-bound), chunked CE keeps the logits out of HBM.
    'bench-1b': LlamaConfig(vocab_size=32768, dim=2048, n_layers=16,
                            n_heads=16, n_kv_heads=8, ffn_dim=8192,
                            max_seq_len=2048, ce_chunks=8),
    # CPU-scale bench model: big enough that a decode step is compute-
    # (not dispatch-) dominated on a laptop/CI core, so scheduling
    # benches (decode_bench --workload mixed) measure batching policy
    # rather than framework overhead. 'debug' stays the test model.
    'bench-cpu': LlamaConfig(vocab_size=2048, dim=256, n_layers=3,
                             n_heads=4, n_kv_heads=2, ffn_dim=768,
                             max_seq_len=256, remat=False),
    'debug': LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, ffn_dim=128, max_seq_len=128,
                         remat=False),
}


# ------------------------------------------------------------------- init


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialize a param pytree. Layer params are stacked along a leading

    axis (scanned), so the tree has one entry per weight *kind*."""
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    init = jax.nn.initializers.normal(stddev=0.02)
    hd = cfg.head_dim

    def stack_init(k, shape):
        return init(k, (cfg.n_layers,) + shape, cfg.dtype)

    ks = jax.random.split(k_layers, 7)
    params: Params = {
        'tok_embedding': init(k_emb, (cfg.vocab_size, cfg.dim), cfg.dtype),
        'layers': {
            'attn_norm': jnp.ones((cfg.n_layers, cfg.dim), cfg.dtype),
            'wq': stack_init(ks[0], (cfg.dim, cfg.n_heads * hd)),
            'wk': stack_init(ks[1], (cfg.dim, cfg.n_kv_heads * hd)),
            'wv': stack_init(ks[2], (cfg.dim, cfg.n_kv_heads * hd)),
            'wo': stack_init(ks[3], (cfg.n_heads * hd, cfg.dim)),
            'ffn_norm': jnp.ones((cfg.n_layers, cfg.dim), cfg.dtype),
            'w1': stack_init(ks[4], (cfg.dim, cfg.ffn_dim)),
            'w3': stack_init(ks[5], (cfg.dim, cfg.ffn_dim)),
            'w2': stack_init(ks[6], (cfg.ffn_dim, cfg.dim)),
        },
        'out_norm': jnp.ones((cfg.dim,), cfg.dtype),
        'lm_head': init(k_out, (cfg.dim, cfg.vocab_size), cfg.dtype),
    }
    return params


def param_partition_specs(cfg: LlamaConfig) -> Params:
    """Same-structure pytree of PartitionSpecs (megatron-style TP + FSDP).

    Contraction/head dims over 'model'; the complementary dim over 'fsdp'.
    Layer-stacked tensors lead with None (the scan axis is replicated).
    """
    del cfg
    return {
        'tok_embedding': P(MODEL_AXIS, FSDP_AXIS),
        'layers': {
            'attn_norm': P(None, None),
            'wq': P(None, FSDP_AXIS, MODEL_AXIS),
            'wk': P(None, FSDP_AXIS, MODEL_AXIS),
            'wv': P(None, FSDP_AXIS, MODEL_AXIS),
            'wo': P(None, MODEL_AXIS, FSDP_AXIS),
            'ffn_norm': P(None, None),
            'w1': P(None, FSDP_AXIS, MODEL_AXIS),
            'w3': P(None, FSDP_AXIS, MODEL_AXIS),
            'w2': P(None, MODEL_AXIS, FSDP_AXIS),
        },
        'out_norm': P(None),
        'lm_head': P(FSDP_AXIS, MODEL_AXIS),
    }


# ---------------------------------------------------------------- forward


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * weight


def _rope_freqs(cfg: LlamaConfig, positions: jax.Array) -> Tuple[jax.Array,
                                                                 jax.Array]:
    hd = cfg.head_dim
    inv_freq = 1.0 / (cfg.rope_theta
                      **(jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B?,S,hd/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, H, hd]; cos/sin: [S, hd/2] or [B, S, hd/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    # Insert the head axis: [.., S, hd/2] → [.., S, 1, hd/2]; leading batch
    # dims broadcast.
    cos = cos[..., :, None, :]
    sin = sin[..., :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def full_sequence_attention(cfg: LlamaConfig, q: jax.Array, k: jax.Array,
                            v: jax.Array,
                            seq_axis_sharded: bool = False) -> jax.Array:
    """The config-selected attention for full (non-cached) sequences:
    ring (sequence parallel) > Pallas flash > dense XLA. Single source of
    truth for train, prefill, and MoE paths."""
    if seq_axis_sharded:
        return attention_ops.ring_attention(q, k, v, axis_name=SEQ_AXIS)
    if cfg.flash_attention:
        from skypilot_tpu.ops import flash_attention as fa
        return fa.flash_attention(q, k, v, True)
    return attention_ops.gqa_attention(q, k, v, causal=True)


def attn_sublayer(cfg: LlamaConfig, x: jax.Array, layer: Params,
                  cos: jax.Array, sin: jax.Array,
                  seq_axis_sharded: bool = False
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Norm → QKV → RoPE → attention → residual. Returns (x, k, v) so
    prefill can seed the KV cache from the same code path training uses."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    h = rms_norm(x, layer['attn_norm'], cfg.norm_eps)
    q = _mm(h, layer['wq']).reshape(b, s, cfg.n_heads, hd)
    k = _mm(h, layer['wk']).reshape(b, s, cfg.n_kv_heads, hd)
    v = _mm(h, layer['wv']).reshape(b, s, cfg.n_kv_heads, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn_out = full_sequence_attention(cfg, q, k, v, seq_axis_sharded)
    attn_out = attn_out.reshape(b, s, cfg.n_heads * hd)
    # Named for the 'attn' remat policy: saving this [B,S,dim]-sized
    # tensor (cheap vs the [B,S,ffn_dim] FFN activations) lets the
    # backward pass skip recomputing QKV projections + the flash kernel.
    attn_out = checkpoint_name(attn_out, 'attn_out')
    return x + _mm(attn_out, layer['wo']).astype(cfg.dtype), k, v


def quant_mm(x: jax.Array, w) -> jax.Array:
    """Matmul that dispatches on int8-quantized weights (serving path;
    see ops/quant.py — int8×int8 runs ~2× on the v5e/v6e MXU and halves
    weight HBM traffic). Public: decode.py routes its projections here."""
    from skypilot_tpu.ops import quant
    if isinstance(w, quant.QuantizedTensor):
        return quant.int8_matmul(x, w)
    return x @ w


_mm = quant_mm  # intra-module shorthand


def ffn_sublayer(cfg: LlamaConfig, x: jax.Array,
                 layer: Params) -> jax.Array:
    """Norm → SwiGLU → residual (dense FFN)."""
    h = rms_norm(x, layer['ffn_norm'], cfg.norm_eps)
    w1_out = checkpoint_name(_mm(h, layer['w1']), 'ffn_w1')
    w3_out = checkpoint_name(_mm(h, layer['w3']), 'ffn_w3')
    gate = jax.nn.silu(w1_out.astype(jnp.float32))
    up = w3_out.astype(jnp.float32)
    down = _mm((gate * up).astype(cfg.dtype), layer['w2'])
    return x + down.astype(cfg.dtype)


def _block(cfg: LlamaConfig, x: jax.Array, layer: Params, cos: jax.Array,
           sin: jax.Array, seq_axis_sharded: bool) -> jax.Array:
    x, _, _ = attn_sublayer(cfg, x, layer, cos, sin, seq_axis_sharded)
    return ffn_sublayer(cfg, x, layer)


def forward_hidden(params: Params,
                   tokens: jax.Array,
                   cfg: LlamaConfig,
                   positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 → final normed hidden states [B, S, dim].

    Scans over the stacked layer params; each block body optionally
    rematerialized.
    """
    b, s = tokens.shape
    del b
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.int32)
    cos, sin = _rope_freqs(cfg, positions)
    x = params['tok_embedding'][tokens].astype(cfg.dtype)

    seq_sharded = cfg.ring_attention

    def body(carry, layer):
        out = _block(cfg, carry, layer, cos, sin, seq_sharded)
        return out, None

    if cfg.remat:
        if cfg.remat_policy == 'full':
            policy = None
        elif cfg.remat_policy == 'dots':
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == 'ffn':
            policy = jax.checkpoint_policies.save_only_these_names(
                'ffn_w1', 'ffn_w3')
        elif cfg.remat_policy == 'ffn1':
            # Half the 'ffn' policy's [B,S,ffn_dim] stacking cost for
            # half its recompute saving.
            policy = jax.checkpoint_policies.save_only_these_names(
                'ffn_w1')
        elif cfg.remat_policy == 'attn':
            # Save the attention outputs ([B,S,dim] per layer — 16x
            # smaller than the FFN activations the 'ffn' policy stacks):
            # backward recomputes only norms + FFN, not QKV + flash.
            policy = jax.checkpoint_policies.save_only_these_names(
                'attn_out')
        else:
            raise ValueError(f'unknown remat_policy: {cfg.remat_policy!r} '
                             "(expected 'full', 'dots', 'ffn' or 'attn')")
        body = jax.checkpoint(body, prevent_cse=False, policy=policy)
    x, _ = jax.lax.scan(body, x, params['layers'])

    return rms_norm(x, params['out_norm'], cfg.norm_eps)


def forward(params: Params,
            tokens: jax.Array,
            cfg: LlamaConfig,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, vocab] float32."""
    x = forward_hidden(params, tokens, cfg, positions)
    return (x @ params['lm_head']).astype(jnp.float32)


def _xent_from_logits(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Summed (not mean) next-token cross-entropy, fp32."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None],
                               axis=-1).squeeze(-1)
    return jnp.sum(logz - gold)


def chunked_cross_entropy(x: jax.Array, lm_head: jax.Array,
                          targets: jax.Array, num_chunks: int) -> jax.Array:
    """Mean CE over [B, S] without ever materializing [B, S, vocab].

    Scans sequence chunks; each chunk's lm_head GEMM + softmax runs under
    ``jax.checkpoint``, so backward recomputes the chunk's logits rather
    than holding S×vocab activations (the HBM cliff that caps the bench
    model's batch size — at vocab 32k, seq 2048, bs 16 the fp32 logits
    alone are 8 GB).
    """
    b, s, d = x.shape
    assert s % num_chunks == 0, (s, num_chunks)
    xs = x.reshape(b, num_chunks, s // num_chunks, d).swapaxes(0, 1)
    ts = targets.reshape(b, num_chunks, s // num_chunks).swapaxes(0, 1)

    @jax.checkpoint
    def chunk(carry, xt):
        xc, tc = xt
        logits = (xc @ lm_head).astype(jnp.float32)
        return carry + _xent_from_logits(logits, tc), None

    total, _ = jax.lax.scan(chunk, jnp.zeros((), jnp.float32), (xs, ts))
    return total / (b * s)


def loss_fn(params: Params, tokens: jax.Array, targets: jax.Array,
            cfg: LlamaConfig) -> jax.Array:
    """Mean next-token cross-entropy (targets = tokens shifted by caller)."""
    x = forward_hidden(params, tokens, cfg)
    if cfg.ce_chunks > 1:
        return chunked_cross_entropy(x, params['lm_head'], targets,
                                     cfg.ce_chunks)
    logits = (x @ params['lm_head']).astype(jnp.float32)
    return _xent_from_logits(logits, targets) / targets.size
