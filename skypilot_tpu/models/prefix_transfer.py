"""Cross-replica KV prefix-block transfer: wire format + HTTP fetch.

The serving half of the fleet-wide prefix cache (ROADMAP item 3): each
replica's radix cache (PR 8) holds the KV blocks of the prompt prefixes
it has served, and the prefix-affinity LB routes shared-prefix traffic
to one owner — but rehashes (load spill, drain, failover) still land
requests on replicas whose pool is cold for that prefix. Instead of
re-prefilling, the engine pulls the matched blocks from a peer:

* The OWNER side (``serve/model_server.py`` ``POST /prefix_blocks``)
  radix-matches the posted token prefix on the engine loop thread and
  returns the matched pool blocks, serialized by :func:`encode_payload`.
  Reads go through ``jax.device_get`` of the pool gather, which under a
  tensor-parallel mesh assembles the full (unsharded) block from every
  shard — the wire format is always the logical
  ``[L, n_blocks, block_k, Hkv, hd]`` view, so a tp=4 owner can feed a
  tp=1 peer and vice versa (each side re-shards on injection).
* The MISS side (``models/engine.py`` ``_maybe_prefix_fetch``) POSTs
  the block-aligned prompt prefix to the LB-advertised owner (the
  ``X-Skytpu-Prefix-Owner`` hop header) or the configured
  ``SKYTPU_PREFIX_PEERS``, bounded by
  ``SKYTPU_PREFIX_FETCH_BUDGET_SECONDS`` — a slow or dead peer degrades
  the admission to plain prefill, never stalls it.

The dtype survives exactly: bf16 pools ship bf16 bytes, int8 pools ship
int8 values plus their fp32 scale planes — which is what makes a
fetched-block decode bit-identical to the local re-prefill it replaced
(pinned in tier-1).
"""
import base64
import json
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

# np.dtype('bfloat16') resolves once ml_dtypes (a jax dependency) has
# registered its extension dtypes.
import ml_dtypes  # noqa: F401  pylint: disable=unused-import

# Engine-side knobs (read in models/engine.py; registered in
# utils/env_registry.py).
PREFIX_PEERS_ENV = 'SKYTPU_PREFIX_PEERS'
FETCH_BUDGET_ENV = 'SKYTPU_PREFIX_FETCH_BUDGET_SECONDS'
DEFAULT_FETCH_BUDGET_SECONDS = 0.5
FETCH_MIN_TOKENS_ENV = 'SKYTPU_PREFIX_FETCH_MIN_TOKENS'
# A peer whose fetch failed (timeout/connect error/garbage) is skipped
# for this long: without the backoff, one dead-but-configured peer
# costs every eligible cold admission a budget's worth of engine-loop
# stall, forever.
FETCH_BACKOFF_ENV = 'SKYTPU_PREFIX_FETCH_BACKOFF_SECONDS'
DEFAULT_FETCH_BACKOFF_SECONDS = 10.0
# Disaggregated prefill/decode handoff (push direction): the per-push
# budget for streaming one chunk's worth of pool blocks to the decode
# peer. Pushes happen from the prefill engine loop, so the budget bounds
# the loop stall exactly like the fetch budget does — a slow decode peer
# degrades the request to decode-in-place, never wedges prefill.
PUSH_BUDGET_ENV = 'SKYTPU_HANDOFF_PUSH_BUDGET_SECONDS'
DEFAULT_PUSH_BUDGET_SECONDS = 2.0


def encode_array(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(a)
    return {'shape': list(a.shape), 'dtype': str(a.dtype),
            'data': base64.b64encode(a.tobytes()).decode('ascii')}


def decode_array(d: Dict[str, Any]) -> np.ndarray:
    buf = base64.b64decode(d['data'])
    return np.frombuffer(buf, dtype=np.dtype(str(d['dtype']))).reshape(
        [int(s) for s in d['shape']])


def empty_payload(from_tokens: int, block_k: int,
                  kv_cache_dtype: str) -> Dict[str, Any]:
    """An honest "nothing cached past from_tokens" reply. Transport
    functions must return THIS (not None) for a reachable-but-cold
    peer: None means transport failure and puts the peer in the
    engine's failure backoff."""
    return {'matched_tokens': int(from_tokens),
            'from_tokens': int(from_tokens),
            'block_k': int(block_k),
            'kv_cache_dtype': kv_cache_dtype,
            'arrays': {}}


def encode_payload(matched_tokens: int, from_tokens: int, block_k: int,
                   kv_cache_dtype: str,
                   arrays: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """The ``/prefix_blocks`` response body: the pool arrays covering
    blocks ``[from_tokens // block_k, matched_tokens // block_k)`` of
    the posted prefix, each ``[L, n, block_k, ...]``."""
    return {
        'matched_tokens': int(matched_tokens),
        'from_tokens': int(from_tokens),
        'block_k': int(block_k),
        'kv_cache_dtype': kv_cache_dtype,
        'arrays': {name: encode_array(a) for name, a in arrays.items()},
    }


def decode_payload(body: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Inverse of :func:`encode_payload`; None for malformed bodies
    (a corrupt peer response degrades to plain prefill, it does not
    crash admission)."""
    try:
        out = {
            'matched_tokens': int(body['matched_tokens']),
            'from_tokens': int(body['from_tokens']),
            'block_k': int(body['block_k']),
            'kv_cache_dtype': str(body['kv_cache_dtype']),
            'arrays': {str(name): decode_array(d)
                       for name, d in body['arrays'].items()},
        }
    except (KeyError, TypeError, ValueError):
        return None
    return out


def http_fetch(peer_url: str, tokens: Sequence[int], from_tokens: int,
               budget_seconds: float,
               instance: Optional[str] = None
               ) -> Optional[Dict[str, Any]]:
    """Default peer transport: ``POST <peer>/prefix_blocks`` with the
    block-aligned prompt prefix; returns the decoded payload, a
    ``{'self': True}`` marker (the peer IS the calling engine —
    instance-id echo), or None on any failure (timeout, non-200,
    malformed body). The budget bounds the TOTAL stall: requests'
    scalar timeout applies to connect and read independently, so it is
    split into a (connect, read) tuple — a stalling peer costs at most
    ~one budget, then the admission prefills locally."""
    import requests
    half = max(budget_seconds / 2, 1e-3)
    deadline = time.monotonic() + max(budget_seconds, 1e-3)
    try:
        resp = requests.post(
            peer_url.rstrip('/') + '/prefix_blocks',
            # budget_seconds rides along so the OWNER caps its export
            # wait too: past the fetcher's timeout nobody reads the
            # reply, and the owner must not burn engine-loop + encode
            # time producing it. `instance` lets the owner answer "I
            # am you" instantly under a fleet-shared peers list.
            json={'prompt': [int(t) for t in tokens],
                  'from_tokens': int(from_tokens),
                  'budget_seconds': float(budget_seconds),
                  'instance': instance},
            timeout=(half, half), stream=True)
    except requests.RequestException:
        return None
    try:
        if resp.status_code != 200:
            return None
        # Stream the body under a WALL-CLOCK deadline: requests' read
        # timeout is between-bytes, so a slow-but-streaming peer could
        # otherwise hold the (engine-loop-blocking) fetch far past the
        # budget while each individual read stays under the timeout.
        chunks = []
        for chunk in resp.iter_content(64 * 1024):
            chunks.append(chunk)
            if time.monotonic() > deadline:
                return None
    except requests.RequestException:
        return None
    finally:
        resp.close()
    try:
        body = json.loads(b''.join(chunks))
    except ValueError:
        return None
    if isinstance(body, dict) and body.get('self'):
        return {'self': True}
    return decode_payload(body)


def http_push(peer_url: str, tokens: Sequence[int],
              payload: Dict[str, Any], budget_seconds: float,
              instance: Optional[str] = None) -> bool:
    """Handoff transport (push direction): ``POST <peer>/handoff_blocks``
    with the prompt prefix the payload's blocks cover, returning True
    only when the decode peer acked the injection (200 + ``ok``). Any
    failure — timeout, non-200, injection error, malformed reply —
    returns False and the prefill side degrades to decode-in-place.

    The same wire format as the fetch direction rides the body
    (:func:`encode_payload` fields over the engine's raw-numpy export),
    so dtype/shape/block_k validation on the decode side is shared
    with PR 15's fetch injection."""
    import requests
    from skypilot_tpu.utils import chaos
    body = encode_payload(payload['matched_tokens'],
                          payload['from_tokens'], payload['block_k'],
                          payload['kv_cache_dtype'], payload['arrays'])
    body['prompt'] = [int(t) for t in tokens]
    body['instance'] = instance
    data = json.dumps(body)
    if chaos.should_fire('handoff_truncate'):
        # Truncated block stream: ship half the serialized body. The
        # decode side sees malformed JSON, answers non-2xx, and the
        # prefill side degrades — the chaos e2e pins that the request
        # is still answered.
        data = data[:len(data) // 2]
    half = max(budget_seconds / 2, 1e-3)
    try:
        resp = requests.post(
            peer_url.rstrip('/') + '/handoff_blocks',
            data=data, headers={'Content-Type': 'application/json'},
            timeout=(half, half))
    except requests.RequestException:
        return False
    try:
        if resp.status_code != 200:
            return False
        reply = resp.json()
    except (requests.RequestException, ValueError):
        return False
    finally:
        resp.close()
    return bool(isinstance(reply, dict) and reply.get('ok'))
