"""Sharded training loop pieces: optimizer, train step, MFU accounting.

The TPU rewrite of the reference's torch finetune recipes
(``llm/llama-3_1-finetuning``, ``examples/torch_ddp_benchmark``): one jitted
train step over a ``Mesh`` with GSPMD shardings — XLA inserts the
fsdp all-gathers/reduce-scatters and tensor-parallel collectives over ICI.
"""
import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(10 * cfg.warmup_steps, 1000))
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(schedule,
                    b1=cfg.beta1,
                    b2=cfg.beta2,
                    weight_decay=cfg.weight_decay),
    )


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=['params', 'opt_state', 'step'],
                   meta_fields=[])
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def init_train_state(key: jax.Array, model_cfg: llama.LlamaConfig,
                     train_cfg: TrainConfig,
                     mesh: Optional[Mesh] = None) -> TrainState:
    """Initialize params (+Adam state) directly sharded over the mesh —

    params never materialize unsharded (jit with out_shardings)."""
    tx = make_optimizer(train_cfg)
    specs = llama.param_partition_specs(model_cfg)

    def _init(k):
        params = llama.init_params(k, model_cfg)
        opt_state = tx.init(params)
        return params, opt_state

    if mesh is None:
        params, opt_state = jax.jit(_init)(key)
    else:
        param_shardings = mesh_lib.spec_to_sharding(mesh, specs)
        abstract = jax.eval_shape(_init, key)
        opt_shardings = _opt_state_shardings(abstract[1], param_shardings,
                                             mesh)
        params, opt_state = jax.jit(
            _init, out_shardings=(param_shardings, opt_shardings))(key)
    return TrainState(params=params,
                      opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32))


def _opt_state_shardings(abstract_opt_state, param_shardings, mesh: Mesh):
    """Adam moments mirror the param shardings; everything else replicates.

    The moments are pytrees congruent to params (same treedef), so they are
    detected structurally rather than by optax state type.
    """
    params_treedef = jax.tree.structure(param_shardings)

    def assign(state):
        if jax.tree.structure(state) == params_treedef:
            return param_shardings
        if isinstance(state, tuple):
            fields = getattr(state, '_fields', None)
            mapped = [assign(s) for s in state]
            # namedtuples (incl. empty ones like optax EmptyState) must
            # keep their type; plain tuples stay tuples.
            return type(state)(*mapped) if fields is not None \
                else tuple(mapped)
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), state)

    return assign(abstract_opt_state)


def make_train_step(model_cfg: llama.LlamaConfig, train_cfg: TrainConfig,
                    mesh: Optional[Mesh] = None):
    """Returns jitted (state, tokens, targets) → (state, metrics).

    With a mesh, inputs are constrained to batch-over-(data, fsdp) and the
    whole step donates the state (in-place update, halves HBM traffic).
    """
    tx = make_optimizer(train_cfg)

    def step_fn(state: TrainState, tokens: jax.Array,
                targets: jax.Array) -> Tuple[TrainState, Dict[str, Any]]:
        if mesh is not None:
            tokens = jax.lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, mesh_lib.batch_spec()))
            targets = jax.lax.with_sharding_constraint(
                targets, NamedSharding(mesh, mesh_lib.batch_spec()))
        loss, grads = jax.value_and_grad(llama.loss_fn)(state.params,
                                                       tokens, targets,
                                                       model_cfg)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        grad_norm = optax.global_norm(grads)
        new_state = TrainState(params=new_params,
                               opt_state=new_opt,
                               step=state.step + 1)
        return new_state, {'loss': loss, 'grad_norm': grad_norm}

    return jax.jit(step_fn, donate_argnums=(0,))


def tokens_per_second_to_mfu(tokens_per_sec: float,
                             model_cfg: llama.LlamaConfig, seq_len: int,
                             peak_flops: float) -> float:
    """Model FLOPs utilization given hardware peak (bf16) FLOPs/sec."""
    return tokens_per_sec * model_cfg.flops_per_token(seq_len) / peak_flops
