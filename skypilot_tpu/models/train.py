"""Sharded training loop pieces: optimizer, train step, MFU accounting.

The TPU rewrite of the reference's torch finetune recipes
(``llm/llama-3_1-finetuning``, ``examples/torch_ddp_benchmark``): one jitted
train step over a ``Mesh`` with GSPMD shardings — XLA inserts the
fsdp all-gathers/reduce-scatters and tensor-parallel collectives over ICI.
"""
import dataclasses
import functools
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100
    # Storage dtype for the Adam moments. 'bfloat16' halves optimizer
    # HBM (9.1GB → 4.6GB on a 1.1B model) — on a 16GB v5e that buys a
    # lighter remat policy worth ~15% step time; the moment update math
    # still runs in f32. Default stays f32 (exact Adam).
    moment_dtype: str = 'float32'


def _scale_by_adam_low_mem(b1: float, b2: float, eps: float,
                           moment_dtype) -> optax.GradientTransformation:
    """Adam moment tracking with mu AND nu stored in ``moment_dtype``
    (optax.scale_by_adam only casts mu). Math in f32; storage cast."""

    def init_fn(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=moment_dtype)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32),
                                      mu=jax.tree.map(zeros, params),
                                      nu=jax.tree.map(zeros, params))

    def update_fn(updates, state, params=None):
        del params
        count = optax.safe_int32_increment(state.count)
        mu32 = jax.tree.map(
            lambda g, m: m.astype(jnp.float32) * b1 +
            g.astype(jnp.float32) * (1 - b1), updates, state.mu)
        nu32 = jax.tree.map(
            lambda g, v: v.astype(jnp.float32) * b2 +
            jnp.square(g.astype(jnp.float32)) * (1 - b2), updates,
            state.nu)
        bc1 = 1 - b1**count.astype(jnp.float32)
        bc2 = 1 - b2**count.astype(jnp.float32)
        scaled = jax.tree.map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu32,
            nu32)
        cast = lambda t: jax.tree.map(lambda x: x.astype(moment_dtype), t)
        return scaled, optax.ScaleByAdamState(count=count,
                                              mu=cast(mu32),
                                              nu=cast(nu32))

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(10 * cfg.warmup_steps, 1000))
    if cfg.moment_dtype != 'float32':
        adam = optax.chain(
            _scale_by_adam_low_mem(cfg.beta1, cfg.beta2, 1e-8,
                                   jnp.dtype(cfg.moment_dtype)),
            optax.add_decayed_weights(cfg.weight_decay),
            optax.scale_by_learning_rate(schedule),
        )
    else:
        adam = optax.adamw(schedule,
                           b1=cfg.beta1,
                           b2=cfg.beta2,
                           weight_decay=cfg.weight_decay)
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        adam,
    )


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=['params', 'opt_state', 'step'],
                   meta_fields=[])
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def init_train_state(key: jax.Array, model_cfg: llama.LlamaConfig,
                     train_cfg: TrainConfig,
                     mesh: Optional[Mesh] = None) -> TrainState:
    """Initialize params (+Adam state) directly sharded over the mesh —

    params never materialize unsharded (jit with out_shardings)."""
    tx = make_optimizer(train_cfg)
    specs = llama.param_partition_specs(model_cfg)

    def _init(k):
        params = llama.init_params(k, model_cfg)
        opt_state = tx.init(params)
        return params, opt_state

    if mesh is None:
        params, opt_state = jax.jit(_init)(key)
    else:
        param_shardings = mesh_lib.spec_to_sharding(mesh, specs)
        abstract = jax.eval_shape(_init, key)
        opt_shardings = _opt_state_shardings(abstract[1], param_shardings,
                                             mesh)
        params, opt_state = jax.jit(
            _init, out_shardings=(param_shardings, opt_shardings))(key)
    return TrainState(params=params,
                      opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32))


def _opt_state_shardings(abstract_opt_state, param_shardings, mesh: Mesh):
    """Adam moments mirror the param shardings; everything else replicates.

    The moments are pytrees congruent to params (same treedef), so they are
    detected structurally rather than by optax state type.
    """
    params_treedef = jax.tree.structure(param_shardings)

    def assign(state):
        if jax.tree.structure(state) == params_treedef:
            return param_shardings
        if isinstance(state, tuple):
            fields = getattr(state, '_fields', None)
            mapped = [assign(s) for s in state]
            # namedtuples (incl. empty ones like optax EmptyState) must
            # keep their type; plain tuples stay tuples.
            return type(state)(*mapped) if fields is not None \
                else tuple(mapped)
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), state)

    return assign(abstract_opt_state)


def make_train_step(model_cfg: llama.LlamaConfig, train_cfg: TrainConfig,
                    mesh: Optional[Mesh] = None):
    """Returns jitted (state, tokens, targets) → (state, metrics).

    With a mesh, inputs are constrained to batch-over-(data, fsdp) and the
    whole step donates the state (in-place update, halves HBM traffic).
    """
    tx = make_optimizer(train_cfg)

    def step_fn(state: TrainState, tokens: jax.Array,
                targets: jax.Array) -> Tuple[TrainState, Dict[str, Any]]:
        if mesh is not None:
            spec = mesh_lib.batch_spec(
                multislice=mesh_lib.DCN_AXIS in mesh.shape)
            tokens = jax.lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, spec))
            targets = jax.lax.with_sharding_constraint(
                targets, NamedSharding(mesh, spec))
        loss, grads = jax.value_and_grad(llama.loss_fn)(state.params,
                                                       tokens, targets,
                                                       model_cfg)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        grad_norm = optax.global_norm(grads)
        new_state = TrainState(params=new_params,
                               opt_state=new_opt,
                               step=state.step + 1)
        return new_state, {'loss': loss, 'grad_norm': grad_norm}

    return jax.jit(step_fn, donate_argnums=(0,))


def tokens_per_second_to_mfu(tokens_per_sec: float,
                             model_cfg: llama.LlamaConfig, seq_len: int,
                             peak_flops: float) -> float:
    """Model FLOPs utilization given hardware peak (bf16) FLOPs/sec."""
    return tokens_per_sec * model_cfg.flops_per_token(seq_len) / peak_flops


def train_loop(model_cfg: llama.LlamaConfig,
               train_cfg: TrainConfig,
               num_steps: int,
               batch_size: int,
               seq_len: int,
               mesh: Optional[Mesh] = None,
               checkpoint_dir: Optional[str] = None,
               save_every: int = 100,
               keep: int = 3,
               data_seed: int = 0,
               log_every: int = 10,
               sleep_per_step: float = 0.0,
               dataset: Optional['Any'] = None) -> 'TrainState':
    """Run (or RESUME) a training run with periodic checkpointing.

    The resume-from-step path the managed-jobs preemption story depends on
    (SURVEY §5.4): if ``checkpoint_dir`` holds a complete checkpoint, the
    state — params, Adam moments, AND step counter — restores from it and
    the loop continues at step N, not 0. Deterministic synthetic data is
    derived per-step from ``data_seed`` so a resumed run sees the same
    stream it would have unpreempted.
    """
    from skypilot_tpu.models import checkpoint as ckpt_lib
    from skypilot_tpu.utils import jax_cache

    key = jax.random.PRNGKey(0)
    start_step = 0
    state = None
    if checkpoint_dir:
        if ckpt_lib.list_steps(
                os.path.abspath(os.path.expanduser(checkpoint_dir))):
            # This run will RESUME: opt out of the shared persistent
            # compilation cache BEFORE the restore compiles anything.
            # Executables compiled against orbax-restored buffers are
            # not fully distinguished by jax<=0.4.x's cache key from
            # other processes' entries, and loading a cross-process
            # entry on the resume path corrupts the heap (free()/
            # malloc aborts, NaN losses) — the root cause of the
            # long-seed-broken checkpoint-resume recovery tests,
            # isolated by per-entry cache bisection. Recovery is rare;
            # one full re-compile per resume buys soundness.
            jax_cache.disable_persistent_cache()
        abstract = ckpt_lib.abstract_train_state(key, model_cfg, train_cfg,
                                                 mesh=mesh)
        restored = ckpt_lib.restore_latest(checkpoint_dir, abstract)
        if restored is not None:
            state, start_step = restored
            print(f'[train] resumed from step {start_step} '
                  f'({checkpoint_dir})', flush=True)
    if state is None:
        state = init_train_state(key, model_cfg, train_cfg, mesh=mesh)

    step_fn = make_train_step(model_cfg, train_cfg, mesh=mesh)

    import numpy as np

    # Telemetry: step-time histogram + tokens/sec + MFU gauges through
    # the process metrics registry, and an env-gated (SKYTPU_PROFILE_DIR)
    # jax.profiler capture window. Host-side only — no extra syncs.
    from skypilot_tpu.observability import runtime_metrics
    telemetry = runtime_metrics.TrainTelemetry(model_cfg=model_cfg,
                                               seq_len=seq_len)
    profiler = runtime_metrics.StepProfiler(tag='train')
    # No explicit step_start(): the first record_step silently arms the
    # timer, so the compile-dominated first step never pollutes the
    # step-time histogram.
    for step in range(start_step, num_steps):
        profiler.step()
        # Batches stay HOST numpy all the way into the jitted step: in a
        # multi-process gang every host computes the same (seed, step)-
        # deterministic global batch, and replicated-numpy inputs are
        # valid multi-process jit arguments — jit shards them per the
        # step's with_sharding_constraint. (Committing per-process with
        # jnp.asarray would produce non-globally-addressable arrays and
        # fail under a multi-host mesh.)
        if dataset is not None:
            # Real data: pure in (seed, step) — resume at step N replays
            # the exact unpreempted stream (models/data).
            tokens, targets = dataset.batch(step, batch_size, seq_len,
                                            seed=data_seed)
        else:
            rng = np.random.default_rng(
                np.random.SeedSequence([data_seed, step]))
            tokens = rng.integers(0, model_cfg.vocab_size,
                                  (batch_size, seq_len), dtype=np.int32)
            targets = np.roll(tokens, -1, axis=1)
        state, metrics = step_fn(state, tokens, targets)
        telemetry.record_step(tokens=batch_size * seq_len)
        if sleep_per_step:
            # Pacing knob for tests/demos (preemption windows).
            import time
            time.sleep(sleep_per_step)
        if log_every and (step + 1) % log_every == 0:
            print(f'[train] step {step + 1}/{num_steps} '
                  f'loss={float(metrics["loss"]):.4f}', flush=True)
        if checkpoint_dir and (step + 1) % save_every == 0:
            ckpt_lib.save(checkpoint_dir, state, step + 1, keep=keep)
            print(f'[train] checkpoint @ step {step + 1}', flush=True)
    profiler.stop()
    if (checkpoint_dir and num_steps > start_step and
            num_steps % save_every != 0):  # loop already saved otherwise
        ckpt_lib.save(checkpoint_dir, state, num_steps, keep=keep)
        print(f'[train] final checkpoint @ step {num_steps}', flush=True)
    return state


def main() -> None:
    """CLI for recipes: ``python -m skypilot_tpu.models.train ...``."""
    import argparse
    import os
    if os.environ.get('JAX_PLATFORMS'):
        # Some accelerator plugins override platform selection at
        # registration; restore the standard env semantics for recipes
        # that pin a backend (e.g. CPU smoke runs).
        jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
    parser = argparse.ArgumentParser(description='skypilot_tpu train loop')
    parser.add_argument('--model', default='debug',
                        choices=sorted(llama.CONFIGS))
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--batch-size', type=int, default=2)
    parser.add_argument('--seq-len', type=int, default=128)
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--save-every', type=int, default=10)
    parser.add_argument('--log-every', type=int, default=10)
    parser.add_argument('--sleep-per-step', type=float, default=0.0)
    # Multislice: the gang runtime exports MEGASCALE_NUM_SLICES on
    # multislice clusters (num_nodes > 1 TPU slices); the flag overrides.
    parser.add_argument('--num-slices', type=int,
                        default=int(os.environ.get('MEGASCALE_NUM_SLICES',
                                                   '1')))
    parser.add_argument('--data', default=None,
                        help='token file (models/data.py format); '
                        'default: deterministic synthetic stream')
    args = parser.parse_args()
    # Preemption-safe compile-cache writes BEFORE the first dispatch:
    # this process is exactly the one spot teardown kills mid-compile,
    # and jax<=0.4.x's non-atomic cache write would poison the shared
    # cache for every later resume (utils/jax_cache.py).
    from skypilot_tpu.utils import jax_cache
    jax_cache.harden_compilation_cache()
    # Multi-host gangs: the runtime injects JAX_COORDINATOR_ADDRESS /
    # JAX_NUM_PROCESSES / JAX_PROCESS_ID (gang_run.build_rank_envs).
    # jax only auto-reads the coordinator address from env — process
    # count/id must be passed explicitly or non-auto-detectable
    # clusters raise at startup.
    if int(os.environ.get('JAX_NUM_PROCESSES', '1')) > 1:
        jax.distributed.initialize(
            coordinator_address=os.environ['JAX_COORDINATOR_ADDRESS'],
            num_processes=int(os.environ['JAX_NUM_PROCESSES']),
            process_id=int(os.environ['JAX_PROCESS_ID']))
    cfg = llama.CONFIGS[args.model]
    n_devices = len(jax.devices())
    if args.num_slices > 1:
        mesh = mesh_lib.make_multislice_mesh(args.num_slices)
    elif n_devices > 1:
        mesh = mesh_lib.make_mesh()  # fsdp over every chip by default
    else:
        mesh = None
    dataset = None
    if args.data:
        from skypilot_tpu.models import data as data_lib
        dataset = data_lib.TokenDataset.open(args.data)
        if dataset.vocab_size > cfg.vocab_size:
            raise SystemExit(
                f'Dataset vocab {dataset.vocab_size} exceeds model '
                f'vocab {cfg.vocab_size}.')
    state = train_loop(cfg, TrainConfig(warmup_steps=5), args.steps,
                       args.batch_size, args.seq_len,
                       mesh=mesh,
                       checkpoint_dir=args.checkpoint_dir,
                       save_every=args.save_every,
                       log_every=args.log_every,
                       sleep_per_step=args.sleep_per_step,
                       dataset=dataset)
    print(f'[train] done at step {int(state.step)}', flush=True)


if __name__ == '__main__':
    main()
