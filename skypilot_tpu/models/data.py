"""Training data: memmap token shards with deterministic, resume-safe
batching.

The reference delegates data loading to user payloads (torch
DataLoader in its recipes); the TPU-native equivalent is deliberately
simple and jit-friendly: a flat binary file of token ids is memmapped
and sliced into [batch, seq+1] windows on the host, then fed to the
jitted step. Determinism contract (shared with ``train_loop``'s
synthetic stream): batch contents are a pure function of
``(seed, step)``, so a preempted run that resumes at step N sees
exactly the stream it would have seen unpreempted — no sampler state
in the checkpoint.

Dataset format: a raw little-endian token file (uint16 for vocab
< 65536, else uint32) with an optional sidecar ``<name>.json`` carrying
``{"dtype": "uint16", "vocab_size": N}``. ``python -m
skypilot_tpu.models.data encode <txt> <out.bin>`` builds one from
whitespace-tokenized text for smoke runs.
"""
import dataclasses
import json
import os
from typing import Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class TokenDataset:
    """Memmapped token file + (seed, step) → batch windows."""

    tokens: np.ndarray  # 1-D memmap of token ids
    vocab_size: int

    @classmethod
    def open(cls, path: str,
             vocab_size: Optional[int] = None) -> 'TokenDataset':
        path = os.path.expanduser(path)
        dtype = np.uint16
        sidecar = f'{os.path.splitext(path)[0]}.json'
        if os.path.exists(sidecar):
            with open(sidecar, encoding='utf-8') as f:
                meta = json.load(f)
            dtype = np.dtype(meta.get('dtype', 'uint16'))
            vocab_size = vocab_size or meta.get('vocab_size')
        tokens = np.memmap(path, dtype=dtype, mode='r')
        if tokens.size == 0:
            raise ValueError(f'Empty token file: {path}')
        if vocab_size is None:
            # One pass over the (memmapped) file; cheap at smoke scale,
            # and exact — a wrong vocab guess would crash the embedding
            # gather on-device with a far worse error.
            vocab_size = int(tokens.max()) + 1
        return cls(tokens=tokens, vocab_size=int(vocab_size))

    def num_windows(self, seq_len: int) -> int:
        return max(0, (self.tokens.size - 1) // seq_len)

    def batch(self, step: int, batch_size: int, seq_len: int,
              seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        """(tokens [B,S], targets [B,S]) for ``step`` — pure in
        (seed, step), sampling windows without replacement per epoch.

        Epoch ordering is a seeded permutation of window indices;
        consecutive steps walk it, wrapping to a re-seeded permutation
        per epoch. All hosts compute the same permutation (same seed)
        and feed the same full global numpy batch to the jitted train
        step — replicated-numpy inputs are valid in multi-process jit,
        which shards them per the step's sharding constraint
        (models/train.train_loop).
        """
        windows = self.num_windows(seq_len)
        if windows == 0:
            raise ValueError(
                f'Dataset too small for seq_len={seq_len} '
                f'({self.tokens.size} tokens).')
        steps_per_epoch = max(1, windows // batch_size)
        epoch, pos = divmod(step, steps_per_epoch)
        rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
        perm = rng.permutation(windows)
        idx = perm[(pos * batch_size + np.arange(batch_size)) % windows]
        rows = np.stack([
            self.tokens[i * seq_len:i * seq_len + seq_len + 1].astype(
                np.int32) for i in idx
        ])
        return rows[:, :-1], rows[:, 1:]


def encode_text(src: str, dst: str, vocab_size: int = 32768) -> int:
    """Whitespace-hash tokenizer → token file (smoke-run tooling, not a
    real tokenizer). Returns the token count."""
    import hashlib
    ids = []
    with open(os.path.expanduser(src), encoding='utf-8') as f:
        for line in f:
            for word in line.split():
                h = int.from_bytes(
                    hashlib.blake2b(word.encode(),
                                    digest_size=4).digest(), 'little')
                ids.append(h % (vocab_size - 1) + 1)  # 0 reserved
            ids.append(0)  # newline separator
    arr = np.asarray(ids, dtype=np.uint16 if vocab_size <= 65536
                     else np.uint32)
    dst = os.path.expanduser(dst)
    arr.tofile(dst)
    with open(f'{os.path.splitext(dst)[0]}.json', 'w',
              encoding='utf-8') as f:
        json.dump({'dtype': str(arr.dtype), 'vocab_size': vocab_size}, f)
    return arr.size


def main() -> None:
    import argparse
    parser = argparse.ArgumentParser(description='token file tooling')
    sub = parser.add_subparsers(dest='cmd', required=True)
    enc = sub.add_parser('encode', help='text → token file')
    enc.add_argument('src')
    enc.add_argument('dst')
    enc.add_argument('--vocab-size', type=int, default=32768)
    args = parser.parse_args()
    if args.cmd == 'encode':
        n = encode_text(args.src, args.dst, args.vocab_size)
        print(f'[data] wrote {n} tokens to {args.dst}')


if __name__ == '__main__':
    main()
