"""Model checkpoint save/restore — the spot-TPU resume story.

The reference's recovery recipes checkpoint to a Storage MOUNT and resume
from the latest step after preemption (``llm/llama-3_1-finetuning/lora.yaml``
mounts ``/output``; SURVEY §5.4). Here the train loop checkpoints the full
``TrainState`` (params + Adam moments + step) with orbax into a directory —
typically a mounted bucket path — and ``restore_latest`` picks up where the
preempted run stopped.

TPU-first details:
* orbax OCDBT + zarr3: sharded async-friendly writes, no host gather — on a
  multi-host slice every host writes its own param shards (orbax handles
  the cross-host coordination through jax.distributed).
* ``keep`` bounds retained checkpoints so a mounted bucket doesn't grow
  unboundedly; retention runs at save time.
* Restore takes an ``abstract_state`` (from ``jax.eval_shape`` over the
  init fn) so the restored arrays land directly with the right sharding —
  params never materialize unsharded.
"""
import os
import re
from typing import Any, Optional

import jax

STEP_PREFIX = 'step_'


def _ckpt_dir(root: str, step: int) -> str:
    return os.path.join(root, f'{STEP_PREFIX}{step}')


def list_steps(root: str) -> list:
    """Completed checkpoint steps under root, ascending."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        m = re.fullmatch(f'{STEP_PREFIX}(\\d+)', name)
        if m and os.path.isdir(os.path.join(root, name)):
            # orbax writes a commit marker; an interrupted save leaves a
            # tmp dir that must not be resumed from.
            if _is_complete(os.path.join(root, name)):
                steps.append(int(m.group(1)))
    return sorted(steps)


def _is_complete(path: str) -> bool:
    try:
        entries = os.listdir(path)
    except OSError:
        return False
    return not any(e.endswith('.orbax-checkpoint-tmp') or
                   e == 'tmp' for e in entries) and bool(entries)


def save(root: str, state: Any, step: int, keep: int = 3) -> str:
    """Write state at `step` under root; prune to the newest `keep`."""
    import orbax.checkpoint as ocp
    path = _ckpt_dir(os.path.abspath(os.path.expanduser(root)), step)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    _prune(os.path.abspath(os.path.expanduser(root)), keep)
    return path


def _prune(root: str, keep: int) -> None:
    import shutil
    steps = list_steps(root)
    for step in steps[:-keep] if keep > 0 else []:
        shutil.rmtree(_ckpt_dir(root, step), ignore_errors=True)


def restore(root: str, step: int, abstract_state: Any) -> Any:
    """Restore the state saved at `step` (shapes/shardings from
    abstract_state, e.g. jax.eval_shape of the init fn)."""
    import orbax.checkpoint as ocp
    path = _ckpt_dir(os.path.abspath(os.path.expanduser(root)), step)
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(path, abstract_state)
    finally:
        ckptr.close()


def restore_latest(root: str,
                   abstract_state: Any) -> Optional[tuple]:
    """(state, step) from the newest complete checkpoint, or None."""
    steps = list_steps(os.path.abspath(os.path.expanduser(root)))
    if not steps:
        return None
    step = steps[-1]
    return restore(root, step, abstract_state), step


def abstract_train_state(key: jax.Array, model_cfg, train_cfg,
                         mesh=None) -> Any:
    """ShapeDtypeStruct pytree matching ``train.init_train_state`` output,
    with shardings attached when a mesh is given."""
    from skypilot_tpu.models import llama, train
    from skypilot_tpu.parallel import mesh as mesh_lib

    def _init(k):
        return train.init_train_state(k, model_cfg, train_cfg)

    abstract = jax.eval_shape(_init, key)
    if mesh is None:
        return abstract
    specs = llama.param_partition_specs(model_cfg)
    param_shardings = mesh_lib.spec_to_sharding(mesh, specs)
    opt_shardings = train._opt_state_shardings(  # pylint: disable=protected-access
        abstract.opt_state, param_shardings, mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    def attach(x, sh):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

    params = jax.tree.map(attach, abstract.params, param_shardings)
    opt_state = jax.tree.map(attach, abstract.opt_state, opt_shardings)
    step = jax.ShapeDtypeStruct(abstract.step.shape, abstract.step.dtype,
                                sharding=NamedSharding(mesh, P()))
    return train.TrainState(params=params, opt_state=opt_state, step=step)
