"""Batched autoregressive inference: prefill + KV-cache decode.

The serving-side compute path (the reference serves LLMs via vLLM examples,
``llm/vllm/service.yaml``; the TPU-native analogue is a JetStream-style
static-shape engine):

* KV cache preallocated at [L, B, max_len, Hkv, hd] — static shapes, so
  one compiled decode step serves every position (XLA requirement).
* Prefill runs the full forward once (flash/ring attention applies),
  writing the cache; decode is a ``lax.scan`` of single-token steps whose
  attention reads the cache with a position mask (no recompilation, MXU
  does [B,1,d]x[d,*] matmuls batched over the whole batch).
* Greedy or temperature sampling; generation stops per-sequence on EOS
  via a done mask (static loop length, masked writes).
"""
import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.ops import attention as attention_ops

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    max_len: int = 2048
    temperature: float = 0.0          # 0 = greedy
    eos_id: Optional[int] = None


def quantize_params(params: Params) -> Params:
    """Int8-quantize the per-layer GEMM weights (FFN + attention
    projections) for serving. Layer weights are stacked [L, in, out]: the
    contraction axis is 1, so scales are per (layer, output-channel). The
    quantized tensors flow through scan/jit as pytrees (ops/quant.py).
    Embedding/lm_head and the KV cache stay bf16."""
    from skypilot_tpu.ops import quant
    out = dict(params)
    layers = dict(params['layers'])
    for name in ('w1', 'w3', 'w2', 'wq', 'wk', 'wv', 'wo'):
        layers[name] = quant.quantize_int8(layers[name], axis=1)
    out['layers'] = layers
    return out


def init_kv_cache(cfg: llama.LlamaConfig, batch: int,
                  max_len: int) -> Dict[str, jax.Array]:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        'k': jnp.zeros(shape, cfg.dtype),
        'v': jnp.zeros(shape, cfg.dtype),
    }


def _attend_cached(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                   cur_len: jax.Array) -> jax.Array:
    """q [B,1,H,hd] against cache [B,max_len,Hkv,hd]; positions >= cur_len
    masked out."""
    b, _, h, hd = q.shape
    hkv = k_cache.shape[2]
    k = attention_ops.repeat_kv(k_cache, h // hkv)
    v = attention_ops.repeat_kv(v_cache, h // hkv)
    scale = hd**-0.5
    logits = jnp.einsum('bshd,bthd->bhst', q, k,
                        preferred_element_type=jnp.float32) * scale
    kv_pos = jnp.arange(k.shape[1])
    mask = kv_pos[None, :] < cur_len[:, None]          # [B, max_len]
    logits = jnp.where(mask[:, None, None, :], logits,
                       attention_ops.NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum('bhst,bthd->bshd', probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _block_decode(cfg: llama.LlamaConfig, x: jax.Array, layer: Params,
                  k_cache: jax.Array, v_cache: jax.Array,
                  cos: jax.Array, sin: jax.Array, pos: jax.Array
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder block for one new token; returns (x, k_new, v_new)."""
    b, s, _ = x.shape  # s == 1
    hd = cfg.head_dim
    h = llama.rms_norm(x, layer['attn_norm'], cfg.norm_eps)
    q = llama.quant_mm(h, layer['wq']).reshape(b, s, cfg.n_heads, hd)
    k = llama.quant_mm(h, layer['wk']).reshape(b, s,
                                               cfg.n_kv_heads, hd)
    v = llama.quant_mm(h, layer['wv']).reshape(b, s,
                                               cfg.n_kv_heads, hd)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    # Insert this step's K/V at each sequence's current position.
    b_idx = jnp.arange(b)
    k_cache = k_cache.at[b_idx, pos].set(k[:, 0])
    v_cache = v_cache.at[b_idx, pos].set(v[:, 0])
    attn = _attend_cached(q, k_cache, v_cache, cur_len=pos + 1)
    attn = attn.reshape(b, s, cfg.n_heads * hd)
    x = x + llama.quant_mm(attn, layer['wo']).astype(cfg.dtype)
    return llama.ffn_sublayer(cfg, x, layer), k_cache, v_cache


def prefill(params: Params, tokens: jax.Array, cfg: llama.LlamaConfig,
            cache: Dict[str, jax.Array], prompt_lens: jax.Array
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Run the prompt through the model, filling the cache.

    tokens [B, S_prompt] (right-padded); returns (logits at each
    sequence's last prompt token [B, vocab], cache).
    """
    _, s = tokens.shape
    positions = jnp.arange(s, dtype=jnp.int32)
    cos, sin = llama._rope_freqs(cfg, positions)  # pylint: disable=protected-access
    x = params['tok_embedding'][tokens].astype(cfg.dtype)

    def body(carry, layer):
        # Shared sublayers with training (flash attention flag honored —
        # prefill is exactly where the [S,S] logits would hurt most);
        # attn_sublayer hands back K/V to seed the cache.
        xc, k, v = llama.attn_sublayer(cfg, carry, layer, cos, sin)
        return llama.ffn_sublayer(cfg, xc, layer), (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params['layers'])
    # ks/vs: [L, B, S, Hkv, hd] → cache prefix.
    cache = {
        'k': cache['k'].at[:, :, :s].set(ks),
        'v': cache['v'].at[:, :, :s].set(vs),
    }
    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x @ params['lm_head']).astype(jnp.float32)  # [B, S, V]
    last = jnp.take_along_axis(
        logits, (prompt_lens - 1)[:, None, None], axis=1)[:, 0]
    return last, cache


def decode_step(params: Params, token: jax.Array, pos: jax.Array,
                cfg: llama.LlamaConfig, cache: Dict[str, jax.Array]
                ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """token [B] at positions pos [B] → (logits [B, vocab], cache)."""
    b = token.shape[0]
    cos, sin = llama._rope_freqs(cfg, pos[:, None])  # pylint: disable=protected-access
    x = params['tok_embedding'][token][:, None].astype(cfg.dtype)

    def body(carry, layer_kv):
        xc = carry
        layer, k_cache, v_cache = layer_kv
        xc, k_new, v_new = _block_decode(cfg, xc, layer, k_cache, v_cache,
                                         cos, sin, pos)
        return xc, (k_new, v_new)

    x, (ks, vs) = jax.lax.scan(body, x,
                               (params['layers'], cache['k'], cache['v']))
    cache = {'k': ks, 'v': vs}
    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x[:, 0] @ params['lm_head']).astype(jnp.float32)
    return logits, cache


def _sample(logits: jax.Array, key: jax.Array,
            temperature: float) -> jax.Array:
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature,
                                  axis=-1).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'dcfg', 'max_new_tokens'))
def generate(params: Params,
             prompt: jax.Array,
             prompt_lens: jax.Array,
             cfg: llama.LlamaConfig,
             dcfg: DecodeConfig,
             max_new_tokens: int,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """prompt [B, S_prompt] right-padded → generated tokens
    [B, max_new_tokens] (post-EOS positions hold eos_id)."""
    b, s_prompt = prompt.shape
    assert s_prompt + max_new_tokens <= dcfg.max_len
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    first_key, steps_key = jax.random.split(rng)
    cache = init_kv_cache(cfg, b, dcfg.max_len)
    last_logits, cache = prefill(params, prompt, cfg, cache, prompt_lens)

    first = _sample(last_logits, first_key, dcfg.temperature)
    done0 = (jnp.full((b,), False) if dcfg.eos_id is None else
             first == dcfg.eos_id)

    def step(carry, key):
        token, pos, cache_c, done = carry
        logits, cache_c = decode_step(params, token, pos, cfg, cache_c)
        nxt = _sample(logits, key, dcfg.temperature)
        if dcfg.eos_id is not None:
            nxt = jnp.where(done, dcfg.eos_id, nxt)
            done = done | (nxt == dcfg.eos_id)
        return (nxt, pos + 1, cache_c, done), nxt

    keys = jax.random.split(steps_key, max_new_tokens - 1) \
        if max_new_tokens > 1 else jnp.zeros((0, 2), jnp.uint32)
    (_, _, _, _), rest = jax.lax.scan(
        step, (first, prompt_lens, cache, done0), keys)
    return jnp.concatenate([first[:, None], rest.T], axis=1)
