"""Batched autoregressive inference: prefill + KV-cache decode.

The serving-side compute path (the reference serves LLMs via vLLM examples,
``llm/vllm/service.yaml``; the TPU-native analogue is a JetStream-style
static-shape engine):

* KV cache preallocated at [L, B, max_len, Hkv, hd] — static shapes, so
  one compiled decode step serves every position (XLA requirement).
  Optionally int8 (``kv_cache_dtype='int8'``) with per-(position, kv-head)
  fp32 scales, halving the cache HBM traffic that bounds decode.
* PAGED alternative (the serving engine's cache): one global block pool
  [L, n_blocks, block_k, Hkv, hd] (``init_block_pool``) plus per-sequence
  int32 block tables — position ``p`` of a sequence lives in pool block
  ``table[p // block_k]`` at offset ``p % block_k``. Prefill
  (``paged_prefill`` / ``paged_prefill_with_prefix``) and the per-step
  ``_write_kv`` scatter through the table; attention gathers through it
  (``ops/decode_attention.paged_decode_attention``). HBM then scales
  with *live tokens*, not ``num_slots × max_len``, and sequences whose
  tables name the same blocks share prompt prefixes copy-free
  (``models/engine.py``'s radix prefix cache). The with-prefix prefill
  additionally SKIPS the forward pass over an already-cached prefix:
  only the suffix runs through the model, attending over prefix K/V
  gathered from the pool.
* Prefill runs the full forward once (flash/ring attention applies),
  writing the cache; decode is a ``lax.scan`` of single-token steps whose
  attention reads the cache through the Pallas flash-decode kernel
  (``ops/decode_attention.py``: online softmax over cache blocks, GQA
  in-kernel, dead blocks past each row's cur_len never read) or a
  grouped-einsum XLA fallback (``decode_attention='xla'``, and
  automatically off-TPU).
* The public ``decode_step``/``generate`` entry points donate the cache
  (``donate_argnums``), so XLA updates the [L,B,max_len,Hkv,hd] carry in
  place instead of allocating + copying it each call.
* Greedy or temperature sampling; generation stops per-sequence on EOS
  via a done mask (static loop length, masked writes).
"""
import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.ops import decode_attention as decode_attention_ops

Params = Dict[str, Any]
# Cache pytree: {'k', 'v'} [L,B,max_len,Hkv,hd] (+ {'k_scale','v_scale'}
# [L,B,max_len,Hkv] fp32 when the cache is int8).
Cache = Dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    max_len: int = 2048
    temperature: float = 0.0          # 0 = greedy
    eos_id: Optional[int] = None
    # Cached-attention implementation: 'kernel' = Pallas flash-decode
    # (TPU; falls back to the XLA path off-TPU), 'xla' = grouped einsum.
    decode_attention: str = 'kernel'
    # KV cache storage: 'bf16' (model dtype) or 'int8' (+ fp32 scales).
    kv_cache_dtype: str = 'bf16'
    # KV block streamed per kernel grid step (block skipping granularity).
    kernel_block_k: int = decode_attention_ops.DEFAULT_BLOCK_K
    # None: auto (compiled on TPU, XLA fallback elsewhere); True forces
    # the Pallas interpreter (CPU numerics tests).
    kernel_interpret: Optional[bool] = None
    # Speculative decoding (paged engine only, greedy): a truncated-layer
    # drafter proposes spec_k tokens per engine step and one batched
    # multi-token verify scores them against the full model. 0 disables.
    spec_k: int = 0
    # The drafter is the served model's first N decoder layers plus its
    # (tied) out-norm + lm_head — no second set of weights, and its
    # attention reads the SAME pool blocks the full model wrote, so the
    # only extra state is a spec_k-deep local K/V buffer per draft round.
    spec_drafter_layers: int = 1


def quantize_params(params: Params) -> Params:
    """Int8-quantize the per-layer GEMM weights (FFN + attention
    projections) for serving. Layer weights are stacked [L, in, out]: the
    contraction axis is 1, so scales are per (layer, output-channel). The
    quantized tensors flow through scan/jit as pytrees (ops/quant.py).
    Embedding/lm_head stay bf16; the KV cache quantizes separately via
    ``DecodeConfig.kv_cache_dtype``."""
    from skypilot_tpu.ops import quant
    out = dict(params)
    layers = dict(params['layers'])
    for name in ('w1', 'w3', 'w2', 'wq', 'wk', 'wv', 'wo'):
        layers[name] = quant.quantize_int8(layers[name], axis=1)
    out['layers'] = layers
    return out


def init_kv_cache(cfg: llama.LlamaConfig, batch: int, max_len: int,
                  kv_cache_dtype: str = 'bf16') -> Cache:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    if kv_cache_dtype == 'int8':
        return {
            'k': jnp.zeros(shape, jnp.int8),
            'v': jnp.zeros(shape, jnp.int8),
            'k_scale': jnp.zeros(shape[:-1], jnp.float32),
            'v_scale': jnp.zeros(shape[:-1], jnp.float32),
        }
    assert kv_cache_dtype == 'bf16', kv_cache_dtype
    return {
        'k': jnp.zeros(shape, cfg.dtype),
        'v': jnp.zeros(shape, cfg.dtype),
    }


def init_block_pool(cfg: llama.LlamaConfig, num_blocks: int, block_k: int,
                    kv_cache_dtype: str = 'bf16') -> Cache:
    """Global paged KV pool: [L, num_blocks, block_k, Hkv, hd] (+ scale
    planes [L, num_blocks, block_k, Hkv] when int8). Block 0 is reserved
    by the engine as a write-off scratch block (frozen lanes and bucket
    padding scatter there), so usable capacity is ``num_blocks - 1``
    blocks."""
    shape = (cfg.n_layers, num_blocks, block_k, cfg.n_kv_heads,
             cfg.head_dim)
    if kv_cache_dtype == 'int8':
        return {
            'k': jnp.zeros(shape, jnp.int8),
            'v': jnp.zeros(shape, jnp.int8),
            'k_scale': jnp.zeros(shape[:-1], jnp.float32),
            'v_scale': jnp.zeros(shape[:-1], jnp.float32),
        }
    assert kv_cache_dtype == 'bf16', kv_cache_dtype
    return {
        'k': jnp.zeros(shape, cfg.dtype),
        'v': jnp.zeros(shape, cfg.dtype),
    }


def _attend_cached(dcfg: DecodeConfig, q: jax.Array, lcache: Cache,
                   cur_len: jax.Array) -> jax.Array:
    """q [B,1,H,hd] against one layer's cache [B,max_len,Hkv,hd];
    positions >= cur_len masked out (kernel path: never even read)."""
    k_scale = lcache.get('k_scale')
    v_scale = lcache.get('v_scale')
    if dcfg.decode_attention == 'kernel':
        return decode_attention_ops.decode_attention(
            q, lcache['k'], lcache['v'], cur_len,
            k_scale=k_scale, v_scale=v_scale,
            block_k=dcfg.kernel_block_k,
            interpret=dcfg.kernel_interpret)
    assert dcfg.decode_attention == 'xla', dcfg.decode_attention
    return decode_attention_ops.decode_attention_xla(
        q, lcache['k'], lcache['v'], cur_len,
        k_scale=k_scale, v_scale=v_scale)


def _write_kv(cache: Cache, idx, k: jax.Array, v: jax.Array) -> Cache:
    """Write K/V into ``cache[...]`` at index tuple ``idx``, quantizing
    on the way in when the cache is int8 — the single copy of the
    write-side scheme, shared by prefill (whole [L,B,S,...] prefix) and
    decode ([b_idx, pos] scatter)."""
    cache = dict(cache)
    if 'k_scale' in cache:
        from skypilot_tpu.ops import quant
        kq, ks = quant.quantize_kv(k)
        vq, vs = quant.quantize_kv(v)
        cache['k'] = cache['k'].at[idx].set(kq)
        cache['v'] = cache['v'].at[idx].set(vq)
        cache['k_scale'] = cache['k_scale'].at[idx].set(ks)
        cache['v_scale'] = cache['v_scale'].at[idx].set(vs)
    else:
        cache['k'] = cache['k'].at[idx].set(k.astype(cache['k'].dtype))
        cache['v'] = cache['v'].at[idx].set(v.astype(cache['v'].dtype))
    return cache


def _block_decode(cfg: llama.LlamaConfig, dcfg: DecodeConfig, x: jax.Array,
                  layer: Params, lcache: Cache, cos: jax.Array,
                  sin: jax.Array, pos: jax.Array
                  ) -> Tuple[jax.Array, Cache]:
    """One decoder block for one new token; returns (x, updated cache)."""
    b, s, _ = x.shape  # s == 1
    hd = cfg.head_dim
    h = llama.rms_norm(x, layer['attn_norm'], cfg.norm_eps)
    q = llama.quant_mm(h, layer['wq']).reshape(b, s, cfg.n_heads, hd)
    k = llama.quant_mm(h, layer['wk']).reshape(b, s,
                                               cfg.n_kv_heads, hd)
    v = llama.quant_mm(h, layer['wv']).reshape(b, s,
                                               cfg.n_kv_heads, hd)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    lcache = _write_kv(lcache, (jnp.arange(b), pos), k[:, 0], v[:, 0])
    attn = _attend_cached(dcfg, q, lcache, cur_len=pos + 1)
    attn = attn.reshape(b, s, cfg.n_heads * hd)
    x = x + llama.quant_mm(attn, layer['wo']).astype(cfg.dtype)
    return llama.ffn_sublayer(cfg, x, layer), lcache


def _prefill_forward(params: Params, tokens: jax.Array,
                     cfg: llama.LlamaConfig
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The prompt forward pass shared by batch prefill and the engine's
    slot-targeted prefill: tokens [B, S] → (logits [B, S, V],
    ks, vs [L, B, S, Hkv, hd]). Causal, so each row's activations depend
    only on its own prefix — identical whether a prompt runs here in a
    [B, S] batch or alone in a [1, S_bucket] bucket (what makes the
    continuous engine's per-request prefill token-equivalent to static
    batched prefill)."""
    _, s = tokens.shape
    positions = jnp.arange(s, dtype=jnp.int32)
    cos, sin = llama._rope_freqs(cfg, positions)  # pylint: disable=protected-access
    x = params['tok_embedding'][tokens].astype(cfg.dtype)

    def body(carry, layer):
        # Shared sublayers with training (flash attention flag honored —
        # prefill is exactly where the [S,S] logits would hurt most);
        # attn_sublayer hands back K/V to seed the cache.
        xc, k, v = llama.attn_sublayer(cfg, carry, layer, cos, sin)
        return llama.ffn_sublayer(cfg, xc, layer), (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params['layers'])
    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x @ params['lm_head']).astype(jnp.float32)  # [B, S, V]
    return logits, ks, vs


def prefill(params: Params, tokens: jax.Array, cfg: llama.LlamaConfig,
            cache: Cache, prompt_lens: jax.Array
            ) -> Tuple[jax.Array, Cache]:
    """Run the prompt through the model, filling the cache.

    tokens [B, S_prompt] (right-padded); returns (logits at each
    sequence's last prompt token [B, vocab], cache). An int8 cache
    (extra scale entries in the pytree) quantizes the K/V prefix at
    write time.
    """
    _, s = tokens.shape
    logits, ks, vs = _prefill_forward(params, tokens, cfg)
    # ks/vs: [L, B, S, Hkv, hd] → cache prefix.
    cache = _write_kv(cache, jnp.index_exp[:, :, :s], ks, vs)
    last = jnp.take_along_axis(
        logits, (prompt_lens - 1)[:, None, None], axis=1)[:, 0]
    return last, cache


def _prefill_into_slot(params: Params, tokens: jax.Array,
                       prompt_len: jax.Array, slot: jax.Array,
                       cfg: llama.LlamaConfig, cache: Cache
                       ) -> Tuple[jax.Array, Cache]:
    """Prefill ONE request into ONE lane of a multi-slot cache.

    tokens [1, S_bucket] right-padded, prompt_len/slot scalar int32;
    cache [L, num_slots, max_len, Hkv, hd]. The K/V prefix scatters into
    lane ``slot`` positions [0, S_bucket) (int8 caches quantize on the
    way in via ``_write_kv``); every other lane's entries are untouched,
    so the continuous engine can refill a freed slot while its neighbors
    are mid-decode. Returns (last-prompt-token logits [vocab], cache).
    """
    _, s = tokens.shape
    logits, ks, vs = _prefill_forward(params, tokens, cfg)
    # Lane scatter: value [L, S, Hkv, hd] lands at [:, slot, :s].
    cache = _write_kv(cache, jnp.index_exp[:, slot, :s],
                      ks[:, 0], vs[:, 0])
    return logits[0, prompt_len - 1], cache


# Engine-serving entry point (models/engine.py): the multi-slot cache is
# DONATED — prefilling a slot updates the persistent cache buffers in
# place; callers must rebind to the returned cache. One compile per
# prompt bucket length.
prefill_into_slot = jax.jit(_prefill_into_slot, static_argnames=('cfg',),
                            donate_argnums=(5,))


# ------------------------------------------------------------------ paged


def _attend_paged(dcfg: DecodeConfig, q: jax.Array, lpool: Cache,
                  block_tables: jax.Array, cur_len: jax.Array,
                  mesh=None) -> jax.Array:
    """q [B,1,H,hd] against one layer's pool [n_blocks, block_k, Hkv, hd]
    through ``block_tables`` [B, max_blocks]. A tensor-parallel
    ``mesh`` selects the shard_map kernel dispatch (the XLA fallback
    partitions under plain GSPMD and ignores it)."""
    k_scale = lpool.get('k_scale')
    v_scale = lpool.get('v_scale')
    if dcfg.decode_attention == 'kernel':
        return decode_attention_ops.paged_decode_attention(
            q, lpool['k'], lpool['v'], block_tables, cur_len,
            k_scale=k_scale, v_scale=v_scale,
            interpret=dcfg.kernel_interpret, mesh=mesh)
    assert dcfg.decode_attention == 'xla', dcfg.decode_attention
    return decode_attention_ops.paged_decode_attention_xla(
        q, lpool['k'], lpool['v'], block_tables, cur_len,
        k_scale=k_scale, v_scale=v_scale)


def _paged_block_decode(cfg: llama.LlamaConfig, dcfg: DecodeConfig,
                        x: jax.Array, layer: Params, lpool: Cache,
                        cos: jax.Array, sin: jax.Array, pos: jax.Array,
                        block_tables: jax.Array, mesh=None
                        ) -> Tuple[jax.Array, Cache]:
    """One decoder block for one new token per sequence, paged cache:
    the K/V write scatters to (table[pos // block_k], pos % block_k)."""
    b, s, _ = x.shape  # s == 1
    hd = cfg.head_dim
    block_k = lpool['k'].shape[1]
    h = llama.rms_norm(x, layer['attn_norm'], cfg.norm_eps)
    q = llama.quant_mm(h, layer['wq']).reshape(b, s, cfg.n_heads, hd)
    k = llama.quant_mm(h, layer['wk']).reshape(b, s, cfg.n_kv_heads, hd)
    v = llama.quant_mm(h, layer['wv']).reshape(b, s, cfg.n_kv_heads, hd)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    blk = jnp.take_along_axis(block_tables,
                              (pos // block_k)[:, None], axis=1)[:, 0]
    lpool = _write_kv(lpool, (blk, pos % block_k), k[:, 0], v[:, 0])
    attn = _attend_paged(dcfg, q, lpool, block_tables, cur_len=pos + 1,
                         mesh=mesh)
    attn = attn.reshape(b, s, cfg.n_heads * hd)
    x = x + llama.quant_mm(attn, layer['wo']).astype(cfg.dtype)
    return llama.ffn_sublayer(cfg, x, layer), lpool


def _paged_decode_step(params: Params, token: jax.Array, pos: jax.Array,
                       block_tables: jax.Array, cfg: llama.LlamaConfig,
                       dcfg: DecodeConfig, pool: Cache, mesh=None
                       ) -> Tuple[jax.Array, Cache]:
    """token [B] at positions pos [B], tables [B, max_blocks] →
    (logits [B, vocab], pool)."""
    cos, sin = llama._rope_freqs(cfg, pos[:, None])  # pylint: disable=protected-access
    x = params['tok_embedding'][token][:, None].astype(cfg.dtype)

    def body(carry, layer_lpool):
        layer, lpool = layer_lpool
        xc, lpool = _paged_block_decode(cfg, dcfg, carry, layer, lpool,
                                        cos, sin, pos, block_tables,
                                        mesh=mesh)
        return xc, lpool

    x, pool = jax.lax.scan(body, x, (params['layers'], pool))
    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x[:, 0] @ params['lm_head']).astype(jnp.float32)
    return logits, pool


def _paged_prefill(params: Params, tokens: jax.Array,
                   prompt_len: jax.Array, block_row: jax.Array,
                   cfg: llama.LlamaConfig, pool: Cache
                   ) -> Tuple[jax.Array, Cache]:
    """Prefill ONE request into pool blocks named by ``block_row``.

    tokens [1, S_bucket] right-padded (S_bucket % block_k == 0),
    block_row [S_bucket // block_k] int32; positions [j*block_k,
    (j+1)*block_k) land in pool block ``block_row[j]``. Bucket-padding
    positions write garbage into whatever block covers them — the engine
    points table rows past the allocation at the scratch block, and
    attention masks by cur_len, so it is never read. Returns
    (last-prompt-token logits [vocab], pool).
    """
    _, s = tokens.shape
    block_k = pool['k'].shape[2]
    nb = s // block_k
    logits, ks, vs = _prefill_forward(params, tokens, cfg)
    # ks/vs [L, 1, S, Hkv, hd] → [L, nb, block_k, Hkv, hd] block scatter.
    shp = (ks.shape[0], nb, block_k) + ks.shape[3:]
    pool = _write_kv(pool, jnp.index_exp[:, block_row],
                     ks[:, 0].reshape(shp), vs[:, 0].reshape(shp))
    return logits[0, prompt_len - 1], pool


def _prefix_suffix_attention(q: jax.Array, pk: jax.Array, pv: jax.Array,
                             sk: jax.Array, sv: jax.Array,
                             prefix_len: jax.Array) -> jax.Array:
    """Suffix-prefill attention: suffix queries attend the gathered
    prefix K/V (positions < prefix_len) plus the suffix itself
    (causal). q/sk/sv [1, S, ...], pk/pv [1, P_buf, Hkv, hd]; grouped
    GQA einsum, no repeat_kv materialization."""
    _, s, h, hd = q.shape
    p_buf = pk.shape[1]
    hkv = pk.shape[2]
    g = h // hkv
    k = jnp.concatenate([pk, sk], axis=1)
    v = jnp.concatenate([pv, sv], axis=1)
    qg = q.reshape(1, s, hkv, g, hd)
    logits = jnp.einsum('bskgd,btkd->bkgst', qg, k,
                        preferred_element_type=jnp.float32) * hd**-0.5
    t_idx = jnp.arange(p_buf + s)
    j_idx = jnp.arange(s)
    # [S, P_buf + S]: prefix entries gate on prefix_len, suffix causal.
    mask = jnp.where(t_idx[None, :] < p_buf,
                     t_idx[None, :] < prefix_len,
                     (t_idx[None, :] - p_buf) <= j_idx[:, None])
    logits = jnp.where(mask[None, None, None, :, :], logits,
                       decode_attention_ops.NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(1, s, h, hd).astype(q.dtype)


def _gather_prefix_kv(pool: Cache, prefix_blocks: jax.Array,
                      dtype) -> Tuple[jax.Array, jax.Array]:
    """Pool → contiguous prefix K/V [L, Npb*block_k, Hkv, hd] (int8
    pools dequantize here; the suffix forward runs in model dtype)."""
    block_k = pool['k'].shape[2]
    npb = prefix_blocks.shape[0]

    def flat(x):
        g = x[:, prefix_blocks]          # [L, Npb, block_k, ...]
        return g.reshape((x.shape[0], npb * block_k) + x.shape[3:])

    pk, pv = flat(pool['k']), flat(pool['v'])
    if 'k_scale' in pool:
        pk = pk.astype(jnp.float32) * flat(pool['k_scale'])[..., None]
        pv = pv.astype(jnp.float32) * flat(pool['v_scale'])[..., None]
    return pk.astype(dtype), pv.astype(dtype)


def _paged_prefill_with_prefix(params: Params, tokens: jax.Array,
                               suffix_len: jax.Array,
                               prefix_len: jax.Array,
                               prefix_blocks: jax.Array,
                               block_row: jax.Array,
                               cfg: llama.LlamaConfig, pool: Cache
                               ) -> Tuple[jax.Array, Cache]:
    """Prefix-skipping prefill: run ONLY the prompt suffix through the
    model, attending over prefix K/V already resident in the pool.

    tokens [1, S_bucket] holds the suffix (prompt[prefix_len:]) right-
    padded; ``prefix_blocks`` [Npb] names the pool blocks covering
    positions [0, prefix_len) (padded entries masked by ``prefix_len``);
    ``block_row`` [S_bucket // block_k + 1] names the blocks receiving
    the suffix K/V writes, starting at the block containing position
    ``prefix_len`` (offset ``prefix_len % block_k`` — the engine hands a
    copy-on-write clone there when that block is shared). This is where
    prefix reuse saves compute: the forward pass (QKV, FFN, lm_head) runs
    over S_bucket tokens instead of prefix_len + S_bucket. Returns
    (last-suffix-token logits [vocab], pool).
    """
    _, s = tokens.shape
    block_k = pool['k'].shape[2]
    positions = prefix_len + jnp.arange(s, dtype=jnp.int32)
    cos, sin = llama._rope_freqs(cfg, positions)  # pylint: disable=protected-access
    x = params['tok_embedding'][tokens].astype(cfg.dtype)
    pk, pv = _gather_prefix_kv(pool, prefix_blocks, cfg.dtype)

    def body(carry, layer_pkv):
        layer, lpk, lpv = layer_pkv
        b, sl, _ = carry.shape
        hd = cfg.head_dim
        h = llama.rms_norm(carry, layer['attn_norm'], cfg.norm_eps)
        q = llama.quant_mm(h, layer['wq']).reshape(b, sl, cfg.n_heads, hd)
        k = llama.quant_mm(h, layer['wk']).reshape(b, sl,
                                                   cfg.n_kv_heads, hd)
        v = llama.quant_mm(h, layer['wv']).reshape(b, sl,
                                                   cfg.n_kv_heads, hd)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        attn = _prefix_suffix_attention(q, lpk[None], lpv[None], k, v,
                                        prefix_len)
        attn = attn.reshape(b, sl, cfg.n_heads * hd)
        xc = carry + llama.quant_mm(attn, layer['wo']).astype(cfg.dtype)
        return llama.ffn_sublayer(cfg, xc, layer), (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, (params['layers'], pk, pv))
    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x @ params['lm_head']).astype(jnp.float32)  # [1, S, V]
    # Scatter suffix K/V: token i sits at global position prefix_len + i
    # → write slot (block_row[(off0 + i) // block_k], (off0 + i) %
    # block_k) with off0 = prefix_len % block_k. Bucket padding spills
    # into scratch-pointed rows exactly like _paged_prefill.
    g = (prefix_len % block_k) + jnp.arange(s, dtype=jnp.int32)
    blk = block_row[g // block_k]
    pool = _write_kv(pool, jnp.index_exp[:, blk, g % block_k],
                     ks[:, 0], vs[:, 0])
    return logits[0, suffix_len - 1], pool


# ------------------------------------------------- speculative decoding


def _gather_layer_kv(lpool: Cache, block_tables: jax.Array,
                     dtype) -> Tuple[jax.Array, jax.Array]:
    """One layer's pool → per-sequence contiguous K/V views
    [B, max_blocks*block_k, Hkv, hd] through the tables (int8 pools
    dequantize here). The drafter's attention history.

    Cost note: this materializes the TABLE-WIDTH view (once per
    drafter layer per spec round), the same O(B × max_len) traffic
    every paged XLA-fallback attention call already pays — fine on the
    CPU tier and for shallow drafters, but on TPU at long max_len the
    drafter should instead stream live blocks through the paged kernel
    and merge the spec_k-deep local buffer via an online-softmax
    combine (needs the kernel to expose its m/l state; ROADMAP
    follow-up)."""
    k, v, ks, vs = decode_attention_ops.gather_paged_kv(
        lpool['k'], lpool['v'], block_tables,
        lpool.get('k_scale'), lpool.get('v_scale'))
    if ks is not None:
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
    return k.astype(dtype), v.astype(dtype)


def _draft_attention(q: jax.Array, hist_k: jax.Array, hist_v: jax.Array,
                     hist_len: jax.Array, buf_k: jax.Array,
                     buf_v: jax.Array, n_filled: int) -> jax.Array:
    """Drafter attention: q [B,1,H,hd] against the pool-gathered history
    (positions < hist_len, per row) PLUS the drafted-this-round local
    K/V buffer ([B, spec_k, Hkv, hd], entries < ``n_filled`` live —
    the current draft token's own K/V included). One joint softmax, so
    no pool writes happen during drafting and a rejected tail needs no
    rollback at all in the drafter layers."""
    b, s, h, hd = q.shape
    t_hist = hist_k.shape[1]
    hkv = hist_k.shape[2]
    g = h // hkv
    k = jnp.concatenate([hist_k, buf_k], axis=1)
    v = jnp.concatenate([hist_v, buf_v], axis=1)
    qg = q.reshape(b, s, hkv, g, hd)
    logits = jnp.einsum('bskgd,btkd->bkgst', qg, k,
                        preferred_element_type=jnp.float32) * hd**-0.5
    t_idx = jnp.arange(k.shape[1])
    # [B, T+K]: history gates on hist_len, buffer entries on fill count
    # (a static python int — the mask folds to a constant per step).
    mask = jnp.where(t_idx[None, :] < t_hist,
                     t_idx[None, :] < hist_len[:, None],
                     (t_idx[None, :] - t_hist) < n_filled)
    logits = jnp.where(mask[:, None, None, None, :], logits,
                       decode_attention_ops.NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    probs = jnp.where(mask[:, None, None, None, :], probs, 0)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s, h, hd).astype(q.dtype)


def _spec_draft_tokens(params: Params, token: jax.Array, pos: jax.Array,
                       block_tables: jax.Array, cfg: llama.LlamaConfig,
                       dcfg: DecodeConfig, pool: Cache) -> jax.Array:
    """Greedy-draft ``spec_k`` tokens per sequence with the truncated-
    layer drafter. token [B] (last emitted token, K/V not yet written),
    pos [B] its position. Returns drafts [B, spec_k]; the pool is READ
    (history gather per drafter layer) but never written — verify owns
    all cache writes, which is what makes rejection rollback purely
    positional."""
    k_spec = dcfg.spec_k
    d = dcfg.spec_drafter_layers
    assert 1 <= d <= cfg.n_layers, (d, cfg.n_layers)
    b = token.shape[0]
    hd = cfg.head_dim
    layers = [jax.tree.map(lambda a, i=i: a[i], params['layers'])
              for i in range(d)]
    hist = [_gather_layer_kv(
        jax.tree.map(lambda a, i=i: a[i], pool), block_tables, cfg.dtype)
        for i in range(d)]
    buf_k = [jnp.zeros((b, k_spec, cfg.n_kv_heads, hd), cfg.dtype)
             for _ in range(d)]
    buf_v = [jnp.zeros((b, k_spec, cfg.n_kv_heads, hd), cfg.dtype)
             for _ in range(d)]
    drafts = []
    tok = token
    for j in range(k_spec):
        positions = pos + j
        cos, sin = llama._rope_freqs(cfg, positions[:, None])  # pylint: disable=protected-access
        x = params['tok_embedding'][tok][:, None].astype(cfg.dtype)
        for li in range(d):
            layer = layers[li]
            h = llama.rms_norm(x, layer['attn_norm'], cfg.norm_eps)
            q = llama.quant_mm(h, layer['wq']).reshape(
                b, 1, cfg.n_heads, hd)
            kx = llama.quant_mm(h, layer['wk']).reshape(
                b, 1, cfg.n_kv_heads, hd)
            vx = llama.quant_mm(h, layer['wv']).reshape(
                b, 1, cfg.n_kv_heads, hd)
            q = llama.apply_rope(q, cos, sin)
            kx = llama.apply_rope(kx, cos, sin)
            buf_k[li] = buf_k[li].at[:, j].set(kx[:, 0])
            buf_v[li] = buf_v[li].at[:, j].set(vx[:, 0])
            attn = _draft_attention(q, hist[li][0], hist[li][1], pos,
                                    buf_k[li], buf_v[li], j + 1)
            attn = attn.reshape(b, 1, cfg.n_heads * hd)
            x = x + llama.quant_mm(attn, layer['wo']).astype(cfg.dtype)
            x = llama.ffn_sublayer(cfg, x, layer)
        x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
        logits = (x[:, 0] @ params['lm_head']).astype(jnp.float32)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        drafts.append(tok)
    return jnp.stack(drafts, axis=1)


def _attend_paged_verify(dcfg: DecodeConfig, q: jax.Array, lpool: Cache,
                         block_tables: jax.Array,
                         start_pos: jax.Array, mesh=None) -> jax.Array:
    """q [B,S,H,hd] against one layer's pool; query ``i`` masks by its
    own causal length ``start_pos + i + 1``."""
    k_scale = lpool.get('k_scale')
    v_scale = lpool.get('v_scale')
    if dcfg.decode_attention == 'kernel':
        return decode_attention_ops.paged_verify_attention(
            q, lpool['k'], lpool['v'], block_tables, start_pos,
            k_scale=k_scale, v_scale=v_scale,
            interpret=dcfg.kernel_interpret, mesh=mesh)
    assert dcfg.decode_attention == 'xla', dcfg.decode_attention
    return decode_attention_ops.paged_verify_attention_xla(
        q, lpool['k'], lpool['v'], block_tables, start_pos,
        k_scale=k_scale, v_scale=v_scale)


def _paged_verify_step(params: Params, tokens: jax.Array, pos: jax.Array,
                       block_tables: jax.Array, cfg: llama.LlamaConfig,
                       dcfg: DecodeConfig, pool: Cache, mesh=None
                       ) -> Tuple[jax.Array, Cache]:
    """Multi-token full-model step: score tokens [B, S] (the last
    emitted token followed by S-1 drafts) at positions pos..pos+S-1 →
    (logits [B, S, vocab], pool).

    K/V for ALL S positions scatter through the tables before attention
    (exactly the write-then-attend order of the single-token step), so
    the accepted prefix's cache entries are already correct when the
    host commits it — rejected tails are "rolled back" by simply not
    advancing ``pos`` past them: attention masks by position, and the
    stale entries are overwritten when a real token reaches that
    position. Positions at/past the table's capacity (a near-budget
    lane drafted past its reservation) route their writes to the
    engine's scratch block 0 instead of wrapping into a live block.
    """
    b, s = tokens.shape
    block_k = pool['k'].shape[2]
    max_len = block_tables.shape[1] * block_k
    positions = pos[:, None] + jnp.arange(s, dtype=jnp.int32)  # [B, S]
    cos, sin = llama._rope_freqs(cfg, positions)  # pylint: disable=protected-access
    x = params['tok_embedding'][tokens].astype(cfg.dtype)
    safe = positions < max_len
    pidx = jnp.minimum(positions, max_len - 1)
    blk_all = jnp.take_along_axis(block_tables, pidx // block_k, axis=1)
    # Scratch-route overflow writes (block 0 is engine-reserved).
    blk_all = jnp.where(safe, blk_all, 0)
    off = pidx % block_k

    def body(carry, layer_lpool):
        layer, lpool = layer_lpool
        bl, sl, _ = carry.shape
        hd = cfg.head_dim
        h = llama.rms_norm(carry, layer['attn_norm'], cfg.norm_eps)
        q = llama.quant_mm(h, layer['wq']).reshape(bl, sl, cfg.n_heads,
                                                   hd)
        k = llama.quant_mm(h, layer['wk']).reshape(bl, sl,
                                                   cfg.n_kv_heads, hd)
        v = llama.quant_mm(h, layer['wv']).reshape(bl, sl,
                                                   cfg.n_kv_heads, hd)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        lpool = _write_kv(lpool, (blk_all, off), k, v)
        attn = _attend_paged_verify(dcfg, q, lpool, block_tables, pos,
                                    mesh=mesh)
        attn = attn.reshape(bl, sl, cfg.n_heads * hd)
        xc = carry + llama.quant_mm(attn, layer['wo']).astype(cfg.dtype)
        return llama.ffn_sublayer(cfg, xc, layer), lpool

    x, pool = jax.lax.scan(body, x, (params['layers'], pool))
    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x @ params['lm_head']).astype(jnp.float32)  # [B, S, V]
    return logits, pool


def _copy_block(pool: Cache, src: jax.Array, dst: jax.Array) -> Cache:
    """Copy one pool block (all layers, scales included) — the device
    half of copy-on-write: the engine clones a shared block before a new
    request writes into its tail."""
    out = dict(pool)
    for name in pool:
        out[name] = pool[name].at[:, dst].set(pool[name][:, src])
    return out


def _inject_pool_blocks(pool: Cache, block_idx: jax.Array,
                        values: Cache) -> Cache:
    """Write peer-fetched prefix blocks into the pool: ``values[name]``
    is ``[L, n, block_k, ...]`` landing at pool blocks ``block_idx``
    [n] — the device half of the cross-replica prefix tier. Values
    arrive in the pool's own storage dtype (int8 pools receive int8 +
    scale planes verbatim), so injection is a pure scatter: no
    re-quantization, and the fetched bytes decode bit-identically to
    the peer's."""
    out = dict(pool)
    for name in pool:
        out[name] = pool[name].at[:, block_idx].set(
            values[name].astype(pool[name].dtype))
    return out


# Engine-serving entry points for the paged cache. The pool is DONATED
# everywhere — block scatters mutate the persistent HBM buffers; callers
# rebind to the returned pool. One compile per (bucket, prefix-bucket)
# shape actually used.
paged_prefill = jax.jit(_paged_prefill, static_argnames=('cfg',),
                        donate_argnums=(5,))
paged_prefill_with_prefix = jax.jit(_paged_prefill_with_prefix,
                                    static_argnames=('cfg',),
                                    donate_argnums=(7,))
copy_block = jax.jit(_copy_block, donate_argnums=(0,))
inject_pool_blocks = jax.jit(_inject_pool_blocks, donate_argnums=(0,))


def _decode_step(params: Params, token: jax.Array, pos: jax.Array,
                 cfg: llama.LlamaConfig, dcfg: DecodeConfig, cache: Cache
                 ) -> Tuple[jax.Array, Cache]:
    """token [B] at positions pos [B] → (logits [B, vocab], cache)."""
    cos, sin = llama._rope_freqs(cfg, pos[:, None])  # pylint: disable=protected-access
    x = params['tok_embedding'][token][:, None].astype(cfg.dtype)

    def body(carry, layer_lcache):
        layer, lcache = layer_lcache
        xc, lcache = _block_decode(cfg, dcfg, carry, layer, lcache,
                                   cos, sin, pos)
        return xc, lcache

    x, cache = jax.lax.scan(body, x, (params['layers'], cache))
    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x[:, 0] @ params['lm_head']).astype(jnp.float32)
    return logits, cache


# Step-serving entry point. BREAKING vs the pre-flash-decode signature
# (new dcfg arg, so old call sites fail loudly at the TypeError, not
# silently): the cache is DONATED — its buffers are reused for the
# returned cache instead of copying ~GBs per token, so callers must
# rebind to the returned cache (`logits, cache = decode_step(...)`);
# touching the donated input afterwards raises on TPU.
decode_step = jax.jit(_decode_step, static_argnames=('cfg', 'dcfg'),
                      donate_argnums=(5,))


def _sample(logits: jax.Array, key: jax.Array,
            temperature: float) -> jax.Array:
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature,
                                  axis=-1).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'dcfg', 'max_new_tokens'),
                   donate_argnums=(7,))
def _generate_impl(params: Params, prompt: jax.Array,
                   prompt_lens: jax.Array, cfg: llama.LlamaConfig,
                   dcfg: DecodeConfig, max_new_tokens: int,
                   rng: jax.Array, cache: Cache
                   ) -> Tuple[jax.Array, Cache]:
    b, _ = prompt.shape
    first_key, steps_key = jax.random.split(rng)
    last_logits, cache = prefill(params, prompt, cfg, cache, prompt_lens)

    first = _sample(last_logits, first_key, dcfg.temperature)
    done0 = (jnp.full((b,), False) if dcfg.eos_id is None else
             first == dcfg.eos_id)

    def step(carry, key):
        token, pos, cache_c, done = carry
        logits, cache_c = _decode_step(params, token, pos, cfg, dcfg,
                                       cache_c)
        nxt = _sample(logits, key, dcfg.temperature)
        if dcfg.eos_id is not None:
            nxt = jnp.where(done, dcfg.eos_id, nxt)
            done = done | (nxt == dcfg.eos_id)
        return (nxt, pos + 1, cache_c, done), nxt

    keys = jax.random.split(steps_key, max_new_tokens - 1) \
        if max_new_tokens > 1 else jnp.zeros((0, 2), jnp.uint32)
    (_, _, cache, _), rest = jax.lax.scan(
        step, (first, prompt_lens, cache, done0), keys)
    # The cache is returned so the donated input buffers alias an output
    # (true in-place update); `generate` drops it for API compatibility.
    return jnp.concatenate([first[:, None], rest.T], axis=1), cache


def generate(params: Params,
             prompt: jax.Array,
             prompt_lens: jax.Array,
             cfg: llama.LlamaConfig,
             dcfg: DecodeConfig,
             max_new_tokens: int,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """prompt [B, S_prompt] right-padded → generated tokens
    [B, max_new_tokens] (post-EOS positions hold eos_id).

    The KV cache is allocated here and donated into the jitted impl, so
    prefill writes and per-step updates land in the same buffers rather
    than copying the [L,B,max_len,Hkv,hd] carry. Callers needing
    buffer reuse ACROSS requests should drive ``decode_step`` (which
    donates and returns its cache) plus ``prefill`` wrapped in their own
    donating jit — ``prefill`` itself is left untraced/undonated so it
    composes with outer jits.
    """
    b, s_prompt = prompt.shape
    if s_prompt + max_new_tokens > dcfg.max_len:
        # A real error, not an assert: serving callers (the engine's
        # admission path) must be able to catch and reject/clamp an
        # over-budget request instead of dying mid-loop — and asserts
        # vanish under `python -O`.
        raise ValueError(
            f'prompt ({s_prompt}) + max_new_tokens ({max_new_tokens}) '
            f'exceeds max_len {dcfg.max_len}')
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    cache = init_kv_cache(cfg, b, dcfg.max_len, dcfg.kv_cache_dtype)
    # Host-side serving telemetry: KV-cache capacity/occupancy + dtype
    # gauges and a request counter. Latency histograms (TTFT, per-token)
    # are recorded by callers that own a sync boundary (decode_bench) —
    # timing the async dispatch here would measure nothing real.
    from skypilot_tpu.observability import metrics as metrics_lib
    from skypilot_tpu.observability import runtime_metrics
    runtime_metrics.record_kv_cache(b, dcfg.max_len,
                                    s_prompt + max_new_tokens,
                                    dcfg.kv_cache_dtype)
    metrics_lib.counter('skytpu_decode_requests_total',
                        'Decode requests (batched generate calls).').inc()
    tokens, _ = _generate_impl(params, prompt, prompt_lens, cfg, dcfg,
                               max_new_tokens, rng, cache)
    return tokens


def completed_token_counts(tokens, eos_id: Optional[int]):
    """Per-sequence GENERATED token counts of a [B, T] generation.

    The EOS token itself counts (the model produced it); the post-EOS
    positions — which ``generate`` pads with ``eos_id`` — do not.
    Benchmarks must divide by this, not ``B * T``: counting the padding
    inflates tokens/s by exactly the fraction of the batch that stopped
    early. ``eos_id=None`` → every position counts. Host-side numpy.
    """
    import numpy as np
    t = np.asarray(tokens)
    b, n = t.shape
    if eos_id is None:
        return np.full((b,), n, dtype=np.int64)
    is_eos = t == eos_id
    return np.where(is_eos.any(axis=1), is_eos.argmax(axis=1) + 1,
                    n).astype(np.int64)
