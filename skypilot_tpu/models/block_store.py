"""Durable fleet KV cache: a disk-backed persistent prefix-block store.

The cross-replica prefix tier (PR 15) and the handoff plane (PR 16)
made every replica's radix cache one fleet-wide cache — but it all dies
with the fleet: a cold start, a scale-up, or a full restart re-prefills
every shared prefix from scratch. This module is the durable tier
behind that cache: a store process (LB- or head-hosted, or a model
server running ``--role store``) persists published radix runs to disk
and serves them back over the SAME ``POST /prefix_blocks`` protocol a
peer replica speaks, so the engine's cold-miss path needs no second
wire format — peer first, store second, plain prefill last.

Design points:

* **Wire-format exact.** Entries are the :mod:`prefix_transfer` payload
  (bf16 bytes; int8 values + their fp32 scale planes; unsharded logical
  ``[L, n, block_k, ...]`` blocks), so a tp=1 owner's spill warms a
  tp=2 fetcher and vice versa, and the engine's
  ``_install_remote_blocks`` validation/injection path — the thing that
  makes a fetched-block decode token-identical to local prefill — is
  reused verbatim.
* **Torn-write safe.** Spills write ``<digest>.json.tmp-*`` then
  ``os.replace`` — a crash mid-spill leaves either the old entry or a
  tmp file (swept at load), never a half-written visible entry. Any
  entry that fails to deserialize (pre-rename legacy crash, disk
  corruption, chaos ``store_torn_entry``) is dropped from the index and
  unlinked instead of being served: a torn entry is a MISS, not a
  crash and never garbage K/V.
* **Capacity-bounded.** ``SKYTPU_STORE_CAPACITY_BYTES`` caps on-disk
  bytes with LRU eviction over *digest families* (entries sharing a
  prompt head evict together — partial families would leave fetchers
  paying store round-trips for prefixes whose tails are gone).
* **Write-behind spill.** Replicas persist newly published radix runs
  asynchronously (``DecodeEngine._service_store_spills``): the engine
  loop only exports the host-side payload; the POST rides a background
  worker, budget-bounded (``SKYTPU_STORE_SPILL_BUDGET_SECONDS``) and
  backoff-bounded (``SKYTPU_STORE_BACKOFF_SECONDS``) like every other
  transfer in the serving plane. A dead or slow store degrades spill
  and fetch alike to "no store" — never a stall, never a 500.

Chaos points (``SKYTPU_CHAOS``, see utils/chaos.py): ``store_down``
(client transports fail as if the store were unreachable),
``store_torn_entry`` (a spill persists a truncated entry — the read
side must ignore it), ``store_slow`` (the store wedges each lookup
``SKYTPU_CHAOS_STORE_SLOW_SECONDS`` — the fetcher's wall-clock budget
must degrade the admission to plain prefill).
"""
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.models import prefix_transfer
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import common_utils

# Engine/server-side knobs (registered in utils/env_registry.py).
# Where replicas fetch from / spill to (the store's base URL; unset =
# no durable tier).
STORE_URL_ENV = 'SKYTPU_STORE_URL'
# Arms the store ROLE on a model server / LB host: the directory
# entries persist under.
STORE_DIR_ENV = 'SKYTPU_STORE_DIR'
CAPACITY_ENV = 'SKYTPU_STORE_CAPACITY_BYTES'
DEFAULT_CAPACITY_BYTES = 1 << 30
# Cold-miss store lookups ride the engine loop exactly like peer
# fetches, so they get their own (typically tighter) budget.
FETCH_BUDGET_ENV = 'SKYTPU_STORE_FETCH_BUDGET_SECONDS'
DEFAULT_FETCH_BUDGET_SECONDS = 0.5
# One write-behind spill POST's budget (off-loop; bounds the worker,
# not the engine step).
SPILL_BUDGET_ENV = 'SKYTPU_STORE_SPILL_BUDGET_SECONDS'
DEFAULT_SPILL_BUDGET_SECONDS = 2.0
# A store that failed (fetch or spill) is left alone this long.
BACKOFF_ENV = 'SKYTPU_STORE_BACKOFF_SECONDS'
DEFAULT_BACKOFF_SECONDS = 30.0
# Only runs at least this many tokens long are worth a durable entry
# (0 = the engine's block size).
SPILL_MIN_TOKENS_ENV = 'SKYTPU_STORE_SPILL_MIN_TOKENS'
# Digest-family grouping: entries sharing their first N tokens (or
# their full prompt when shorter) evict together and are advertised
# together to the autoscaler's pre-warm plane.
FAMILY_TOKENS_ENV = 'SKYTPU_STORE_FAMILY_TOKENS'
DEFAULT_FAMILY_TOKENS = 128
# Chaos: how long a fired ``store_slow`` wedges one store lookup.
STORE_SLOW_SECONDS_ENV = 'SKYTPU_CHAOS_STORE_SLOW_SECONDS'
DEFAULT_STORE_SLOW_SECONDS = 2.0


def family_digest(tokens: Sequence[int],
                  family_tokens: Optional[int] = None) -> str:
    """The digest-family key of a token run: sha1 over the first
    ``family_tokens`` token ids as decimal text (the
    ``lb_policies.prefix_digest`` encoding, so LB routing digests and
    store families computed over the same head agree)."""
    if family_tokens is None:
        family_tokens = common_utils.env_int(FAMILY_TOKENS_ENV,
                                             DEFAULT_FAMILY_TOKENS)
    head = [int(t) for t in tokens[:max(int(family_tokens), 1)]]
    h = hashlib.sha1()
    for t in head:
        h.update(b'%d,' % t)
    return h.hexdigest()[:16]


def _entry_digest(tokens: Sequence[int]) -> str:
    """Entry key: sha1 over the FULL block-aligned run (same decimal
    text encoding as the family digest)."""
    h = hashlib.sha1()
    for t in tokens:
        h.update(b'%d,' % int(t))
    return h.hexdigest()


class _Entry:
    __slots__ = ('tokens', 'family', 'path', 'nbytes', 'block_k',
                 'kv_cache_dtype')

    def __init__(self, tokens: Tuple[int, ...], family: str, path: str,
                 nbytes: int, block_k: int, kv_cache_dtype: str):
        self.tokens = tokens
        self.family = family
        self.path = path
        self.nbytes = nbytes
        self.block_k = block_k
        self.kv_cache_dtype = kv_cache_dtype


class BlockStore:
    """Disk-backed prefix-block store (one directory, one process).

    Thread-safe: the store role's HTTP handlers call ``get``/``put``
    from server threads. Entries live as
    ``<root>/<family>/<digest>.json`` — the encoded wire payload plus
    the run's token list — and the in-memory index holds metadata only
    (tokens, sizes); array bytes stay on disk until a fetch slices
    them.
    """

    def __init__(self, root: str,
                 capacity_bytes: Optional[int] = None,
                 family_tokens: Optional[int] = None):
        self.root = root
        self.capacity_bytes = (
            capacity_bytes if capacity_bytes is not None
            else common_utils.env_int(CAPACITY_ENV,
                                      DEFAULT_CAPACITY_BYTES))
        self._family_tokens = (
            family_tokens if family_tokens is not None
            else common_utils.env_int(FAMILY_TOKENS_ENV,
                                      DEFAULT_FAMILY_TOKENS))
        self._lock = threading.Lock()
        # digest -> _Entry; families keep LRU order (move_to_end on
        # every touch, popitem(last=False) evicts the coldest family).
        self._index: Dict[str, _Entry] = {}
        self._families: 'OrderedDict[str, set]' = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.spills = 0
        self.evictions = 0
        self.torn_dropped = 0
        os.makedirs(root, exist_ok=True)
        self._load()

    # ------------------------------------------------------------ loading

    def _load(self) -> None:
        """Rebuild the index from disk (process restart — the whole
        point of a durable tier). Tmp files from interrupted spills are
        swept; entries that fail to parse are dropped, not served."""
        for fam in sorted(os.listdir(self.root)):
            fam_dir = os.path.join(self.root, fam)
            if not os.path.isdir(fam_dir):
                continue
            for name in sorted(os.listdir(fam_dir)):
                path = os.path.join(fam_dir, name)
                if not name.endswith('.json'):
                    # Interrupted tmp spill (crash between write and
                    # rename): sweep it.
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                entry = self._parse_entry(path)
                if entry is None:
                    self.torn_dropped += 1
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                digest = name[:-len('.json')]
                self._index[digest] = entry
                self._families.setdefault(entry.family, set()).add(digest)
                self._bytes += entry.nbytes

    def _parse_entry(self, path: str) -> Optional[_Entry]:
        """Metadata-only validation of one on-disk entry; None for
        anything torn or malformed (the caller drops it)."""
        try:
            nbytes = os.path.getsize(path)
            with open(path, 'r', encoding='utf-8') as f:
                body = json.load(f)
            tokens = tuple(int(t) for t in body['prompt'])
            bk = int(body['block_k'])
            matched = int(body['matched_tokens'])
            dtype = str(body['kv_cache_dtype'])
            if (not tokens or bk <= 0 or matched != len(tokens)
                    or matched % bk or not body['arrays']):
                return None
        except (OSError, ValueError, TypeError, KeyError,
                json.JSONDecodeError):
            return None
        return _Entry(tokens, family_digest(tokens, self._family_tokens),
                      path, nbytes, bk, dtype)

    # ------------------------------------------------------------- writes

    def put(self, tokens: Sequence[int], payload: Dict[str, Any]) -> bool:
        """Persist one published radix run (decoded-payload form: numpy
        arrays, ``from_tokens == 0`` — spills always ship whole runs so
        any fetcher offset can be served by slicing). Returns False on
        validation failure or disk error (the spiller backs off);
        duplicate runs are a cheap no-op True."""
        tokens = tuple(int(t) for t in tokens)
        try:
            matched = int(payload['matched_tokens'])
            bk = int(payload['block_k'])
            arrays = payload['arrays']
        except (KeyError, TypeError, ValueError):
            return False
        if (not tokens or int(payload.get('from_tokens', -1)) != 0
                or matched != len(tokens) or bk <= 0 or matched % bk
                or not arrays):
            return False
        digest = _entry_digest(tokens)
        with self._lock:
            if digest in self._index:
                self._touch_family(self._index[digest].family)
                return True
        fam = family_digest(tokens, self._family_tokens)
        fam_dir = os.path.join(self.root, fam)
        os.makedirs(fam_dir, exist_ok=True)
        body = prefix_transfer.encode_payload(
            matched, 0, bk, payload['kv_cache_dtype'], arrays)
        body['prompt'] = list(tokens)
        data = json.dumps(body).encode('utf-8')
        path = os.path.join(fam_dir, digest + '.json')
        if chaos.should_fire('store_torn_entry'):
            # A spiller killed mid-write (legacy non-atomic writer /
            # disk corruption): half the bytes land at the FINAL path.
            # The spiller believes it succeeded; reads and restarts
            # must treat the entry as absent, never deserialize it.
            try:
                with open(path, 'wb') as f:
                    f.write(data[:len(data) // 2])
            except OSError:
                return False
            with self._lock:
                self._admit_entry(digest, _Entry(
                    tokens, fam, path, len(data) // 2, bk,
                    str(payload['kv_cache_dtype'])))
            return True
        tmp = path + f'.tmp-{os.getpid()}-{threading.get_ident()}'
        try:
            with open(tmp, 'wb') as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        with self._lock:
            self._admit_entry(digest, _Entry(
                tokens, fam, path, len(data), bk,
                str(payload['kv_cache_dtype'])))
        return True

    def _admit_entry(self, digest: str, entry: _Entry) -> None:
        """Index one just-written entry (lock held), then evict cold
        families over capacity. A stored strict prefix of the new run
        is NOT pruned: ``get`` probes by exact extension, so the
        128-token shared head and a 136-token tail-specific run serve
        DIFFERENT fetchers — dropping the short one would turn every
        other tail of that family into a store miss."""
        self._index[digest] = entry
        self._families.setdefault(entry.family, set()).add(digest)
        self._touch_family(entry.family)
        self._bytes += entry.nbytes
        self.spills += 1
        self._evict_over_capacity(keep=entry.family)

    def _drop_entry(self, digest: str) -> None:
        entry = self._index.pop(digest, None)
        if entry is None:
            return
        fam = self._families.get(entry.family)
        if fam is not None:
            fam.discard(digest)
            if not fam:
                self._families.pop(entry.family, None)
        self._bytes -= entry.nbytes
        try:
            os.unlink(entry.path)
        except OSError:
            pass

    def _touch_family(self, family: str) -> None:
        if family in self._families:
            self._families.move_to_end(family)

    def _evict_over_capacity(self, keep: Optional[str] = None) -> None:
        """LRU-evict whole digest families until under capacity (lock
        held). ``keep`` (the family just touched) survives even when it
        alone exceeds capacity — evicting the entry being admitted
        would turn an over-sized knob into a store that caches
        nothing."""
        while self._bytes > self.capacity_bytes and self._families:
            victim = next(iter(self._families))
            if victim == keep and len(self._families) == 1:
                break
            if victim == keep:
                # Cheapest way to skip the protected head: rotate it to
                # the MRU end; the loop then sees the true coldest.
                self._families.move_to_end(victim)
                victim = next(iter(self._families))
            for digest in list(self._families.get(victim, ())):
                self._drop_entry(digest)
            self._families.pop(victim, None)
            self.evictions += 1

    # -------------------------------------------------------------- reads

    def get(self, tokens: Sequence[int], from_tokens: int,
            block_k: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Longest stored prefix of ``tokens`` extending past
        ``from_tokens``, as a decoded wire payload sliced to the
        caller's offset (``from_tokens`` multiples of the entry's
        block_k only — the engine always asks block-aligned). None =
        store miss (including torn entries, which are dropped on
        contact)."""
        if chaos.should_fire('store_slow'):
            time.sleep(common_utils.env_float(
                STORE_SLOW_SECONDS_ENV, DEFAULT_STORE_SLOW_SECONDS))
        tokens = tuple(int(t) for t in tokens)
        from_tokens = int(from_tokens)
        entry = digest = None
        with self._lock:
            # Longest-prefix probe by hash lookup: O(len/block_k) tuple
            # hashes, no scan. The caller's block_k bounds the stride;
            # absent (store-side handlers), fall back to probing every
            # stored length in this family.
            if block_k and block_k > 0:
                lengths = range((len(tokens) // block_k) * block_k, 0,
                                -block_k)
            else:
                fam = family_digest(tokens, self._family_tokens)
                lengths = sorted(
                    {len(self._index[d].tokens)
                     for d in self._families.get(fam, ())
                     if len(self._index[d].tokens) <= len(tokens)},
                    reverse=True)
            for n in lengths:
                if n <= from_tokens:
                    break
                d = _entry_digest(tokens[:n])
                e = self._index.get(d)
                if e is not None and e.tokens == tokens[:n]:
                    entry, digest = e, d
                    self._touch_family(e.family)
                    break
        if entry is None:
            with self._lock:
                self.misses += 1
            return None
        payload = self._read_payload(digest, entry)
        if payload is None:
            return None
        return self._slice_payload(payload, from_tokens)

    def _read_payload(self, digest: str,
                      entry: _Entry) -> Optional[Dict[str, Any]]:
        """Load + decode one entry; a torn/corrupt body drops the entry
        (counted) and reads as a miss."""
        try:
            with open(entry.path, 'r', encoding='utf-8') as f:
                body = json.load(f)
            payload = prefix_transfer.decode_payload(body)
        except (OSError, ValueError, json.JSONDecodeError):
            payload = None
        if (payload is None or not payload['arrays']
                or payload['matched_tokens'] != len(entry.tokens)):
            with self._lock:
                self._drop_entry(digest)
                self.torn_dropped += 1
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return payload

    @staticmethod
    def _slice_payload(payload: Dict[str, Any],
                       from_tokens: int) -> Optional[Dict[str, Any]]:
        """Re-base a whole-run payload to the caller's offset: drop the
        blocks before ``from_tokens`` (the fetcher already has them)."""
        bk = payload['block_k']
        if from_tokens % bk or from_tokens >= payload['matched_tokens']:
            return None
        start = from_tokens // bk
        return {
            'matched_tokens': payload['matched_tokens'],
            'from_tokens': from_tokens,
            'block_k': bk,
            'kv_cache_dtype': payload['kv_cache_dtype'],
            'arrays': {name: a[:, start:]
                       for name, a in payload['arrays'].items()},
        }

    def get_by_digest(self, digest: str
                      ) -> Optional[Tuple[List[int], Dict[str, Any]]]:
        """Pre-warm lookup: the longest entry of the FAMILY ``digest``
        names (family key or full entry digest both resolve), returned
        as (tokens, whole-run payload) — what a joining replica needs
        to warm a family it has never seen a prompt for."""
        with self._lock:
            candidates = list(self._families.get(digest, ()))
            if not candidates and digest in self._index:
                candidates = [digest]
            if not candidates:
                self.misses += 1
                return None
            best = max(candidates,
                       key=lambda d: len(self._index[d].tokens))
            entry = self._index[best]
            self._touch_family(entry.family)
        payload = self._read_payload(best, entry)
        if payload is None:
            return None
        return list(entry.tokens), payload

    def stats(self) -> Dict[str, Any]:
        """The store block of ``/slo`` / ``GET /prefix_blocks``."""
        with self._lock:
            return {
                'entries': len(self._index),
                'families': len(self._families),
                'bytes': self._bytes,
                'capacity_bytes': self.capacity_bytes,
                'hits': self.hits,
                'misses': self.misses,
                'spills': self.spills,
                'evictions': self.evictions,
                'torn_dropped': self.torn_dropped,
            }

    def families(self) -> List[str]:
        """Family keys, LRU → MRU (the pre-warm plane's menu)."""
        with self._lock:
            return list(self._families)


# --------------------------------------------------------------- transport
# The store speaks the peer protocol: fetches reuse
# prefix_transfer.http_fetch against the store URL; the spill direction
# POSTs the same encoded body WITH arrays (the handler disambiguates on
# their presence). ``store_down`` chaos fires client-side so an armed
# replica degrades exactly as if the store host vanished.


def http_store_fetch(store_url: str, tokens: Sequence[int],
                     from_tokens: int, budget_seconds: float,
                     instance: Optional[str] = None
                     ) -> Optional[Dict[str, Any]]:
    """Fetch transport: identical wire exchange to a peer fetch. None
    on failure/unreachable (the engine backs the store off)."""
    if chaos.should_fire('store_down'):
        return None
    return prefix_transfer.http_fetch(store_url, tokens, from_tokens,
                                      budget_seconds, instance=instance)


def http_store_spill(store_url: str, tokens: Sequence[int],
                     payload: Dict[str, Any],
                     budget_seconds: float) -> bool:
    """Spill transport: POST the encoded whole-run payload (plus its
    prompt) to the store's ``/prefix_blocks``. True only on an acked
    persist."""
    if chaos.should_fire('store_down'):
        return False
    import requests
    body = prefix_transfer.encode_payload(
        payload['matched_tokens'], payload['from_tokens'],
        payload['block_k'], payload['kv_cache_dtype'],
        payload['arrays'])
    body['prompt'] = [int(t) for t in tokens]
    half = max(budget_seconds / 2, 1e-3)
    try:
        resp = requests.post(store_url.rstrip('/') + '/prefix_blocks',
                             json=body, timeout=(half, half))
    except requests.RequestException:
        return False
    try:
        if resp.status_code != 200:
            return False
        reply = resp.json()
    except (requests.RequestException, ValueError):
        return False
    finally:
        resp.close()
    return bool(isinstance(reply, dict) and reply.get('ok'))


def http_store_prewarm_fetch(store_url: str, digest: str,
                             budget_seconds: float
                             ) -> Optional[Tuple[List[int],
                                                 Dict[str, Any]]]:
    """Pre-warm transport: resolve a digest family to its longest
    stored run — (tokens, decoded whole-run payload) or None."""
    if chaos.should_fire('store_down'):
        return None
    import requests
    half = max(budget_seconds / 2, 1e-3)
    try:
        resp = requests.post(store_url.rstrip('/') + '/prefix_blocks',
                             json={'digest': str(digest)},
                             timeout=(half, half))
    except requests.RequestException:
        return None
    try:
        if resp.status_code != 200:
            return None
        body = resp.json()
    except (requests.RequestException, ValueError):
        return None
    finally:
        resp.close()
    if not isinstance(body, dict) or 'prompt' not in body:
        return None
    payload = prefix_transfer.decode_payload(body)
    if payload is None:
        return None
    try:
        tokens = [int(t) for t in body['prompt']]
    except (TypeError, ValueError):
        return None
    return tokens, payload


# ---------------------------------------------------------------- serving


def handle_store_post(store: BlockStore,
                      body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
    """The store role's ``POST /prefix_blocks`` dispatch, shared by the
    model server's store role and any LB/head host. Three request
    shapes, disambiguated by body keys:

    * ``arrays`` present → SPILL: decode, persist, ack ``{'ok': true}``.
    * ``digest`` present → PRE-WARM: the family's longest run (encoded,
      with its ``prompt``) or an empty payload.
    * otherwise → FETCH (the peer-protocol body): longest stored prefix
      past ``from_tokens``, or an honest empty payload.

    Returns (status, json_body); errors are 400s with a reason — the
    store must never 500 over a malformed body.
    """
    if not isinstance(body, dict):
        return 400, {'error': 'malformed body'}
    if 'arrays' in body:
        payload = prefix_transfer.decode_payload(body)
        tokens = body.get('prompt')
        if payload is None or not isinstance(tokens, list):
            return 400, {'error': 'malformed spill payload'}
        ok = store.put(tokens, payload)
        return 200, {'ok': bool(ok)}
    if 'digest' in body:
        hit = store.get_by_digest(str(body['digest']))
        if hit is None:
            return 200, {'ok': False}
        tokens, payload = hit
        out = prefix_transfer.encode_payload(
            payload['matched_tokens'], payload['from_tokens'],
            payload['block_k'], payload['kv_cache_dtype'],
            payload['arrays'])
        out['prompt'] = tokens
        return 200, out
    try:
        tokens = [int(t) for t in body['prompt']]
        from_tokens = int(body.get('from_tokens', 0))
    except (KeyError, TypeError, ValueError):
        return 400, {'error': 'malformed fetch body'}
    payload = store.get(tokens, from_tokens)
    if payload is None:
        return 200, prefix_transfer.empty_payload(from_tokens, 0, '')
    return 200, prefix_transfer.encode_payload(
        payload['matched_tokens'], payload['from_tokens'],
        payload['block_k'], payload['kv_cache_dtype'],
        payload['arrays'])
