"""Mixtral-family Mixture-of-Experts decoder with expert parallelism.

TPU-first MoE (SURVEY §2.11 "EP" — absent from the reference's library,
present in its serving examples via vLLM flags):

* Router → top-k gating → **capacity-bounded dispatch/combine einsums**
  (GShard/Switch style): token routing is two dense einsums against
  one-hot dispatch masks, so everything stays static-shaped on the MXU —
  no gather/scatter, no data-dependent shapes under jit.
* Expert weights carry an 'expert' mesh-axis sharding; under GSPMD the
  dispatch einsum lowers to the all-to-all over the expert axis, XLA
  choosing the collective schedule over ICI.
* Dropped tokens (over capacity) fall through the residual connection —
  standard Switch behavior; an aux load-balancing loss keeps the router
  spread.

Attention/norm/RoPE are shared with ``models.llama``.
"""
import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.parallel.mesh import EXPERT_AXIS, FSDP_AXIS, MODEL_AXIS

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoeConfig(llama.LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    # Per-expert capacity = top_k * tokens / n_experts * capacity_factor.
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01


CONFIGS: Dict[str, MoeConfig] = {
    'mixtral-8x7b': MoeConfig(dim=4096, n_layers=32, n_heads=32,
                              n_kv_heads=8, ffn_dim=14336, n_experts=8,
                              top_k=2),
    'moe-debug': MoeConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                           n_kv_heads=2, ffn_dim=96, n_experts=4, top_k=2,
                           max_seq_len=128, remat=False),
}


# ------------------------------------------------------------------- init


def init_params(key: jax.Array, cfg: MoeConfig) -> Params:
    base = llama.init_params(key, cfg)
    init = jax.nn.initializers.normal(stddev=0.02)
    k_router, k1, k2, k3 = jax.random.split(jax.random.fold_in(key, 7), 4)
    layers = dict(base['layers'])
    # Replace the dense FFN with router + per-expert SwiGLU stacks.
    for name in ('w1', 'w2', 'w3'):
        del layers[name]
    e, d, f = cfg.n_experts, cfg.dim, cfg.ffn_dim
    layers['router'] = init(k_router, (cfg.n_layers, d, e), jnp.float32)
    layers['we1'] = init(k1, (cfg.n_layers, e, d, f), cfg.dtype)
    layers['we3'] = init(k2, (cfg.n_layers, e, d, f), cfg.dtype)
    layers['we2'] = init(k3, (cfg.n_layers, e, f, d), cfg.dtype)
    base['layers'] = layers
    return base


def param_partition_specs(cfg: MoeConfig) -> Params:
    base = llama.param_partition_specs(cfg)
    layers = dict(base['layers'])
    for name in ('w1', 'w2', 'w3'):
        del layers[name]
    layers['router'] = P(None, FSDP_AXIS, None)
    # Experts over the 'expert' axis; within an expert, megatron-style.
    layers['we1'] = P(None, EXPERT_AXIS, FSDP_AXIS, MODEL_AXIS)
    layers['we3'] = P(None, EXPERT_AXIS, FSDP_AXIS, MODEL_AXIS)
    layers['we2'] = P(None, EXPERT_AXIS, MODEL_AXIS, FSDP_AXIS)
    base['layers'] = layers
    return base


# ---------------------------------------------------------------- routing


def _route(h: jax.Array, router: jax.Array, cfg: MoeConfig
           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """h [T, d] → (dispatch [T, E, C] bool, combine [T, E, C] f32, aux).

    GShard top-k routing with per-expert capacity C.
    """
    t = h.shape[0]
    e = cfg.n_experts
    capacity = max(1, int(cfg.top_k * t / e * cfg.capacity_factor))

    logits = (h.astype(jnp.float32) @ router)            # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, cfg.top_k)  # [T, K]
    # Renormalize the kept gates (Mixtral convention).
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    # Position of each (token, k) within its expert's capacity: a cumsum
    # over the one-hot expert assignment, k-major so first choices win
    # slots before second choices.
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)  # [T, K, E]
    flat = onehot.transpose(1, 0, 2).reshape(cfg.top_k * t, e)  # k-major
    pos_flat = jnp.cumsum(flat, axis=0) - flat               # [K*T, E]
    pos = pos_flat.reshape(cfg.top_k, t, e).transpose(1, 0, 2)  # [T, K, E]
    within_cap = (pos < capacity) & (onehot == 1)            # [T, K, E]

    slot = jnp.sum(pos * onehot, axis=-1)                    # [T, K]
    slot_onehot = jax.nn.one_hot(slot, capacity,
                                 dtype=jnp.float32)          # [T, K, C]
    keep = jnp.any(within_cap, axis=-1).astype(jnp.float32)  # [T, K]

    # combine[t, e, c] = gate weight of token t in expert e slot c.
    combine = jnp.einsum('tke,tkc,tk,tk->tec',
                         onehot.astype(jnp.float32), slot_onehot,
                         gate_vals, keep)
    dispatch = combine > 0.0

    # Switch aux loss: fraction-of-assignments · mean-prob per expert.
    frac_tokens = jnp.mean(onehot.sum(axis=1) / cfg.top_k, axis=0)  # [E]
    mean_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * mean_probs)
    return dispatch, combine, aux


def moe_ffn(h: jax.Array, layer: Params, cfg: MoeConfig
            ) -> Tuple[jax.Array, jax.Array]:
    """h [B, S, d] → (out [B, S, d], aux loss). SwiGLU per expert."""
    b, s, d = h.shape
    flat = h.reshape(b * s, d)
    dispatch, combine, aux = _route(flat, layer['router'], cfg)
    # [T,E,C] x [T,d] → expert inputs [E,C,d]; under GSPMD with 'expert'-
    # sharded weights this is the EP all-to-all.
    xin = jnp.einsum('tec,td->ecd', dispatch.astype(cfg.dtype), flat)
    gate = jax.nn.silu(jnp.einsum(
        'ecd,edf->ecf', xin, layer['we1'],
        preferred_element_type=jnp.float32))
    up = jnp.einsum('ecd,edf->ecf', xin, layer['we3'],
                    preferred_element_type=jnp.float32)
    act = (gate * up).astype(cfg.dtype)
    xout = jnp.einsum('ecf,efd->ecd', act, layer['we2'],
                      preferred_element_type=jnp.float32)  # [E,C,d]
    out = jnp.einsum('tec,ecd->td', combine, xout.astype(jnp.float32))
    return out.reshape(b, s, d).astype(cfg.dtype), aux


# ---------------------------------------------------------------- forward


def _moe_block(cfg: MoeConfig, x: jax.Array, layer: Params,
               cos: jax.Array, sin: jax.Array) -> Tuple[jax.Array,
                                                        jax.Array]:
    # Shared attention sublayer (honors flash/ring config flags); only the
    # FFN differs from the dense decoder.
    x, _, _ = llama.attn_sublayer(cfg, x, layer, cos, sin)
    h = llama.rms_norm(x, layer['ffn_norm'], cfg.norm_eps)
    ffn_out, aux = moe_ffn(h, layer, cfg)
    return x + ffn_out, aux


def forward(params: Params,
            tokens: jax.Array,
            cfg: MoeConfig,
            positions: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, jax.Array]:
    """tokens [B,S] → (logits [B,S,V] f32, aux router loss scalar)."""
    _, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.int32)
    cos, sin = llama._rope_freqs(cfg, positions)  # pylint: disable=protected-access
    x = params['tok_embedding'][tokens].astype(cfg.dtype)

    def body(carry, layer):
        out, aux = _moe_block(cfg, carry, layer, cos, sin)
        return out, aux

    if cfg.remat:
        body = jax.checkpoint(body, prevent_cse=False)
    x, aux_per_layer = jax.lax.scan(body, x, params['layers'])

    x = llama.rms_norm(x, params['out_norm'], cfg.norm_eps)
    logits = (x @ params['lm_head']).astype(jnp.float32)
    return logits, jnp.sum(aux_per_layer) * cfg.router_aux_coef


def loss_fn(params: Params, tokens: jax.Array, targets: jax.Array,
            cfg: MoeConfig) -> jax.Array:
    logits, aux = forward(params, tokens, cfg)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None],
                               axis=-1).squeeze(-1)
    return jnp.mean(logz - gold) + aux
