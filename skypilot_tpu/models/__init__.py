"""In-tree model recipes — the TPU rewrites of the reference's llm/ and

examples/ workloads (``llm/llama-3_1-finetuning``, ``resnet_distributed_*``).
"""
