"""Optimizer: choose the cheapest/fastest (cloud, region, slice) per task.

Parity: ``sky/optimizer.py:107`` (Optimizer.optimize), ``:991``
(_optimize_dag), ``:1213`` (_fill_in_launchable_resources). TPU-first
redesign notes:

* Candidates for a TPU request are (region, zone) offerings of the slice,
  priced per chip-hour (host included) — the GPU-vs-TPU comparison the
  north-star needs falls out of ranking these against GPU instance SKUs.
* Time estimation uses the task's declared ``estimated_runtime`` if present,
  else peak-bf16-FLOPs as a throughput proxy so `--minimize-time` prefers
  bigger/faster slices.
* Chains use DP with per-edge egress costs (parity: _optimize_by_dp); small
  general DAGs use exhaustive enumeration (the reference shells out to an ILP
  solver; candidate sets here are small enough to enumerate).
"""
import enum
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import check as sky_check
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import ux_utils

logger = sky_logging.init_logger(__name__)

_DEFAULT_RUNTIME_SECONDS = 3600.0


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


class Optimizer:
    """Static methods only, mirroring the reference surface."""

    @staticmethod
    def optimize(dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[List[
                     resources_lib.Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        """Fill in task.best_resources for every task in the dag.

        Raises ResourcesUnavailableError if any task has no feasible
        candidate.
        """
        candidates = {
            task: Optimizer._estimate_candidates(task, minimize,
                                                 blocked_resources or [])
            for task in dag.tasks
        }
        used_greedy = False
        if dag.is_chain() or len(dag.tasks) <= 1:
            plan = Optimizer._optimize_by_dp(dag, candidates, minimize)
        else:
            plan, used_greedy = Optimizer._optimize_exhaustive(
                dag, candidates, minimize)
        for task, (resources, _) in plan.items():
            task.best_resources = resources
        if not quiet:
            Optimizer.print_plan(dag, plan, candidates, minimize,
                                 greedy_note=used_greedy)
        return dag

    # ------------------------------------------------------------ candidates

    @staticmethod
    def _fill_in_launchable_resources(
            task: 'task_lib.Task',
            blocked_resources: List[resources_lib.Resources]
    ) -> List[resources_lib.Resources]:
        """Expand each (partial) task resource over enabled clouds into

        launchable candidates, one per (cloud, region, zone) offering.
        Parity: optimizer.py:1213."""
        enabled_clouds = sky_check.get_cached_enabled_clouds_or_refresh()
        launchable: List[resources_lib.Resources] = []
        fuzzy_hints: List[str] = []
        for res in task.resources:
            clouds_to_try = ([res.cloud] if res.cloud is not None else
                             enabled_clouds)
            for cloud in clouds_to_try:
                if res.cloud is None and cloud.name not in [
                        c.name for c in enabled_clouds
                ]:
                    continue
                feasible, hints = cloud.get_feasible_launchable_resources(
                    res, task.num_nodes)
                fuzzy_hints.extend(hints)
                for cand in feasible:
                    # One candidate per concrete region (zone picked at
                    # provision time by the failover loop).
                    regions = cloud.regions_with_offering(
                        cand.instance_type, cand.accelerators,
                        cand.use_spot, cand.region, cand.zone)
                    for region in regions:
                        launchable.append(cand.copy(region=region.name))
        launchable = [
            c for c in launchable
            if not Optimizer._is_blocked(c, blocked_resources)
        ]
        if not launchable:
            hint_msg = (f' Did you mean: {sorted(set(fuzzy_hints))}?'
                        if fuzzy_hints else '')
            raise exceptions.ResourcesUnavailableError(
                f'No launchable resource found for {task}.{hint_msg} '
                'Try other resource requirements or regions, or run '
                '`sky check`.')
        return launchable

    @staticmethod
    def _is_blocked(candidate: resources_lib.Resources,
                    blocked: List[resources_lib.Resources]) -> bool:
        return any(b.less_demanding_than(candidate) for b in blocked)

    @staticmethod
    def _estimate_candidates(
        task: 'task_lib.Task', minimize: OptimizeTarget,
        blocked_resources: List[resources_lib.Resources]
    ) -> List[Tuple[resources_lib.Resources, float, float]]:
        """[(resources, cost, est_time_seconds)] sorted by the target."""
        out = []
        for cand in Optimizer._fill_in_launchable_resources(
                task, blocked_resources):
            est_time = Optimizer._estimate_time_seconds(task, cand)
            # COST ranks over a UNIFORM runtime (task-declared or
            # default): the FLOPs proxy scales accelerator candidates
            # for the TIME objective (and the est_time tie-break), but
            # discounting cost by estimated speed would double-count —
            # the reference prices hourly_cost × the task's declared
            # runtime for every candidate.
            est = getattr(task, 'estimated_runtime', None)
            cost_basis = float(est) if est else _DEFAULT_RUNTIME_SECONDS
            cost = cand.get_cost(cost_basis) * task.num_nodes
            out.append((cand, cost, est_time))
        key = (lambda t: (t[1], t[2])) if minimize == OptimizeTarget.COST \
            else (lambda t: (t[2], t[1]))
        out.sort(key=key)
        return out

    # Peak dense bf16 (fp16 where no bf16) TFLOPs per accelerator — the
    # throughput table behind `minimize=time` (VERDICT-r3 item 6: the
    # time model must be honest or refuse). A task that declares
    # ``estimated_runtime`` overrides the proxy entirely; unknown
    # accelerators/CPU candidates rank neutrally at the default runtime.
    _GPU_PEAK_BF16_TFLOPS = {
        'A100': 312.0,
        'A100-80GB': 312.0,
        'H100': 989.0,
        'H100-MEGA': 989.0,
        'H200': 989.0,
        'GH200': 989.0,
        'V100': 125.0,
        'T4': 65.0,
        'A10': 125.0,
        'A10G': 125.0,
        'L4': 121.0,
        'L40S': 362.0,
        'A40': 150.0,
        'RTX4090': 165.0,
        'RTX3090': 71.0,
        'RTX6000-ADA': 364.0,
        'RTX4000': 53.0,
    }
    # Normalization anchor: one v5e-8 slice (8 × 197 TFLOPs).
    _TIME_BASELINE_TFLOPS = 8 * 197.0

    @staticmethod
    def _estimate_time_seconds(task: 'task_lib.Task',
                               cand: resources_lib.Resources) -> float:
        """FLOPs-throughput time proxy (exact when the task declares
        ``estimated_runtime``). Compute-bound scaling is assumed; the
        proxy exists to rank candidates, not to predict wall-clock."""
        est = getattr(task, 'estimated_runtime', None)
        if est:
            return float(est)
        topo = cand.tpu_topology
        if topo is not None:
            return (_DEFAULT_RUNTIME_SECONDS *
                    Optimizer._TIME_BASELINE_TFLOPS /
                    max(topo.peak_bf16_tflops, 1e-9))
        if cand.accelerators:
            name, count = next(iter(cand.accelerators.items()))
            peak = Optimizer._GPU_PEAK_BF16_TFLOPS.get(name)
            if peak:
                return (_DEFAULT_RUNTIME_SECONDS *
                        Optimizer._TIME_BASELINE_TFLOPS /
                        (peak * float(count)))
        return _DEFAULT_RUNTIME_SECONDS

    # -------------------------------------------------------------- egress

    # Assumed inter-cloud transfer bandwidth for TIME-objective egress
    # (parity: the reference's DEFAULT egress-time model).
    _EGRESS_GBPS = 1.0
    # Joint-enumeration budget for general DAGs (placements examined).
    _ENUM_LIMIT = 50_000
    # Per-task candidate cap inside the joint enumeration.
    _ENUM_TOP_K = 6

    @staticmethod
    def _egress_penalty(src_cloud, dst_cloud, gigabytes: float,
                        minimize: OptimizeTarget) -> float:
        """$ (COST) or seconds (TIME) to move `gigabytes` between clouds."""
        if (src_cloud is None or gigabytes <= 0 or
                src_cloud.is_same_cloud(dst_cloud)):
            return 0.0
        if minimize == OptimizeTarget.COST:
            return src_cloud.get_egress_cost(gigabytes)
        return gigabytes * 8.0 / Optimizer._EGRESS_GBPS

    @staticmethod
    def _node_objective(task: 'task_lib.Task',
                        cand: resources_lib.Resources, cost: float,
                        est_time: float,
                        minimize: OptimizeTarget) -> float:
        """Candidate objective incl. pulling the task's declared inputs
        from their home cloud (parity: optimizer.py _egress_cost from
        inputs)."""
        obj = cost if minimize == OptimizeTarget.COST else est_time
        inputs_cloud = task.get_inputs_cloud()
        gb = task.estimated_inputs_size_gigabytes or 0.0
        return obj + Optimizer._egress_penalty(inputs_cloud, cand.cloud, gb,
                                               minimize)

    @staticmethod
    def _edge_penalty(parent: 'task_lib.Task',
                      src: resources_lib.Resources,
                      dst: resources_lib.Resources,
                      minimize: OptimizeTarget) -> float:
        """Penalty for shipping `parent`'s declared outputs to the child's
        placement (parity: optimizer.py per-edge egress)."""
        gb = parent.estimated_outputs_size_gigabytes or 0.0
        return Optimizer._egress_penalty(src.cloud, dst.cloud, gb, minimize)

    # ------------------------------------------------------------------ DP

    @staticmethod
    def _optimize_by_dp(
        dag: dag_lib.Dag, candidates, minimize: OptimizeTarget
    ) -> Dict['task_lib.Task', Tuple[resources_lib.Resources, float]]:
        """Exact DP over a task chain with per-edge egress (parity:
        optimizer.py:410 _optimize_by_dp)."""
        order = dag.get_sorted_tasks() if len(dag.tasks) > 1 else \
            list(dag.tasks)
        if not order:
            return {}
        first = order[0]
        # dp[i] = (best total objective ending with candidate i, plan)
        dp: List[Tuple[float, list]] = [
            (Optimizer._node_objective(first, cand, cost, est_time,
                                       minimize), [(first, cand, cost)])
            for cand, cost, est_time in candidates[first]
        ]
        for prev_task, task in zip(order, order[1:]):
            prev_cands = candidates[prev_task]
            new_dp: List[Tuple[float, list]] = []
            for cand, cost, est_time in candidates[task]:
                node = Optimizer._node_objective(task, cand, cost, est_time,
                                                 minimize)
                best_val, best_plan = None, None
                for j, (val, plan) in enumerate(dp):
                    total = val + node + Optimizer._edge_penalty(
                        prev_task, prev_cands[j][0], cand, minimize)
                    if best_val is None or total < best_val:
                        best_val = total
                        best_plan = plan + [(task, cand, cost)]
                new_dp.append((best_val, best_plan))
            dp = new_dp
        _, plan = min(dp, key=lambda v: v[0])
        return {task: (cand, cost) for task, cand, cost in plan}

    @staticmethod
    def _topk_cloud_diverse(cands: List[Tuple], k: int) -> List[Tuple]:
        """Top-k by rank, but guarantee the best candidate of EVERY cloud a
        slot first — a flat prefix cut can fill all k slots with regional
        duplicates of one cloud and blind the enumeration to cross-cloud
        colocation (egress depends only on the cloud)."""
        picked_idx: List[int] = []
        seen_clouds = set()
        for i, (cand, _, _) in enumerate(cands):
            name = cand.cloud.name if cand.cloud else None
            if name not in seen_clouds:
                seen_clouds.add(name)
                picked_idx.append(i)
            if len(picked_idx) >= k:
                break
        for i in range(len(cands)):
            if len(picked_idx) >= k:
                break
            if i not in picked_idx:
                picked_idx.append(i)
        return [cands[i] for i in sorted(picked_idx)]

    @staticmethod
    def _optimize_exhaustive(
        dag: dag_lib.Dag, candidates, minimize: OptimizeTarget
    ) -> Tuple[Dict['task_lib.Task',
                    Tuple[resources_lib.Resources, float]], bool]:
        """General DAGs: joint enumeration over top-K candidates per task
        when the placement space fits the budget, else topo-order greedy
        that accounts egress from already-placed parents.

        Returns ``(plan, used_greedy)`` — the caller surfaces the greedy
        fallback loudly (plan-table footnote), because a greedy plan
        carries no optimality guarantee.

        The reference shells out to an ILP solver (optimizer.py:471);
        bounded enumeration is exact on the same small DAGs and the greedy
        fallback degrades gracefully on large ones.
        """
        import itertools
        order = dag.get_sorted_tasks()
        topk = {
            task: Optimizer._topk_cloud_diverse(candidates[task],
                                                Optimizer._ENUM_TOP_K)
            for task in order
        }
        edges = [(u, v) for u, v in dag.graph.edges]
        space = 1
        for task in order:
            space *= max(1, len(topk[task]))
        if space <= Optimizer._ENUM_LIMIT:
            # Precompute per-candidate node objectives: only K*N distinct
            # values exist across the whole product.
            node_obj = {
                t: [
                    Optimizer._node_objective(t, cand, cost, est_time,
                                              minimize)
                    for cand, cost, est_time in topk[t]
                ] for t in order
            }
            # Per-edge K×K penalty matrices: each edge has at most K²
            # distinct values across the whole product.
            pos = {t: i for i, t in enumerate(order)}
            edge_mat = [
                (pos[u], pos[v], [[
                    Optimizer._edge_penalty(u, cu[0], cv[0], minimize)
                    for cv in topk[v]
                ] for cu in topk[u]]) for u, v in edges
            ]
            node_rows = [node_obj[t] for t in order]
            best_val, best_choice = None, None
            for choice in itertools.product(
                    *(range(len(topk[t])) for t in order)):
                total = sum(row[i] for row, i in zip(node_rows, choice))
                total += sum(mat[choice[ui]][choice[vi]]
                             for ui, vi, mat in edge_mat)
                if best_val is None or total < best_val:
                    best_val, best_choice = total, choice
            assert best_choice is not None
            return {
                t: (topk[t][i][0], topk[t][i][1])
                for t, i in zip(order, best_choice)
            }, False
        # Greedy fallback: place in topo order, charging egress from the
        # parents placed so far. This is NOT cost-optimal in general —
        # warn loudly so the user knows the plan has no guarantee.
        logger.warning(
            f'DAG placement space ({space:,} combinations) exceeds the '
            f'enumeration budget ({Optimizer._ENUM_LIMIT:,}); falling '
            'back to a parent-aware greedy placement with NO optimality '
            'guarantee. Consider splitting the DAG into chains or '
            'reducing per-task resource alternatives.')
        plan: Dict['task_lib.Task',
                   Tuple[resources_lib.Resources, float]] = {}
        for task in order:
            parents = list(dag.graph.predecessors(task))
            best_val, best = None, None
            for cand, cost, est_time in candidates[task]:
                total = Optimizer._node_objective(task, cand, cost,
                                                  est_time, minimize)
                total += sum(
                    Optimizer._edge_penalty(p, plan[p][0], cand, minimize)
                    for p in parents if p in plan)
                if best_val is None or total < best_val:
                    best_val, best = total, (cand, cost)
            assert best is not None
            plan[task] = best
        return plan, True

    # ---------------------------------------------------------------- print

    @staticmethod
    def print_plan(dag, plan, candidates, minimize,
                   greedy_note: bool = False) -> None:
        rows = []
        for task, (chosen, cost) in plan.items():
            n = task.num_nodes
            topo = chosen.tpu_topology
            infra = f'{chosen.cloud} ({chosen.region})'
            if topo is not None:
                acc = f'{topo.name}:{topo.num_chips} [{topo.topology_str}]'
            elif chosen.accelerators:
                name, cnt = next(iter(chosen.accelerators.items()))
                acc = f'{name}:{int(cnt)}'
            else:
                acc = '-'
            rows.append((task.name or '-', str(n), infra,
                         chosen.instance_type or '-', acc,
                         f'{chosen.get_hourly_cost() * n:.2f}'))
        header = ('TASK', 'NODES', 'INFRA', 'INSTANCE', 'ACCELERATORS',
                  '$/hr')
        widths = [
            max(len(header[i]), *(len(r[i]) for r in rows))
            for i in range(len(header))
        ]
        print(ux_utils.bold('Optimizer plan '
                            f'(minimizing {minimize.value}):'))
        print('  ' + '  '.join(h.ljust(widths[i])
                               for i, h in enumerate(header)))
        for r in rows:
            print('  ' + '  '.join(c.ljust(widths[i])
                                   for i, c in enumerate(r)))
        if greedy_note:
            print('  NOTE: the DAG placement space exceeded the '
                  'enumeration budget; this plan was produced by a '
                  'greedy heuristic and may not be '
                  f'{minimize.value}-optimal. Splitting the DAG into '
                  'chains restores the exact optimizer.')
