"""Optimizer: choose the cheapest/fastest (cloud, region, slice) per task.

Parity: ``sky/optimizer.py:107`` (Optimizer.optimize), ``:991``
(_optimize_dag), ``:1213`` (_fill_in_launchable_resources). TPU-first
redesign notes:

* Candidates for a TPU request are (region, zone) offerings of the slice,
  priced per chip-hour (host included) — the GPU-vs-TPU comparison the
  north-star needs falls out of ranking these against GPU instance SKUs.
* Time estimation uses the task's declared ``estimated_runtime`` if present,
  else peak-bf16-FLOPs as a throughput proxy so `--minimize-time` prefers
  bigger/faster slices.
* Chains use DP with per-edge egress costs (parity: _optimize_by_dp); small
  general DAGs use exhaustive enumeration (the reference shells out to an ILP
  solver; candidate sets here are small enough to enumerate).
"""
import collections
import enum
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import check as sky_check
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import ux_utils

logger = sky_logging.init_logger(__name__)

_DEFAULT_RUNTIME_SECONDS = 3600.0


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


class Optimizer:
    """Static methods only, mirroring the reference surface."""

    @staticmethod
    def optimize(dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[List[
                     resources_lib.Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        """Fill in task.best_resources for every task in the dag.

        Raises ResourcesUnavailableError if any task has no feasible
        candidate.
        """
        candidates = {
            task: Optimizer._estimate_candidates(task, minimize,
                                                 blocked_resources or [])
            for task in dag.tasks
        }
        if dag.is_chain() or len(dag.tasks) <= 1:
            plan = Optimizer._optimize_by_dp(dag, candidates, minimize)
        else:
            plan = Optimizer._optimize_exhaustive(dag, candidates, minimize)
        for task, (resources, _) in plan.items():
            task.best_resources = resources
        if not quiet:
            Optimizer.print_plan(dag, plan, candidates, minimize)
        return dag

    # ------------------------------------------------------------ candidates

    @staticmethod
    def _fill_in_launchable_resources(
            task: 'task_lib.Task',
            blocked_resources: List[resources_lib.Resources]
    ) -> List[resources_lib.Resources]:
        """Expand each (partial) task resource over enabled clouds into

        launchable candidates, one per (cloud, region, zone) offering.
        Parity: optimizer.py:1213."""
        enabled_clouds = sky_check.get_cached_enabled_clouds_or_refresh()
        launchable: List[resources_lib.Resources] = []
        fuzzy_hints: List[str] = []
        for res in task.resources:
            clouds_to_try = ([res.cloud] if res.cloud is not None else
                             enabled_clouds)
            for cloud in clouds_to_try:
                if res.cloud is None and cloud.name not in [
                        c.name for c in enabled_clouds
                ]:
                    continue
                feasible, hints = cloud.get_feasible_launchable_resources(
                    res, task.num_nodes)
                fuzzy_hints.extend(hints)
                for cand in feasible:
                    # One candidate per concrete region (zone picked at
                    # provision time by the failover loop).
                    regions = cloud.regions_with_offering(
                        cand.instance_type, cand.accelerators,
                        cand.use_spot, cand.region, cand.zone)
                    for region in regions:
                        launchable.append(cand.copy(region=region.name))
        launchable = [
            c for c in launchable
            if not Optimizer._is_blocked(c, blocked_resources)
        ]
        if not launchable:
            hint_msg = (f' Did you mean: {sorted(set(fuzzy_hints))}?'
                        if fuzzy_hints else '')
            raise exceptions.ResourcesUnavailableError(
                f'No launchable resource found for {task}.{hint_msg} '
                'Try other resource requirements or regions, or run '
                '`sky check`.')
        return launchable

    @staticmethod
    def _is_blocked(candidate: resources_lib.Resources,
                    blocked: List[resources_lib.Resources]) -> bool:
        return any(b.less_demanding_than(candidate) for b in blocked)

    @staticmethod
    def _estimate_candidates(
        task: 'task_lib.Task', minimize: OptimizeTarget,
        blocked_resources: List[resources_lib.Resources]
    ) -> List[Tuple[resources_lib.Resources, float, float]]:
        """[(resources, cost, est_time_seconds)] sorted by the target."""
        out = []
        for cand in Optimizer._fill_in_launchable_resources(
                task, blocked_resources):
            est_time = Optimizer._estimate_time_seconds(task, cand)
            # COST ranks over a UNIFORM runtime (task-declared or default):
            # the FLOPs proxy only scales TPU candidates, and a one-sided
            # discount would make cost ranking apples-to-oranges across
            # device families (parity: the reference prices hourly_cost ×
            # the task's declared runtime for every candidate).
            est = getattr(task, 'estimated_runtime', None)
            cost_basis = float(est) if est else _DEFAULT_RUNTIME_SECONDS
            cost = cand.get_cost(cost_basis) * task.num_nodes
            out.append((cand, cost, est_time))
        key = (lambda t: (t[1], t[2])) if minimize == OptimizeTarget.COST \
            else (lambda t: (t[2], t[1]))
        out.sort(key=key)
        return out

    @staticmethod
    def _estimate_time_seconds(task: 'task_lib.Task',
                               cand: resources_lib.Resources) -> float:
        est = getattr(task, 'estimated_runtime', None)
        if est:
            return float(est)
        topo = cand.tpu_topology
        if topo is not None:
            # FLOPs-proportional proxy: normalize to a v5e-8's peak so TIME
            # ranking prefers bigger/faster slices.
            baseline = 8 * 197.0
            return _DEFAULT_RUNTIME_SECONDS * baseline / \
                max(topo.peak_bf16_tflops, 1e-9)
        return _DEFAULT_RUNTIME_SECONDS

    # ------------------------------------------------------------------ DP

    @staticmethod
    def _egress_cost(src: Optional[resources_lib.Resources],
                     dst: resources_lib.Resources,
                     gigabytes: float = 0.0) -> float:
        if src is None or gigabytes <= 0:
            return 0.0
        if src.cloud is not None and src.cloud.is_same_cloud(dst.cloud):
            return 0.0
        return src.cloud.get_egress_cost(gigabytes)

    @staticmethod
    def _optimize_by_dp(
        dag: dag_lib.Dag, candidates, minimize: OptimizeTarget
    ) -> Dict['task_lib.Task', Tuple[resources_lib.Resources, float]]:
        """DP over the task chain (parity: optimizer.py:410)."""
        order = dag.get_sorted_tasks() if len(dag.tasks) > 1 else dag.tasks
        # dp[cand] = (total objective, chosen resources chain)
        prev_best: Dict[int, Tuple[float, list]] = {-1: (0.0, [])}
        prev_cands: List[Optional[resources_lib.Resources]] = [None]
        for task in order:
            cur: Dict[int, Tuple[float, list]] = {}
            for i, (cand, cost, est_time) in enumerate(candidates[task]):
                obj = cost if minimize == OptimizeTarget.COST else est_time
                best_val, best_chain = None, None
                for j, (val, chain) in prev_best.items():
                    src = prev_cands[j + 1] if j >= 0 else None
                    total = val + obj + Optimizer._egress_cost(
                        src, cand, gigabytes=0.0)
                    if best_val is None or total < best_val:
                        best_val = total
                        best_chain = chain + [(task, cand, cost)]
                cur[i] = (best_val, best_chain)
            prev_best = cur
            prev_cands = [None] + [c for c, _, _ in candidates[task]]
        _, chain = min(prev_best.values(), key=lambda v: v[0])
        return {task: (cand, cost) for task, cand, cost in chain}

    @staticmethod
    def _optimize_exhaustive(
        dag: dag_lib.Dag, candidates, minimize: OptimizeTarget
    ) -> Dict['task_lib.Task', Tuple[resources_lib.Resources, float]]:
        """Pick each task's best independently (egress handled pairwise).

        The reference solves general DAGs with ILP (optimizer.py:471); with
        our small candidate sets a per-task greedy choice plus pairwise
        egress is exact when egress is zero and near-exact otherwise.
        """
        plan = {}
        for task in dag.tasks:
            cand, cost, _ = candidates[task][0]
            plan[task] = (cand, cost)
        return plan

    # ---------------------------------------------------------------- print

    @staticmethod
    def print_plan(dag, plan, candidates, minimize) -> None:
        rows = []
        for task, (chosen, cost) in plan.items():
            n = task.num_nodes
            topo = chosen.tpu_topology
            infra = f'{chosen.cloud} ({chosen.region})'
            if topo is not None:
                acc = f'{topo.name}:{topo.num_chips} [{topo.topology_str}]'
            elif chosen.accelerators:
                name, cnt = next(iter(chosen.accelerators.items()))
                acc = f'{name}:{int(cnt)}'
            else:
                acc = '-'
            rows.append((task.name or '-', str(n), infra,
                         chosen.instance_type or '-', acc,
                         f'{chosen.get_hourly_cost() * n:.2f}'))
        header = ('TASK', 'NODES', 'INFRA', 'INSTANCE', 'ACCELERATORS',
                  '$/hr')
        widths = [
            max(len(header[i]), *(len(r[i]) for r in rows))
            for i in range(len(header))
        ]
        print(ux_utils.bold('Optimizer plan '
                            f'(minimizing {minimize.value}):'))
        print('  ' + '  '.join(h.ljust(widths[i])
                               for i, h in enumerate(header)))
        for r in rows:
            print('  ' + '  '.join(c.ljust(widths[i])
                                   for i, c in enumerate(r)))
