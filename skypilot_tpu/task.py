"""Declarative task: run/setup commands + resources + mounts + envs.

Parity: ``sky/task.py:196`` (Task), ``:436`` (from_yaml_config), ``:1214``
(to_yaml_config). Env-var substitution in YAML mirrors ``task.py:78``.
"""
import os
import re
from typing import Any, Callable, Dict, List, Optional, Set, Union

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import schemas

logger = sky_logging.init_logger(__name__)

_VAR_PATTERN = re.compile(r'\$\{(\w+)\}')

RunFn = Callable[[int, List[str]], Optional[str]]


def _typed(value: str):
    """Full-scalar substitutions keep YAML scalar typing (num_nodes:
    ${NODES} must become an int) without parsing the value as YAML — a
    value is only ever a scalar, never structure (no injection)."""
    for conv in (int, float):
        try:
            return conv(value)
        except ValueError:
            pass
    if value.lower() in ('true', 'false'):
        return value.lower() == 'true'
    return value


def _fill_env_vars(config, env_overrides: Dict[str, str]):
    """Substitute ${VAR} from overrides then os.environ in the PARSED
    config tree (parity task.py:78). Structure-level substitution: a
    value containing YAML metacharacters ('a: b', newlines) stays one
    string — it can never rewrite sibling fields.
    """

    def lookup(var: str):
        if var in env_overrides:
            return str(env_overrides[var])
        return os.environ.get(var)

    def subst_str(s: str) -> str:
        def repl(m):
            val = lookup(m.group(1))
            return val if val is not None else m.group(0)

        return _VAR_PATTERN.sub(repl, s)

    def walk(node):
        if isinstance(node, dict):
            # Keys substitute too (parameterized mount paths / env names);
            # keys stay strings — no scalar typing.
            return {
                (subst_str(k) if isinstance(k, str) else k): walk(v)
                for k, v in node.items()
            }
        if isinstance(node, list):
            return [walk(v) for v in node]
        if isinstance(node, str):
            full = _VAR_PATTERN.fullmatch(node.strip())
            if full:
                val = lookup(full.group(1))
                return _typed(val) if val is not None else node
            return subst_str(node)
        return node

    return walk(config)


class Task:
    """A coarse-grained unit of execution.

    Example::

        task = Task(name='train',
                    setup='pip list',
                    run='python -c "import jax; print(jax.devices())"',
                    num_nodes=1)
        task.set_resources(Resources(accelerators='tpu-v5e:8'))
    """

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: Optional[Union[str, RunFn]] = None,
        envs: Optional[Dict[str, str]] = None,
        secrets: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        file_mounts: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self._envs = dict(envs) if envs else {}
        self._secrets = dict(secrets) if secrets else {}
        self._num_nodes = 1
        if num_nodes is not None:
            self.num_nodes = num_nodes

        self.file_mounts: Optional[Dict[str, str]] = None
        self.storage_mounts: Dict[str, Any] = {}
        if file_mounts is not None:
            self.set_file_mounts(file_mounts)

        self.resources: Set[resources_lib.Resources] = {
            resources_lib.Resources()
        }
        self.service: Optional[Any] = None  # SkyServiceSpec
        # Filled at execution: estimated best resources from the optimizer.
        self.best_resources: Optional[resources_lib.Resources] = None
        # Declared data flow, used for egress-aware optimization (parity:
        # sky/task.py set_inputs/set_outputs + estimated sizes).
        self._inputs: Optional[str] = None
        self._outputs: Optional[str] = None
        self._estimated_inputs_size_gigabytes: Optional[float] = None
        self._estimated_outputs_size_gigabytes: Optional[float] = None
        # Declared runtime in seconds (parity: set_time_estimator).
        self.estimated_runtime: Optional[float] = None

        self._validate()

        # Auto-register into the active `with Dag():` context (parity:
        # sky/task.py Task.__init__ → dag.add).
        from skypilot_tpu import dag as dag_lib
        current_dag = dag_lib.get_current_dag()
        if current_dag is not None:
            current_dag.add(self)

    def _validate(self) -> None:
        if self.name is not None:
            common_utils.check_cluster_name_is_valid(self.name)
        if self.run is not None and not isinstance(self.run, str) and \
                not callable(self.run):
            raise exceptions.InvalidSkyError(
                f'run must be a string or callable, got {type(self.run)}')
        if self.workdir is not None:
            expanded = os.path.expanduser(self.workdir)
            if not os.path.isdir(expanded):
                raise exceptions.InvalidSkyError(
                    f'workdir {self.workdir!r} is not an existing directory.')

    # ----------------------------------------------------------- num_nodes

    @property
    def num_nodes(self) -> int:
        return self._num_nodes

    @num_nodes.setter
    def num_nodes(self, num_nodes: Optional[int]) -> None:
        if num_nodes is None:
            num_nodes = 1
        if not isinstance(num_nodes, int) or num_nodes < 1:
            raise exceptions.InvalidSkyError(
                f'num_nodes must be a positive int, got {num_nodes!r}')
        self._num_nodes = num_nodes

    # ----------------------------------------------------------- envs

    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    @property
    def secrets(self) -> Dict[str, str]:
        return dict(self._secrets)

    @property
    def envs_and_secrets(self) -> Dict[str, str]:
        out = dict(self._envs)
        out.update(self._secrets)
        return out

    def update_envs(self, envs: Optional[Dict[str, str]]) -> 'Task':
        for k, v in (envs or {}).items():
            if not isinstance(k, str) or not k:
                raise exceptions.InvalidSkyError(f'Invalid env key {k!r}')
            self._envs[k] = '' if v is None else str(v)
        return self

    def update_secrets(self, secrets: Optional[Dict[str, str]]) -> 'Task':
        for k, v in (secrets or {}).items():
            self._secrets[k] = '' if v is None else str(v)
        return self

    # ----------------------------------------------------------- resources

    def set_resources(
        self, resources: Union[resources_lib.Resources,
                               Set[resources_lib.Resources],
                               List[resources_lib.Resources]]
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = {resources}
        self.resources = set(resources)
        return self

    def set_resources_override(self, override: Dict[str, Any]) -> 'Task':
        self.set_resources({r.copy(**override) for r in self.resources})
        return self

    # ----------------------------------------------------------- mounts

    def set_file_mounts(self, file_mounts: Optional[Dict[str, Any]]) -> 'Task':
        """dst → src. Plain str srcs are files/dirs or cloud URIs; dict srcs

        are inline Storage specs (parsed by ``sync_storage_mounts``)."""
        if file_mounts is None:
            self.file_mounts = None
            return self
        plain: Dict[str, str] = {}
        for dst, src in file_mounts.items():
            if isinstance(src, dict):
                try:
                    from skypilot_tpu.data import storage as storage_lib
                except ImportError:
                    raise exceptions.NotSupportedError(
                        'Storage-spec file_mounts require the data '
                        'subsystem, which is not available in this build.'
                    ) from None
                self.storage_mounts[dst] = \
                    storage_lib.Storage.from_yaml_config(src)
            else:
                plain[dst] = str(src)
        self.file_mounts = plain or None
        return self

    def update_file_mounts(self, file_mounts: Dict[str, str]) -> 'Task':
        if self.file_mounts is None:
            self.file_mounts = {}
        self.file_mounts.update(file_mounts)
        return self

    def sync_storage_mounts(self) -> 'Task':
        """Create + upload every storage mount's bucket(s).

        Parity: sky/task.py:1028 — run before file mounts are executed so
        the on-cluster mount commands have a live bucket to point at.
        """
        for storage in self.storage_mounts.values():
            storage.sync_all_stores()
        return self

    # ----------------------------------------------------------- service

    # -------------------------------------------------------- data flow

    def set_inputs(self, inputs: str,
                   estimated_size_gigabytes: float) -> 'Task':
        """Parity: sky/task.py set_inputs."""
        self._inputs = inputs
        self._estimated_inputs_size_gigabytes = float(
            estimated_size_gigabytes)
        return self

    def set_outputs(self, outputs: str,
                    estimated_size_gigabytes: float) -> 'Task':
        """Parity: sky/task.py set_outputs."""
        self._outputs = outputs
        self._estimated_outputs_size_gigabytes = float(
            estimated_size_gigabytes)
        return self

    @property
    def inputs(self) -> Optional[str]:
        return self._inputs

    @property
    def outputs(self) -> Optional[str]:
        return self._outputs

    @property
    def estimated_inputs_size_gigabytes(self) -> Optional[float]:
        return self._estimated_inputs_size_gigabytes

    @property
    def estimated_outputs_size_gigabytes(self) -> Optional[float]:
        return self._estimated_outputs_size_gigabytes

    def get_inputs_cloud(self):
        """Cloud the inputs live on, inferred from the path scheme
        (parity: sky/task.py get_inputs_cloud)."""
        from skypilot_tpu.utils.registry import CLOUD_REGISTRY
        if self._inputs is None:
            return None
        if self._inputs.startswith('gs://'):
            return CLOUD_REGISTRY.from_str('gcp')
        if self._inputs.startswith('s3://'):
            return CLOUD_REGISTRY.from_str('aws')
        if self._inputs.startswith('azure://'):
            return CLOUD_REGISTRY.from_str('azure')
        # r2://: Cloudflare egress is free and R2 is not a compute cloud
        # here — no egress attribution.
        return None

    def set_service(self, service) -> 'Task':
        self.service = service
        return self

    # ----------------------------------------------------------- (de)ser

    @classmethod
    def from_yaml_config(cls,
                         config: Dict[str, Any],
                         env_overrides: Optional[Dict[str, str]] = None
                         ) -> 'Task':
        if env_overrides:
            config = _fill_env_vars(config, env_overrides)
        schemas.validate(config, schemas.get_task_schema(),
                         'Invalid task spec: ')
        config = dict(config)
        envs = config.get('envs') or {}
        envs = {k: ('' if v is None else str(v)) for k, v in envs.items()}
        secrets = config.get('secrets') or {}
        secrets = {k: ('' if v is None else str(v))
                   for k, v in secrets.items()}

        task = cls(
            name=config.get('name'),
            setup=config.get('setup'),
            run=config.get('run'),
            workdir=config.get('workdir'),
            num_nodes=config.get('num_nodes'),
            envs=envs,
        )
        task.update_secrets(secrets)
        if config.get('file_mounts') is not None:
            task.set_file_mounts(config['file_mounts'])
        if config.get('resources') is not None:
            res_config = config['resources']
            alternatives = res_config.get('any_of') or \
                res_config.get('ordered')
            if alternatives:
                # any_of: the optimizer may pick any alternative; each
                # entry inherits the outer keys (parity: resources_utils
                # parse_resources any_of handling).
                base = {
                    k: v for k, v in res_config.items()
                    if k not in ('any_of', 'ordered')
                }
                task.set_resources({
                    resources_lib.Resources.from_yaml_config({**base, **alt})
                    for alt in alternatives
                })
            else:
                task.set_resources(
                    resources_lib.Resources.from_yaml_config(res_config))
        # inputs/outputs: {<path>: <estimated_size_gigabytes>} (parity:
        # the reference task YAML data-flow declaration).
        for key, setter in (('inputs', task.set_inputs),
                            ('outputs', task.set_outputs)):
            spec = config.get(key)
            if spec:
                if len(spec) != 1:
                    raise exceptions.InvalidSkyError(
                        f'{key}: exactly one path entry is supported, '
                        f'got {len(spec)}: {sorted(spec)}')
                path, size = next(iter(spec.items()))
                if not isinstance(size, (int, float)):
                    raise exceptions.InvalidSkyError(
                        f'{key}: {path!r} needs a numeric estimated size '
                        f'in gigabytes, got {size!r}')
                setter(path, float(size))
        if config.get('estimated_runtime') is not None:
            task.estimated_runtime = float(config['estimated_runtime'])
        if config.get('service') is not None:
            try:
                from skypilot_tpu.serve import service_spec
            except ImportError:
                raise exceptions.NotSupportedError(
                    'service: sections require the serve subsystem, which '
                    'is not available in this build.') from None
            task.set_service(
                service_spec.SkyServiceSpec.from_yaml_config(
                    config['service']))
        return task

    @classmethod
    def from_yaml(cls,
                  yaml_path: str,
                  env_overrides: Optional[Dict[str, str]] = None) -> 'Task':
        config = common_utils.read_yaml(yaml_path)
        if not isinstance(config, dict):
            raise exceptions.InvalidSkyError(
                f'{yaml_path} does not contain a task mapping.')
        return cls.from_yaml_config(config, env_overrides)

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key, value):
            if value is not None and value != {} and value != []:
                config[key] = value

        add('name', self.name)
        if len(self.resources) == 1:
            add('resources', next(iter(self.resources)).to_yaml_config())
        elif len(self.resources) > 1:
            add('resources',
                {'any_of': [r.to_yaml_config() for r in self.resources]})
        if self._num_nodes != 1:
            add('num_nodes', self._num_nodes)
        add('workdir', self.workdir)
        add('setup', self.setup)
        add('run', self.run if isinstance(self.run, str) else None)
        add('envs', self._envs or None)
        add('secrets', self._secrets or None)
        mounts: Dict[str, Any] = dict(self.file_mounts or {})
        for dst, store in self.storage_mounts.items():
            mounts[dst] = store.to_yaml_config()
        add('file_mounts', mounts or None)
        if self._inputs is not None:
            add('inputs',
                {self._inputs: self._estimated_inputs_size_gigabytes})
        if self._outputs is not None:
            add('outputs',
                {self._outputs: self._estimated_outputs_size_gigabytes})
        add('estimated_runtime', self.estimated_runtime)
        if self.service is not None:
            add('service', self.service.to_yaml_config())
        return config

    def __repr__(self) -> str:
        label = self.name or '<unnamed>'
        res = next(iter(self.resources)) if self.resources else None
        return f'Task({label}, num_nodes={self._num_nodes}, {res})'
