"""Int8 quantized matmul for TPU inference (weight + dynamic activation).

TPU-native serving lever: the v5e/v6e MXU runs int8×int8→int32 at ~2× the
bf16 rate, and int8 weights halve the HBM traffic that bounds decode. The
scheme is symmetric per-channel (AQT-style, the approach of public JAX
quantization libraries):

* weights: per-OUTPUT-channel scale, quantized once offline
  (:func:`quantize_int8`);
* activations: per-ROW (token) scale computed dynamically at each call —
  ``x_int8 = round(x / scale_x)`` — so no calibration pass is needed;
* ``y = (x_int8 @ w_int8) * scale_x * scale_w`` accumulated in int32
  (``preferred_element_type``), rescaled to the requested dtype.

No reference counterpart: SkyPilot delegates serving kernels to vLLM;
here the model is in-tree, so the quantization op is too.
"""
import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_INT8_MAX = 127.0


@dataclasses.dataclass(frozen=True)
class QuantizedTensor:
    """int8 values + fp32 scale broadcastable against the matmul output."""
    values: jax.Array   # int8
    scale: jax.Array    # f32, shape broadcastable to the output

    @property
    def shape(self):
        return self.values.shape


def _symmetric_quantize(x: jax.Array,
                        axis: int) -> Tuple[jax.Array, jax.Array]:
    """One copy of the scheme's numerics (amax → floored scale →
    round/clip) shared by weight and activation quantization."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / _INT8_MAX
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, scale


def quantize_int8(w: jax.Array, axis: int = 0) -> QuantizedTensor:
    """Symmetric per-channel quantization of a weight matrix.

    ``axis`` is the CONTRACTION axis (reduced in the matmul); the scale is
    computed per remaining (output) channel so each output column keeps
    its own dynamic range.
    """
    q, scale = _symmetric_quantize(w, axis)
    return QuantizedTensor(values=q, scale=scale)


def _quantize_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Dynamic per-row activation quantization: [..., K] → int8 + scale."""
    return _symmetric_quantize(x, -1)


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Int8-quantize K/V cache entries over the head_dim axis.

    [..., Hkv, hd] → (int8 values [..., Hkv, hd], fp32 scales [..., Hkv])
    — one scale per (position, kv-head), the granularity the decode
    kernel dequantizes at (``ops/decode_attention.py``). Halves KV-cache
    HBM traffic, the bandwidth bound of the decode step.
    """
    q, scale = _symmetric_quantize(x, -1)
    return q, scale[..., 0]


def int8_matmul(x: jax.Array, qw: QuantizedTensor,
                out_dtype: Optional[jnp.dtype] = None) -> jax.Array:
    """``x @ w`` with both operands int8 on the MXU.

    x: [..., K] float; qw: quantized [K, N]. Returns [..., N] in
    ``out_dtype`` (default: x.dtype).
    """
    out_dtype = out_dtype or x.dtype
    assert qw.values.ndim == 2, (
        'int8_matmul takes a 2-D quantized weight; stacked [L, K, N] '
        'tensors (decode.quantize_params) are valid only after lax.scan '
        f'slices the layer axis. Got shape {qw.values.shape}.')
    xq, sx = _quantize_rows(x)
    acc = jax.lax.dot_general(
        xq, qw.values,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * sx * qw.scale.reshape(
        (1,) * (acc.ndim - 1) + (-1,))
    return y.astype(out_dtype)


# QuantizedTensor flows through jit/scan as a pytree (values + scale
# leaves); the frozen dataclass itself is static-safe metadata-free.
jax.tree_util.register_pytree_node(
    QuantizedTensor,
    lambda qt: ((qt.values, qt.scale), None),
    lambda _, leaves: QuantizedTensor(values=leaves[0], scale=leaves[1]))
