"""Flash attention for TPU, written in Pallas.

TPU-native replacement for the dense attention path when sequences are
long: the [S, S] logits matrix never materializes in HBM — each Q block
streams K/V blocks through VMEM with an online-softmax accumulator (the
same recurrence ``parallel/ring.py`` uses across chips, here across VMEM
blocks within a chip). Causal blocks that are fully masked are skipped.

Forward is the Pallas kernel; backward (for training) recomputes through
the XLA path via ``jax.custom_vjp`` — correct gradients everywhere, with
the kernel's memory win applying to inference/prefill and to the remat'd
forward. Falls back to the XLA path off-TPU (tests run the kernel in
interpreter mode to check numerics).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from skypilot_tpu.ops import attention as attention_ops

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_len: int,
                  causal: bool, scale: float):
    """One (batch·head, q-block) program: stream K/V blocks, fold online.

    q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq_len, d]; o_ref like q_ref.
    """
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
    q_start = qi * block_q
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # K blocks beyond this q block's last row are fully masked.
        num_k_blocks = pl.cdiv(q_start + block_q, block_k)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        if causal:
            kv_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            mask = q_pos >= kv_pos
            logits = jnp.where(mask, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1, keepdims=True)    # [bq, 1]
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(logits - safe_m)
        if causal:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]
        return m_new, l_new, acc * correction + pv

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   block_q: int, block_k: int,
                   interpret: bool) -> jax.Array:
    """q [B,S,H,D], k/v [B,S,Hkv,D] → [B,S,H,D]."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    n_rep = h // hkv
    # [B,S,H,D] → [B*H, S, D]; KV heads indexed via the block index map so
    # GQA fan-out never materializes.
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)

    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b * h, s // block_q)

    def q_index(bh, qi):
        return (bh, qi, 0)

    def kv_index(bh, qi):
        del qi
        # bh indexes [B*H]; its KV row is (batch, kv_head) flattened.
        return ((bh // h) * hkv + (bh % h) // n_rep, 0, 0)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               seq_len=s, causal=causal,
                               scale=d**-0.5)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, s, d), kv_index),
            pl.BlockSpec((1, s, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_index),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Drop-in for ``attention_ops.gqa_attention`` on full sequences.

    Shapes must tile: S divisible by the (clamped) block sizes. Off-TPU
    the XLA path runs instead unless ``interpret=True`` forces the kernel
    through the Pallas interpreter (tests).
    """
    interpret = _resolve_interpret(interpret)
    s = q.shape[1]
    bq, bk = min(block_q, s), min(block_k, s)
    if interpret is None or s % bq or s % bk:
        # Off-TPU, or S does not tile: the XLA path is exact and safe
        # (an untiled grid would silently leave output rows unwritten).
        return attention_ops.gqa_attention(q, k, v, causal=causal)
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _resolve_interpret(interpret: Optional[bool]) -> Optional[bool]:
    """True → interpreter, False → compiled kernel, None → XLA fallback."""
    if interpret is True:
        return True
    if interpret is False:
        return False
    return False if jax.default_backend() == 'tpu' else None


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    # Recompute through the XLA path for gradients: exact, lets remat'd
    # forwards still use the kernel. (A full Pallas backward is a later
    # optimization; the bench tracks whether it pays.)
    del block_q, block_k, interpret
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_ops.gqa_attention(
            q_, k_, v_, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
