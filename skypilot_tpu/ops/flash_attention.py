"""Flash attention for TPU, written in Pallas — forward AND backward.

TPU-native replacement for the dense attention path when sequences are
long: the [S, S] logits matrix never materializes in HBM — each Q block
streams K/V blocks through VMEM with an online-softmax accumulator (the
same recurrence ``parallel/ring.py`` uses across chips, here across VMEM
blocks within a chip). Causal blocks that are fully masked are skipped.

Backward is the FlashAttention-2 recurrence: the forward additionally
emits the per-row log-normalizer L = m + log(l); backward recomputes
P = exp(scale·qkᵀ − L) block-by-block in VMEM and accumulates
dq (one kernel, gridded over Q blocks) and dk/dv (a second kernel,
gridded over K blocks) — so the [S,S] probabilities are never written to
HBM in either direction. Falls back to the XLA path off-TPU (tests run
the kernels in interpreter mode to check numerics).
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from skypilot_tpu.ops import attention as attention_ops

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256

# Minor-dim padding for the per-row L/D vectors: TPU block specs need the
# last two block dims (8,128)-tiled or equal to the array's, so row
# vectors ride as [rows, LANE_PAD] with the value replicated across lanes.
LANE_PAD = 8


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, *, block_k: int,
                  seq_len: int, causal: bool, scale: float):
    """One (batch·head, q-block) program: stream K/V blocks, fold online.

    q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq_len, d]; o_ref like q_ref;
    l_ref: [1, block_q, LANE_PAD] log-normalizers (m + log l) for the
    backward pass, replicated across the LANE_PAD minor dim — a bare
    [1, block_q] block is illegal on TPU (the last two block dims must be
    (8,128)-tiled or match the array), so the row vector is carried with a
    small padded lane dim instead.
    """
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
    q_start = qi * block_q
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        # K blocks beyond this q block's last row are fully masked.
        num_k_blocks = pl.cdiv(q_start + block_q, block_k)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        if causal:
            kv_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            mask = q_pos >= kv_pos
            logits = jnp.where(mask, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1, keepdims=True)    # [bq, 1]
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(logits - safe_m)
        if causal:
            p = jnp.where(mask, p, 0.0)
        correction = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]
        return m_new, l_new, acc * correction + pv

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)
    o_ref[0] = out.astype(o_ref.dtype)
    # Log-normalizer per row (finite for causal: row i always sees col i).
    lse_col = m + jnp.log(jnp.maximum(l, 1e-20))       # [bq, 1]
    l_ref[0] = jnp.broadcast_to(lse_col, (block_q, LANE_PAD))


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   block_q: int, block_k: int,
                   interpret: bool) -> Tuple[jax.Array, jax.Array]:
    """q [B,S,H,D], k/v [B,S,Hkv,D] → (out [B,S,H,D], L [B*H,S])."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    n_rep = h // hkv
    # [B,S,H,D] → [B*H, S, D]; KV heads indexed via the block index map so
    # GQA fan-out never materializes.
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)

    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b * h, s // block_q)

    def q_index(bh, qi):
        return (bh, qi, 0)

    def kv_index(bh, qi):
        del qi
        # bh indexes [B*H]; its KV row is (batch, kv_head) flattened.
        return ((bh // h) * hkv + (bh % h) // n_rep, 0, 0)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               seq_len=s, causal=causal,
                               scale=d**-0.5)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, s, d), kv_index),
            pl.BlockSpec((1, s, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, LANE_PAD), q_index),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, LANE_PAD), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3), lse[:, :, 0]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, dsum_ref,
                         dq_ref, *, block_k: int, seq_len: int, causal: bool,
                         scale: float):
    """dq for one (batch·head, q-block): stream K/V blocks, recompute P.

    FlashAttention-2 backward, dq pass: P = exp(scale·qkᵀ − L);
    dS = P ⊙ (dO·Vᵀ − D); dq = scale · Σⱼ dSⱼ Kⱼ.
    """
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)                   # [bq, d]
    do = do_ref[0].astype(jnp.float32)                 # [bq, d]
    lse = l_ref[0][:, 0:1]                             # [bq, 1]
    dsum = dsum_ref[0][:, 0:1]                         # [bq, 1]
    q_start = qi * block_q
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    num_k_blocks = pl.cdiv(seq_len, block_k)
    if causal:
        num_k_blocks = pl.cdiv(q_start + block_q, block_k)

    def body(j, acc):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        logits = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        p = jnp.exp(logits - lse)
        if causal:
            kv_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            p = jnp.where(q_pos >= kv_pos, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds_ = p * (dp - dsum)
        return acc + jax.lax.dot_general(
            ds_, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    dq = scale * jax.lax.fori_loop(0, num_k_blocks, body, acc0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, l_ref, dsum_ref,
                          dk_ref, dv_ref, *, block_q: int, seq_len: int,
                          causal: bool, scale: float):
    """dk/dv for one (batch·head, k-block): stream Q/dO blocks.

    dV = Σᵢ Pᵢᵀ dOᵢ;  dK = scale · Σᵢ dSᵢᵀ Qᵢ.
    """
    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    k = k_ref[0].astype(jnp.float32)                   # [bk, d]
    v = v_ref[0].astype(jnp.float32)                   # [bk, d]
    k_start = ki * block_k
    kv_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)

    num_q_blocks = pl.cdiv(seq_len, block_q)
    i0 = 0
    if causal:
        i0 = k_start // block_q  # q blocks strictly above the diag skip

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        lse = l_ref[0, pl.ds(i * block_q, block_q), 0:1]
        dsum = dsum_ref[0, pl.ds(i * block_q, block_q), 0:1]
        logits = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        p = jnp.exp(logits - lse)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            p = jnp.where(q_pos >= kv_pos, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds_ = p * (dp - dsum)
        dk = dk + jax.lax.dot_general(
            ds_, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(i0, num_q_blocks, body, (dk0, dv0))
    dk_ref[0] = (scale * dk).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k,
                    interpret):
    """Pallas backward: returns (dq, dk, dv) in [B,S,H,D]/[B,S,Hkv,D]."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    n_rep = h // hkv
    bq, bk = min(block_q, s), min(block_k, s)

    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    # GQA fan-out for the dkv pass: each (batch, q-head) program needs its
    # kv row writable, so expand here ([B*Hkv,S,D] → [B*H,S,D], 33 MB at
    # bench shapes) and sum-reduce the reps afterwards.
    kt = jnp.repeat(k.transpose(0, 2, 1, 3), n_rep,
                    axis=1).reshape(b * h, s, d)
    vt = jnp.repeat(v.transpose(0, 2, 1, 3), n_rep,
                    axis=1).reshape(b * h, s, d)
    dot = g.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    # D = rowsum(dO ⊙ O), fp32 (cheap elementwise; XLA fuses it).
    dsum = jnp.sum(dot.astype(jnp.float32) *
                   out.transpose(0, 2, 1, 3).reshape(b * h, s, d).astype(
                       jnp.float32), axis=-1)          # [B*H, S]
    # Lane-replicated layouts for the row vectors (see LANE_PAD).
    lse3 = jnp.broadcast_to(lse[:, :, None], (b * h, s, LANE_PAD))
    dsum3 = jnp.broadcast_to(dsum[:, :, None], (b * h, s, LANE_PAD))

    def blk3(bh, i):
        return (bh, i, 0)

    def row(bh, i):
        del i
        return (bh, 0, 0)

    scale = d**-0.5
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=bk, seq_len=s,
                          causal=causal, scale=scale),
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), blk3),     # q
            pl.BlockSpec((1, s, d), row),       # k (full row)
            pl.BlockSpec((1, s, d), row),       # v
            pl.BlockSpec((1, bq, d), blk3),     # dO
            pl.BlockSpec((1, bq, LANE_PAD), blk3),          # L
            pl.BlockSpec((1, bq, LANE_PAD), blk3),          # D
        ],
        out_specs=pl.BlockSpec((1, bq, d), blk3),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt, dot, lse3, dsum3)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=bq, seq_len=s,
                          causal=causal, scale=scale),
        grid=(b * h, s // bk),
        in_specs=[
            pl.BlockSpec((1, bk, d), blk3),     # k block
            pl.BlockSpec((1, bk, d), blk3),     # v block
            pl.BlockSpec((1, s, d), row),       # q (full row)
            pl.BlockSpec((1, s, d), row),       # dO
            pl.BlockSpec((1, s, LANE_PAD), row),            # L
            pl.BlockSpec((1, s, LANE_PAD), row),            # D
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), blk3),
            pl.BlockSpec((1, bk, d), blk3),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        ],
        interpret=interpret,
    )(kt, vt, qt, dot, lse3, dsum3)

    dq = dq.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    # Reduce the GQA reps back to kv heads.
    dk = dk.reshape(b, hkv, n_rep, s, d).sum(axis=2).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, hkv, n_rep, s, d).sum(axis=2).transpose(0, 2, 1, 3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Drop-in for ``attention_ops.gqa_attention`` on full sequences.

    Shapes must tile: S divisible by the (clamped) block sizes. Off-TPU
    the XLA path runs instead unless ``interpret=True`` forces the kernel
    through the Pallas interpreter (tests).
    """
    # Single source of truth for the kernel-vs-XLA predicate: _fwd. The
    # primal and the vjp pairing can then never disagree.
    return _fwd(q, k, v, causal, block_q, block_k, interpret)[0]


def _resolve_interpret(interpret: Optional[bool]) -> Optional[bool]:
    """True → interpreter, False → compiled kernel, None → XLA fallback."""
    if interpret is True:
        return True
    if interpret is False:
        return False
    return False if jax.default_backend() == 'tpu' else None


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    itp = _resolve_interpret(interpret)
    s = q.shape[1]
    bq, bk = min(block_q, s), min(block_k, s)
    if itp is None or s % bq or s % bk:
        out = attention_ops.gqa_attention(q, k, v, causal=causal)
        return out, (q, k, v, None, None)
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, itp)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if out is None:
        # Fallback pairing: gradients through the XLA path.
        _, vjp = jax.vjp(
            lambda q_, k_, v_: attention_ops.gqa_attention(
                q_, k_, v_, causal=causal), q, k, v)
        return vjp(g)
    itp = _resolve_interpret(interpret)
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k,
                           itp)


flash_attention.defvjp(_fwd, _bwd)
