"""Attention ops: GQA causal attention (XLA path) + ring attention hook.

The XLA path is written so the compiler fuses mask+softmax into the two
matmuls and keeps everything on the MXU in bf16 with fp32 accumulation.
Ring attention (sequence-parallel long context) lives in
``skypilot_tpu.parallel.ring`` and is dispatched here when a 'seq' mesh
axis is active.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] → [B, S, Hkv*n_rep, D] (GQA key/value head fan-out)."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :],
                            (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def gqa_attention(q: jax.Array,
                  k: jax.Array,
                  v: jax.Array,
                  causal: bool = True,
                  q_offset: int = 0) -> jax.Array:
    """q [B,S,H,D], k/v [B,Skv,Hkv,D] → [B,S,H,D].

    bf16 in/out; softmax in fp32. `q_offset` supports decode (q positions
    start at q_offset within the kv sequence).
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    scale = d**-0.5
    # Grouped contraction: fold the GQA fan-out into the einsum instead
    # of materializing repeat_kv-expanded K/V (H/Hkv x the HBM traffic).
    # Query head kv*g + r rides in group slot (kv, r) — repeat_kv order.
    qg = q.reshape(b, s, hkv, g, d)
    # [B, Hkv, G, S, Skv]
    logits = jnp.einsum('bskgd,btkd->bkgst', qg, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        skv = k.shape[1]
        q_pos = jnp.arange(s) + q_offset
        kv_pos = jnp.arange(skv)
        mask = q_pos[:, None] >= kv_pos[None, :]
        logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s, h, d).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str) -> jax.Array:
    """Dispatch to the sequence-parallel ring implementation.

    Must be called inside a ``shard_map`` over ``axis_name`` (see
    ``skypilot_tpu.parallel.ring``)."""
    from skypilot_tpu.parallel import ring
    return ring.ring_attention_inner(q, k, v, axis_name=axis_name)
