"""TPU compute ops: attention (XLA + Pallas paths), collective benches."""
