"""Flash-decode attention for the KV-cache serving path, in Pallas.

Decode is HBM-bandwidth bound: every step reads the whole KV cache to
produce one token per sequence. The XLA path pays for that twice — it
reads the *padded* cache (``max_len`` positions regardless of how many
are live) and, with GQA, used to materialize ``repeat_kv``-expanded K/V
(``H/Hkv``x the traffic). This kernel fixes both:

* **Grid (batch, kv-blocks)**: each program streams one ``block_k`` slab
  of one sequence's cache through VMEM. All ``Hkv`` kv heads ride in the
  slab (``[block_k, Hkv, hd]`` is contiguous in the cache layout), so
  the cache is read exactly once per step — not once per query head.
* **GQA inside the kernel**: all ``H/Hkv`` query heads of each kv head
  attend against the slab in one pass (one ``[G, block_k]`` logits tile
  per kv head); the expanded K/V never exist.
* **Online softmax** across kv blocks (the recurrence of
  ``ops/flash_attention.py``, here over cache blocks): running max ``m``,
  normalizer ``l`` and weighted-value accumulator live in VMEM scratch
  that persists across the sequential kv-block grid dimension.
* **Block skipping**: ``cur_len`` rides in as a scalar-prefetch operand,
  so the BlockSpec index map clamps past-the-end block indices to the
  last live block. Pallas only issues a DMA when the block index
  *changes*, so fully-dead blocks are never read from HBM and per-token
  cost tracks the actual sequence length, not ``max_len``. Compute for
  those iterations is predicated off with ``pl.when``.
* **int8 KV (optional)**: the cache may be stored int8 with per
  (position, kv-head) fp32 scales (``ops/quant.py`` numerics), halving
  cache bandwidth; dequantization happens in-kernel after the DMA.

* **Paged KV (block tables)**: the cache may live in a *global block
  pool* ``[n_blocks, block_k, Hkv, hd]`` instead of one dense
  ``[B, max_len, ...]`` lane per sequence; each sequence then owns an
  int32 ``block_tables[B, max_blocks]`` row naming its blocks in order.
  The paged kernel generalizes the ``cur_len`` clamping above: the
  scalar-prefetched block table rides next to ``cur_len``, and the
  BlockSpec index map *indirects* through it — grid step ``j`` of batch
  row ``bi`` DMAs pool block ``block_tables[bi, j]``. Dead entries past
  ``cur_len`` clamp to the last live table slot exactly like the dense
  kernel, so fully-dead blocks are still never read. This is what lets
  the serving engine share prompt-prefix blocks across sequences
  (``models/engine.py``'s radix prefix cache): two rows whose tables
  name the same block read the same HBM, copy-free.

* **Multi-query verify (speculative decoding)**: scoring ``spec_k``
  drafted tokens against the full model is a q-length > 1 decode over
  the same pool — query ``i`` sits at position ``start + i`` and masks
  by its own causal length. ``paged_verify_attention`` widens the paged
  kernel's per-head online-softmax rows to ``H * s_q`` (rows stay
  grouped per kv head so the MXU tiles are unchanged); a q-length-1
  verify is numerically the single-token decode step, which is what
  pins greedy speculative output token-identical to the plain paged
  path.

Off-TPU the grouped-einsum XLA path below runs instead (tests force the
kernel through the Pallas interpreter to check numerics on CPU); its
paged variant gathers pool blocks through the table first.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

# Serving tensor parallelism: the named mesh axis the KV-head dim
# shards over (== parallel/mesh.MODEL_AXIS; a string literal keeps this
# module free of a parallel/ import at module load).
TP_AXIS = 'model'

# Default KV block: small enough that skipping tracks cur_len closely at
# serving lengths (prompt 128 + 128 new = 2 blocks), large enough that
# the [G, block_k] logits tiles keep the MXU's N dim full.
DEFAULT_BLOCK_K = 128


def _decode_kernel(cur_ref, q_ref, k_ref, v_ref, *rest, block_k: int,
                   n_blocks: int, n_kv_heads: int, scale: float,
                   quantized: bool):
    """One (batch, kv-block) program.

    cur_ref: scalar-prefetch [B] int32 live lengths; q_ref [1, H, hd];
    k_ref/v_ref [1, block_k, Hkv, hd] (bf16/f32, or int8 when
    ``quantized`` with ks_ref/vs_ref [1, block_k, Hkv] fp32 scales);
    o_ref like q_ref; m/l scratch [H, 128] (lane-replicated row
    vectors — a bare [H] vector is not a legal TPU vreg shape), acc
    scratch [H, hd]. Scratch carries the online-softmax state across the
    sequential kv-block grid dimension.
    """
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    bi = pl.program_id(0)
    j = pl.program_id(1)
    h = q_ref.shape[1]
    groups = h // n_kv_heads
    cur = cur_ref[bi]
    live_blocks = pl.cdiv(cur, block_k)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(j < live_blocks)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale            # [H, hd]
        k_blk = k_ref[0]                                    # [bk, Hkv, hd]
        v_blk = v_ref[0]
        if quantized:
            k_blk = k_blk.astype(jnp.float32) * ks_ref[0][:, :, None]
            v_blk = v_blk.astype(jnp.float32) * vs_ref[0][:, :, None]
        else:
            k_blk = k_blk.astype(jnp.float32)
            v_blk = v_blk.astype(jnp.float32)

        # GQA in one pass: each kv head's G query heads hit its K slab;
        # the tiles stack back to [H, block_k] (head order kv*G + r —
        # the repeat_kv fan-out order).
        logits = jnp.concatenate([
            jax.lax.dot_general(
                q[g * groups:(g + 1) * groups], k_blk[:, g, :],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            for g in range(n_kv_heads)
        ], axis=0)                                          # [H, bk]
        pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = pos < cur                                    # [1, bk]
        logits = jnp.where(mask, logits, NEG_INF)

        m = m_scr[:, 0:1]                                   # [H, 1]
        l = l_scr[:, 0:1]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.where(mask, jnp.exp(logits - safe_m), 0.0)  # [H, bk]
        correction = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        pv = jnp.concatenate([
            jax.lax.dot_general(
                p[g * groups:(g + 1) * groups], v_blk[:, g, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            for g in range(n_kv_heads)
        ], axis=0)                                          # [H, hd]
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(
            l * correction + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape)
        acc_scr[...] = acc_scr[...] * correction + pv

    @pl.when(j == n_blocks - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        o_ref[0] = (acc_scr[...] /
                    jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def decode_attention_kernel(q: jax.Array, k_cache: jax.Array,
                            v_cache: jax.Array, cur_len: jax.Array,
                            k_scale: Optional[jax.Array] = None,
                            v_scale: Optional[jax.Array] = None,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: bool = False) -> jax.Array:
    """q [B,1,H,hd] vs cache [B,max_len,Hkv,hd] → [B,1,H,hd].

    ``cur_len`` [B] int32: live positions per sequence (positions >=
    cur_len are dead — never read, courtesy of the clamped index map).
    ``k_scale``/``v_scale`` [B,max_len,Hkv] fp32 mark an int8 cache.
    """
    b, s_q, h, hd = q.shape
    assert s_q == 1, q.shape
    _, max_len, hkv, _ = k_cache.shape
    block_k = min(block_k, max_len)
    assert max_len % block_k == 0, (max_len, block_k)
    n_blocks = max_len // block_k
    quantized = k_scale is not None

    def q_index(bi, j, cur_ref):
        del j, cur_ref
        return (bi, 0, 0)

    def _clamp(bi, j, cur_ref):
        # Past-the-end blocks re-map to the last live block: an unchanged
        # block index means Pallas skips the DMA, so dead cache is never
        # read. max(live-1, 0) guards cur_len == 0 rows.
        live = pl.cdiv(cur_ref[bi], block_k)
        return jnp.minimum(j, jnp.maximum(live - 1, 0))

    def kv_index(bi, j, cur_ref):
        return (bi, _clamp(bi, j, cur_ref), 0, 0)

    def scale_index(bi, j, cur_ref):
        return (bi, _clamp(bi, j, cur_ref), 0)

    in_specs = [
        pl.BlockSpec((1, h, hd), q_index),
        pl.BlockSpec((1, block_k, hkv, hd), kv_index),
        pl.BlockSpec((1, block_k, hkv, hd), kv_index),
    ]
    operands = [q[:, 0], k_cache, v_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_k, hkv), scale_index),
            pl.BlockSpec((1, block_k, hkv), scale_index),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),   # m (lane-replicated)
            pltpu.VMEM((h, 128), jnp.float32),   # l
            pltpu.VMEM((h, hd), jnp.float32),    # acc
        ],
    )
    kernel = functools.partial(
        _decode_kernel, block_k=block_k, n_blocks=n_blocks,
        n_kv_heads=hkv, scale=hd**-0.5, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, hd), q.dtype),
        interpret=interpret,
    )(cur_len.astype(jnp.int32), *operands)
    return out[:, None]


def decode_attention_xla(q: jax.Array, k_cache: jax.Array,
                         v_cache: jax.Array, cur_len: jax.Array,
                         k_scale: Optional[jax.Array] = None,
                         v_scale: Optional[jax.Array] = None) -> jax.Array:
    """Grouped-einsum reference/fallback (CPU and odd shapes).

    Same contract as :func:`decode_attention_kernel`. GQA is a grouped
    contraction over [B,S,Hkv,G,hd] — the ``repeat_kv``-expanded K/V are
    never materialized, so even the fallback reads the cache once.
    """
    b, s, h, hd = q.shape
    hkv = k_cache.shape[2]
    g = h // hkv
    k, v = k_cache, v_cache
    if k_scale is not None:
        k = (k.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
        v = (v.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
    qg = q.reshape(b, s, hkv, g, hd)
    logits = jnp.einsum('bskgd,btkd->bkgst', qg, k,
                        preferred_element_type=jnp.float32) * hd**-0.5
    mask = jnp.arange(k.shape[1])[None, :] < cur_len[:, None]  # [B, T]
    logits = jnp.where(mask[:, None, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    # A fully-dead row (cur_len == 0) softmaxes to uniform over garbage;
    # re-masking the probs zeroes it (live rows are untouched: their
    # masked probs already underflowed to exactly 0), matching the
    # kernel's zero output for empty rows.
    probs = jnp.where(mask[:, None, None, None, :], probs, 0)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s, h, hd).astype(q.dtype)


# ------------------------------------------------------------------ paged


def _paged_decode_kernel(cur_ref, bt_ref, *args, **kwargs):
    """Paged program body == dense body: the block table only changes
    *which* pool block each grid step DMAs (the index maps below); the
    online-softmax accumulation over the delivered ``[block_k]`` slab is
    identical, so the dense kernel is reused verbatim."""
    del bt_ref  # consumed by the BlockSpec index maps, not the body
    return _decode_kernel(cur_ref, *args, **kwargs)


def paged_decode_attention_kernel(q: jax.Array, k_pool: jax.Array,
                                  v_pool: jax.Array,
                                  block_tables: jax.Array,
                                  cur_len: jax.Array,
                                  k_scale: Optional[jax.Array] = None,
                                  v_scale: Optional[jax.Array] = None,
                                  interpret: bool = False) -> jax.Array:
    """q [B,1,H,hd] vs a block pool [n_blocks, block_k, Hkv, hd] indirected
    through ``block_tables`` [B, max_blocks] int32 → [B,1,H,hd].

    Sequence ``bi``'s position ``p`` lives in pool block
    ``block_tables[bi, p // block_k]`` at offset ``p % block_k``;
    ``cur_len`` [B] masks exactly like the dense kernel (table entries
    past the last live block are never dereferenced — the index map
    clamps first, then indirects). ``k_scale``/``v_scale``
    [n_blocks, block_k, Hkv] fp32 mark an int8 pool.
    """
    b, s_q, h, hd = q.shape
    assert s_q == 1, q.shape
    _, block_k, hkv, _ = k_pool.shape
    n_bt = block_tables.shape[1]
    assert block_tables.shape[0] == b, (block_tables.shape, b)
    quantized = k_scale is not None

    def q_index(bi, j, cur_ref, bt_ref):
        del j, cur_ref, bt_ref
        return (bi, 0, 0)

    def _table_block(bi, j, cur_ref, bt_ref):
        # Clamp THEN indirect: dead grid steps re-deliver the last live
        # block (unchanged index → Pallas skips the DMA), and table rows
        # past a sequence's allocation are never read.
        live = pl.cdiv(cur_ref[bi], block_k)
        jc = jnp.minimum(j, jnp.maximum(live - 1, 0))
        return bt_ref[bi, jc]

    def kv_index(bi, j, cur_ref, bt_ref):
        return (_table_block(bi, j, cur_ref, bt_ref), 0, 0, 0)

    def scale_index(bi, j, cur_ref, bt_ref):
        return (_table_block(bi, j, cur_ref, bt_ref), 0, 0)

    in_specs = [
        pl.BlockSpec((1, h, hd), q_index),
        pl.BlockSpec((1, block_k, hkv, hd), kv_index),
        pl.BlockSpec((1, block_k, hkv, hd), kv_index),
    ]
    operands = [q[:, 0], k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_k, hkv), scale_index),
            pl.BlockSpec((1, block_k, hkv), scale_index),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_bt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),   # m (lane-replicated)
            pltpu.VMEM((h, 128), jnp.float32),   # l
            pltpu.VMEM((h, hd), jnp.float32),    # acc
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, block_k=block_k, n_blocks=n_bt,
        n_kv_heads=hkv, scale=hd**-0.5, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, hd), q.dtype),
        interpret=interpret,
    )(cur_len.astype(jnp.int32), block_tables.astype(jnp.int32),
      *operands)
    return out[:, None]


def gather_paged_kv(k_pool: jax.Array, v_pool: jax.Array,
                    block_tables: jax.Array,
                    k_scale: Optional[jax.Array] = None,
                    v_scale: Optional[jax.Array] = None):
    """Materialize each sequence's cache view from the pool:
    [n_blocks, block_k, ...] + tables [B, max_blocks] →
    (k, v [B, max_blocks*block_k, Hkv, hd], scales or None). The XLA
    fallback (and host-side debugging) path; the kernel never does this.
    """
    b, n_bt = block_tables.shape
    block_k = k_pool.shape[1]

    def flat(pool):
        g = pool[block_tables]            # [B, n_bt, block_k, ...]
        return g.reshape((b, n_bt * block_k) + pool.shape[2:])

    ks = flat(k_scale) if k_scale is not None else None
    vs = flat(v_scale) if v_scale is not None else None
    return flat(k_pool), flat(v_pool), ks, vs


def paged_decode_attention_xla(q: jax.Array, k_pool: jax.Array,
                               v_pool: jax.Array, block_tables: jax.Array,
                               cur_len: jax.Array,
                               k_scale: Optional[jax.Array] = None,
                               v_scale: Optional[jax.Array] = None
                               ) -> jax.Array:
    """Grouped-einsum fallback over a table-gathered cache view (CPU and
    odd shapes). Same contract as the paged kernel."""
    k, v, ks, vs = gather_paged_kv(k_pool, v_pool, block_tables,
                                   k_scale, v_scale)
    return decode_attention_xla(q, k, v, cur_len, ks, vs)


def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, block_tables: jax.Array,
                           cur_len: jax.Array,
                           k_scale: Optional[jax.Array] = None,
                           v_scale: Optional[jax.Array] = None,
                           interpret: Optional[bool] = None,
                           mesh=None) -> jax.Array:
    """Kernel when it can run (TPU, or forced interpreter), XLA otherwise
    (mirrors :func:`decode_attention`; the pool's block_k is the kernel
    block size, so there is no divisibility fallback to consider).

    ``mesh`` (a Mesh whose 'model' axis is the TP degree) selects the
    tensor-parallel kernel dispatch: the pool arrives sharded by KV
    head, so the kernel runs per shard under shard_map (see
    :func:`_paged_kernel_tp`). The XLA fallback needs no such wrapper —
    its gather + grouped einsum partition under plain GSPMD."""
    itp = _resolve_interpret(interpret)
    if itp is None:
        return paged_decode_attention_xla(q, k_pool, v_pool, block_tables,
                                          cur_len, k_scale, v_scale)
    if mesh is not None and mesh.shape.get(TP_AXIS, 1) > 1:
        return _paged_kernel_tp(paged_decode_attention_kernel, mesh, q,
                                k_pool, v_pool, block_tables, cur_len,
                                k_scale, v_scale, itp)
    return paged_decode_attention_kernel(q, k_pool, v_pool, block_tables,
                                         cur_len, k_scale, v_scale,
                                         interpret=itp)


def _paged_kernel_tp(kernel_fn, mesh, q: jax.Array, k_pool: jax.Array,
                     v_pool: jax.Array, block_tables: jax.Array,
                     lens: jax.Array, k_scale: Optional[jax.Array],
                     v_scale: Optional[jax.Array],
                     interpret: bool) -> jax.Array:
    """Tensor-parallel paged-kernel dispatch: shard_map over the
    'model' (KV-head) axis.

    Pallas calls are opaque to GSPMD's automatic partitioner, so the
    sharding is made explicit: each device runs the UNMODIFIED paged
    kernel on its local ``Hkv / tp`` pool shard and the matching
    ``H / tp`` query heads (q heads are grouped kv-head-major, so a
    contiguous head split keeps every GQA group on the device that
    holds its kv head — no cross-device attention traffic at all; the
    one per-sublayer all-reduce lives in the wo projection outside this
    op). Block tables and lengths are replicated — paging stays a
    host-global concern; only the head axis shards."""
    from skypilot_tpu.parallel import compat  # pylint: disable=import-outside-toplevel
    head = P(None, None, TP_AXIS, None)
    pool = P(None, None, TP_AXIS, None)
    scale = P(None, None, TP_AXIS)
    rep = P(None, None)
    has_scale = k_scale is not None
    in_specs = [head, pool, pool, rep, P(None)]
    if has_scale:
        in_specs += [scale, scale]

    def inner(q_l, k_l, v_l, bt, ln, *scales):
        ks, vs = scales if scales else (None, None)
        return kernel_fn(q_l, k_l, v_l, bt, ln, ks, vs,
                         interpret=interpret)

    fn = compat.shard_map(inner, mesh=mesh, in_specs=tuple(in_specs),
                          out_specs=head, check_vma=False)
    args = (q, k_pool, v_pool, block_tables, lens)
    if has_scale:
        args += (k_scale, v_scale)
    return fn(*args)


# ----------------------------------------------------- paged verify (q>1)


def _paged_verify_kernel(start_ref, bt_ref, q_ref, k_ref, v_ref, *rest,
                         block_k: int, n_blocks: int, n_kv_heads: int,
                         s_q: int, scale: float, quantized: bool):
    """One (batch, kv-block) program of the multi-query verify pass.

    Speculative-decoding verify is a q-length ``s_q`` decode: query ``i``
    sits at position ``start + i`` and may attend keys at positions
    ``<= start + i`` (the drafted tokens' K/V were scattered into the
    pool before this call, so the causal tail among the new positions is
    just part of the same per-query length mask). The body is the dense
    decode kernel's online softmax with the [H, ·] rows widened to
    [H*s_q, ·] — rows ordered (head, query) so each kv head's G*s_q
    query rows stay contiguous for the per-kv-head MXU tiles.

    start_ref: scalar-prefetch [B] int32 first-query positions; q_ref
    [1, H, s_q, hd]; k/v/scale refs as in the paged decode kernel;
    o_ref [1, H, s_q, hd]; m/l scratch [H*s_q, 128], acc [H*s_q, hd].
    """
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    bi = pl.program_id(0)
    j = pl.program_id(1)
    h = q_ref.shape[1]
    groups = h // n_kv_heads
    rows = h * s_q
    start = start_ref[bi]
    # Keys live at positions < start + s_q (the verify block's last
    # query position, inclusive).
    live_blocks = pl.cdiv(start + s_q, block_k)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(j < live_blocks)
    def _accumulate():
        q = q_ref[0].reshape(rows, q_ref.shape[-1])        # [H*S, hd]
        q = q.astype(jnp.float32) * scale
        k_blk = k_ref[0]                                   # [bk, Hkv, hd]
        v_blk = v_ref[0]
        if quantized:
            k_blk = k_blk.astype(jnp.float32) * ks_ref[0][:, :, None]
            v_blk = v_blk.astype(jnp.float32) * vs_ref[0][:, :, None]
        else:
            k_blk = k_blk.astype(jnp.float32)
            v_blk = v_blk.astype(jnp.float32)

        gs = groups * s_q
        logits = jnp.concatenate([
            jax.lax.dot_general(
                q[g * gs:(g + 1) * gs], k_blk[:, g, :],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            for g in range(n_kv_heads)
        ], axis=0)                                         # [H*S, bk]
        pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 1)
        # Row r is query s = r % s_q of head r // s_q: per-query causal
        # length start + s + 1.
        s_of_row = jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 0) % s_q
        mask = pos <= start + s_of_row                     # [H*S, bk]
        logits = jnp.where(mask, logits, NEG_INF)

        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.where(mask, jnp.exp(logits - safe_m), 0.0)
        correction = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe_m))
        pv = jnp.concatenate([
            jax.lax.dot_general(
                p[g * gs:(g + 1) * gs], v_blk[:, g, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            for g in range(n_kv_heads)
        ], axis=0)                                         # [H*S, hd]
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(
            l * correction + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape)
        acc_scr[...] = acc_scr[...] * correction + pv

    @pl.when(j == n_blocks - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        out = (acc_scr[...] / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
        o_ref[0] = out.reshape(h, s_q, o_ref.shape[-1])


def paged_verify_attention_kernel(q: jax.Array, k_pool: jax.Array,
                                  v_pool: jax.Array,
                                  block_tables: jax.Array,
                                  start_pos: jax.Array,
                                  k_scale: Optional[jax.Array] = None,
                                  v_scale: Optional[jax.Array] = None,
                                  interpret: bool = False) -> jax.Array:
    """q [B,S,H,hd] (S > 1 fine) vs a block pool through ``block_tables``
    → [B,S,H,hd]; query ``i`` of row ``b`` sits at position
    ``start_pos[b] + i`` and attends positions ``<= start_pos[b] + i``.
    The speculative-verify counterpart of
    :func:`paged_decode_attention_kernel`."""
    b, s_q, h, hd = q.shape
    _, block_k, hkv, _ = k_pool.shape
    n_bt = block_tables.shape[1]
    assert block_tables.shape[0] == b, (block_tables.shape, b)
    quantized = k_scale is not None

    def q_index(bi, j, start_ref, bt_ref):
        del j, start_ref, bt_ref
        return (bi, 0, 0, 0)

    def _table_block(bi, j, start_ref, bt_ref):
        live = pl.cdiv(start_ref[bi] + s_q, block_k)
        jc = jnp.minimum(j, jnp.maximum(live - 1, 0))
        return bt_ref[bi, jc]

    def kv_index(bi, j, start_ref, bt_ref):
        return (_table_block(bi, j, start_ref, bt_ref), 0, 0, 0)

    def scale_index(bi, j, start_ref, bt_ref):
        return (_table_block(bi, j, start_ref, bt_ref), 0, 0)

    in_specs = [
        pl.BlockSpec((1, h, s_q, hd), q_index),
        pl.BlockSpec((1, block_k, hkv, hd), kv_index),
        pl.BlockSpec((1, block_k, hkv, hd), kv_index),
    ]
    # Rows ordered (head, query): transpose outside the kernel so the
    # per-kv-head row slices stay contiguous.
    operands = [q.transpose(0, 2, 1, 3), k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_k, hkv), scale_index),
            pl.BlockSpec((1, block_k, hkv), scale_index),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_bt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, s_q, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((h * s_q, 128), jnp.float32),   # m
            pltpu.VMEM((h * s_q, 128), jnp.float32),   # l
            pltpu.VMEM((h * s_q, hd), jnp.float32),    # acc
        ],
    )
    kernel = functools.partial(
        _paged_verify_kernel, block_k=block_k, n_blocks=n_bt,
        n_kv_heads=hkv, s_q=s_q, scale=hd**-0.5, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_q, hd), q.dtype),
        interpret=interpret,
    )(start_pos.astype(jnp.int32), block_tables.astype(jnp.int32),
      *operands)
    return out.transpose(0, 2, 1, 3)


def paged_verify_attention_xla(q: jax.Array, k_pool: jax.Array,
                               v_pool: jax.Array, block_tables: jax.Array,
                               start_pos: jax.Array,
                               k_scale: Optional[jax.Array] = None,
                               v_scale: Optional[jax.Array] = None
                               ) -> jax.Array:
    """Grouped-einsum fallback for the multi-query verify pass; same
    contract as :func:`paged_verify_attention_kernel`. The per-query
    masked softmax mirrors :func:`decode_attention_xla` exactly so a
    q-length-1 verify is numerically the single-token decode step —
    what makes greedy speculative output token-identical to the
    non-speculative paged path."""
    b, s, h, hd = q.shape
    k, v, ks, vs = gather_paged_kv(k_pool, v_pool, block_tables,
                                   k_scale, v_scale)
    if ks is not None:
        k = (k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, s, hkv, g, hd)
    logits = jnp.einsum('bskgd,btkd->bkgst', qg, k,
                        preferred_element_type=jnp.float32) * hd**-0.5
    t_idx = jnp.arange(k.shape[1])
    # [B, S, T]: query i attends positions <= start_pos + i. Every row
    # keeps at least its own position, so no empty-row re-mask dance.
    mask = t_idx[None, None, :] <= (start_pos[:, None, None] +
                                    jnp.arange(s)[None, :, None])
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    probs = jnp.where(mask[:, None, None, :, :], probs, 0)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s, h, hd).astype(q.dtype)


def paged_verify_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, block_tables: jax.Array,
                           start_pos: jax.Array,
                           k_scale: Optional[jax.Array] = None,
                           v_scale: Optional[jax.Array] = None,
                           interpret: Optional[bool] = None,
                           mesh=None) -> jax.Array:
    """Kernel when it can run (TPU, or forced interpreter), XLA otherwise
    (mirrors :func:`paged_decode_attention`, tensor-parallel dispatch
    included — the verify q is [B, S, H, hd] but the head axis sits at
    the same position, so the shard_map specs are shared)."""
    itp = _resolve_interpret(interpret)
    if itp is None:
        return paged_verify_attention_xla(q, k_pool, v_pool, block_tables,
                                          start_pos, k_scale, v_scale)
    if mesh is not None and mesh.shape.get(TP_AXIS, 1) > 1:
        return _paged_kernel_tp(paged_verify_attention_kernel, mesh, q,
                                k_pool, v_pool, block_tables, start_pos,
                                k_scale, v_scale, itp)
    return paged_verify_attention_kernel(q, k_pool, v_pool, block_tables,
                                         start_pos, k_scale, v_scale,
                                         interpret=itp)


def _resolve_interpret(interpret: Optional[bool]) -> Optional[bool]:
    """True → interpreter, False → compiled kernel, None → XLA fallback
    (same contract as ``flash_attention._resolve_interpret``)."""
    if interpret is True:
        return True
    if interpret is False:
        return False
    return False if jax.default_backend() == 'tpu' else None


def resolved_path(max_len: int, block_k: int = DEFAULT_BLOCK_K,
                  interpret: Optional[bool] = None) -> str:
    """Which implementation :func:`decode_attention` will actually run for
    this config on this backend: 'kernel' or 'xla'. Single source of
    truth for the dispatch below; benchmarks report it so A/B numbers
    are attributed to the path that executed, not the one requested."""
    itp = _resolve_interpret(interpret)
    if itp is None or max_len % min(block_k, max_len):
        return 'xla'
    return 'kernel'


def decode_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     cur_len: jax.Array,
                     k_scale: Optional[jax.Array] = None,
                     v_scale: Optional[jax.Array] = None,
                     block_k: int = DEFAULT_BLOCK_K,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Kernel when it can run (TPU, or forced interpreter), XLA otherwise."""
    max_len = k_cache.shape[1]
    if resolved_path(max_len, block_k, interpret) == 'xla':
        return decode_attention_xla(q, k_cache, v_cache, cur_len,
                                    k_scale, v_scale)
    return decode_attention_kernel(q, k_cache, v_cache, cur_len,
                                   k_scale, v_scale,
                                   block_k=block_k,
                                   interpret=_resolve_interpret(interpret))
