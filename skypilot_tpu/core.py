"""Cluster management verbs (parity: ``sky/core.py``).

status/start/stop/down/autostop/queue/cancel/tail_logs/download_logs/
cost_report — each operates through the registry + backend.
"""
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import sky_logging
from skypilot_tpu.backends import backend_utils
from skypilot_tpu.backends import gang_backend
from skypilot_tpu.skylet import job_lib
from skypilot_tpu.usage import usage_lib
from skypilot_tpu.utils import locks

logger = sky_logging.init_logger(__name__)


def _backend() -> gang_backend.TpuGangBackend:
    return gang_backend.TpuGangBackend()


@usage_lib.entrypoint(name='status')
def status(cluster_names: Optional[Union[str, List[str]]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records (parity: core.py:92)."""
    if isinstance(cluster_names, str):
        cluster_names = [cluster_names]
    return backend_utils.get_clusters(refresh=refresh,
                                     cluster_names=cluster_names)


@usage_lib.entrypoint(name='fleet_status')
def fleet_status(cluster_names: Optional[Union[str, List[str]]] = None,
                 window_seconds: float = 120.0,
                 timeout: float = 30.0) -> List[Dict[str, Any]]:
    """Fleet telemetry snapshots: per-node resource windows pulled from
    every host of each UP cluster, aggregated (mean/max/p95, straggler +
    stale flags) and published as ``skytpu_node_*``/``skytpu_cluster_*``
    gauges. Backs ``skytpu top``, ``skytpu status -v`` and the
    dashboard's Fleet pane.
    """
    from skypilot_tpu.observability import fleet
    if isinstance(cluster_names, str):
        cluster_names = [cluster_names]
    records = backend_utils.get_clusters(cluster_names=cluster_names)
    if cluster_names:
        missing = set(cluster_names) - {r['name'] for r in records}
        if missing:
            raise exceptions.ClusterDoesNotExist(
                f'Cluster(s) {", ".join(sorted(missing))} do not exist.')
    out = []
    for record in records:
        if record['status'] != global_state.ClusterStatus.UP:
            out.append({'cluster': record['name'], 'ts': time.time(),
                        'nodes': [], 'rollup': {}, 'stale_nodes': [],
                        'stragglers': [],
                        'error': f"cluster is {record['status'].value}"})
            continue
        handle = record['handle']
        try:
            runners = handle.get_command_runners()
            out.append(fleet.collect_cluster(
                record['name'], runners, window_seconds=window_seconds,
                timeout=timeout))
        except Exception as e:  # pylint: disable=broad-except
            # One unreachable cluster must not hide the rest of the
            # fleet from `skytpu top`.
            logger.debug(f"fleet_status({record['name']}): {e}")
            out.append({'cluster': record['name'], 'ts': time.time(),
                        'nodes': [], 'rollup': {}, 'stale_nodes': [],
                        'stragglers': [],
                        'error': f'{type(e).__name__}: {e}'})
    return out


def kubernetes_status() -> List[Dict[str, Any]]:
    """Framework pods across every allowed Kubernetes context (parity:
    `sky status --kubernetes` / _status_kubernetes): the cloud-side
    truth, independent of the local registry — finds pods this client
    forgot about (wiped state, another operator's launches)."""
    from skypilot_tpu.clouds import kubernetes as k8s_cloud
    from skypilot_tpu.provision.kubernetes import instance as k8s_inst
    from skypilot_tpu.provision.kubernetes import k8s_api
    out: List[Dict[str, Any]] = []
    for ctx in k8s_cloud.Kubernetes.existing_allowed_contexts():
        client = k8s_api.make_client(ctx)
        # Same namespace resolution as provisioning (config
        # kubernetes.namespace / in-cluster SA namespace) — listing
        # 'default' would miss every pod of a namespaced deployment.
        namespace = k8s_inst._namespace({'context': ctx})  # pylint: disable=protected-access
        try:
            pods = client.list_pods(namespace, k8s_inst._CLUSTER_LABEL)  # pylint: disable=protected-access
        except k8s_api.K8sApiError as e:
            logger.debug(f'status --kubernetes: context {ctx}: {e}')
            continue
        by_cluster: Dict[str, List[dict]] = {}
        for pod in pods:
            name = pod['metadata']['labels'].get(
                k8s_inst._CLUSTER_LABEL, '?')  # pylint: disable=protected-access
            by_cluster.setdefault(name, []).append(pod)
        for name, cluster_pods in sorted(by_cluster.items()):
            phases = [p.get('status', {}).get('phase', '?')
                      for p in cluster_pods]
            out.append({
                'context': ctx,
                'cluster_name_on_cloud': name,
                'pods': len(cluster_pods),
                'phases': sorted(set(phases)),
                'pod_names': sorted(p['metadata']['name']
                                    for p in cluster_pods),
            })
    return out


def cluster_endpoints(cluster_name: str,
                      port: Optional[int] = None) -> Dict[int, str]:
    """URLs for a cluster's declared ``ports:`` (parity: `sky status
    --endpoints`, core.py endpoints).

    Per transport: ssh hosts → the head's IP; local → loopback;
    kubernetes → the NodePort the ports Service assigned (the node IP
    is cluster-specific, so the node-port mapping is the useful part).
    """
    record = global_state.get_cluster_from_name(cluster_name)
    if record is None or record.get('handle') is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    # Only UP clusters have live endpoints: a STOPPED cluster's cached
    # host IPs are stale (and likely change on restart) — printing
    # them would be wrong, not just useless.
    if record['status'] != global_state.ClusterStatus.UP:
        raise exceptions.InvalidSkyError(
            f'Cluster {cluster_name!r} is '
            f"{record['status'].value}, not UP — endpoints are only "
            'live on running clusters.')
    handle = record['handle']
    res = handle.launched_resources
    from skypilot_tpu.utils import common_utils
    # Per-entry tolerance: one malformed declaration must not hide the
    # valid ports of a cluster the framework itself launched.
    declared: List[int] = []
    for p in (res.ports or []) if res is not None else []:
        try:
            declared.extend(common_utils.expand_ports([p]))
        except ValueError as e:
            logger.debug(f'endpoints: skipping port entry {p!r}: {e}')
    if port is not None:
        if port not in declared:
            raise exceptions.InvalidSkyError(
                f'Port {port} is not declared by {cluster_name!r} '
                f'(declared: {declared or "none"}).')
        declared = [port]
    hosts = handle.cached_hosts or []
    if not hosts:
        raise exceptions.InvalidSkyError(
            f'Cluster {cluster_name!r} has no cached hosts; run '
            '`skytpu status -r` to refresh.')
    head = hosts[0]
    out: Dict[int, str] = {}
    if head['transport'] == 'kubernetes':
        from skypilot_tpu.provision.kubernetes import instance as k8s_inst
        from skypilot_tpu.provision.kubernetes import k8s_api
        client = k8s_api.make_client(head.get('context'))
        try:
            svc = client.get_service(
                head.get('namespace', 'default'),
                k8s_inst._ports_service_name(  # pylint: disable=protected-access
                    handle.cluster_name_on_cloud))
            node_ports = {
                int(sp['port']): int(sp.get('nodePort', sp['port']))
                for sp in svc.get('spec', {}).get('ports', [])
            }
        except k8s_api.K8sApiError:
            node_ports = {}
        for p in declared:
            out[p] = f'http://<node-ip>:{node_ports.get(p, p)}'
    else:
        ip = ('127.0.0.1' if head['transport'] == 'local' else
              head.get('ip', head.get('internal_ip', '')))
        for p in declared:
            out[p] = f'http://{ip}:{p}'
    return out


@usage_lib.entrypoint(name='start')
def start(cluster_name: str,
          idle_minutes_to_autostop: Optional[int] = None,
          retry_until_up: bool = False,
          down: bool = False) -> gang_backend.ClusterHandle:
    """Restart a STOPPED cluster (parity: core.py:393)."""
    from skypilot_tpu import provision as provision_router
    from skypilot_tpu.provision import provisioner as provisioner_lib
    record = backend_utils.refresh_cluster_record(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle: gang_backend.ClusterHandle = record['handle']
    if record['status'] == global_state.ClusterStatus.UP:
        logger.info(f'Cluster {cluster_name!r} is already up.')
        return handle
    backend_utils.check_owner_identity(cluster_name)
    with locks.cluster_status_lock(cluster_name):
        config = backend_utils.make_provision_config(
            handle.launched_resources, handle.launched_nodes,
            handle.cluster_name_on_cloud,
            handle.provider_config.get('region'),
            handle.provider_config.get('availability_zone'))
        provisioner_lib.bulk_provision(handle.provider_name,
                                       handle.provider_config.get('region'),
                                       handle.cluster_name_on_cloud, config)
        cluster_info = provision_router.get_cluster_info(
            handle.provider_name,
            handle.provider_config.get('region'),
            handle.cluster_name_on_cloud,
            provider_config=config.provider_config)
        if handle.launched_resources.tpu_topology is not None:
            cluster_info.custom_metadata['chips_per_host'] = \
                handle.launched_resources.tpu_topology.chips_per_host
        provisioner_lib.wait_for_ssh(cluster_info,
                                     cluster_name=cluster_name)
        provisioner_lib.post_provision_runtime_setup(
            cluster_name, handle.cluster_name_on_cloud, cluster_info,
            cluster_info.provider_config)
        handle.update_cluster_info()
        global_state.add_or_update_cluster(cluster_name, handle, ready=True)
    if idle_minutes_to_autostop is not None:
        _backend().set_autostop(handle, idle_minutes_to_autostop, down)
    return handle


@usage_lib.entrypoint(name='stop')
def stop(cluster_name: str, purge: bool = False) -> None:
    """Parity: core.py:500. TPU pods cannot stop — surfaced as an error."""
    record = global_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    backend_utils.check_owner_identity(cluster_name)
    handle = record['handle']
    res = handle.launched_resources
    if res.tpu_topology is not None and res.tpu_topology.is_pod:
        raise exceptions.NotSupportedError(
            f'Cluster {cluster_name!r} is a multi-host TPU slice, which '
            'GCP cannot stop. Use `sky down` instead.')
    _backend().teardown(handle, terminate=False, purge=purge)


@usage_lib.entrypoint(name='down')
def down(cluster_name: str, purge: bool = False) -> None:
    """Parity: core.py:465."""
    record = global_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    backend_utils.check_owner_identity(cluster_name)
    _backend().teardown(record['handle'], terminate=True, purge=purge)


@usage_lib.entrypoint(name='autostop')
def autostop(cluster_name: str, idle_minutes: int,
             down: bool = False) -> None:  # pylint: disable=redefined-outer-name
    """Parity: core.py:560. idle_minutes < 0 cancels autostop."""
    handle = backend_utils.check_cluster_available(cluster_name, 'autostop')
    res = handle.launched_resources
    if (res.tpu_topology is not None and res.tpu_topology.is_pod and
            not down and idle_minutes >= 0):
        raise exceptions.NotSupportedError(
            'Multi-host TPU slices support autodown (`down=True`), not '
            'autostop.')
    _backend().set_autostop(handle, idle_minutes, down)


@usage_lib.entrypoint(name='queue')
def queue(cluster_name: str,
          skip_finished: bool = False) -> List[Dict[str, Any]]:
    """Parity: core.py:669."""
    handle = backend_utils.check_cluster_available(cluster_name, 'queue')
    jobs = _backend().get_job_queue(handle)
    if skip_finished:
        jobs = [
            j for j in jobs
            if not job_lib.JobStatus(j['status']).is_terminal()
        ]
    return jobs


@usage_lib.entrypoint(name='cancel')
def cancel(cluster_name: str,
           job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> None:
    """Parity: core.py:732."""
    handle = backend_utils.check_cluster_available(cluster_name, 'cancel')
    _backend().cancel_jobs(handle, job_ids, cancel_all=all_jobs)


@usage_lib.entrypoint(name='tail_logs')
def tail_logs(cluster_name: str,
              job_id: Optional[int] = None,
              follow: bool = True) -> int:
    """Parity: core.py:827."""
    handle = backend_utils.check_cluster_available(cluster_name, 'tail logs')
    return _backend().tail_logs(handle, job_id, follow=follow)


@usage_lib.entrypoint(name='download_logs')
def download_logs(cluster_name: str,
                  job_id: Optional[int] = None,
                  local_dir: str = '~/sky_logs') -> str:
    """Parity: core.py:865."""
    handle = backend_utils.check_cluster_available(cluster_name,
                                                   'download logs')
    return _backend().sync_down_logs(handle, job_id, local_dir)


@usage_lib.entrypoint(name='job_status')
def job_status(cluster_name: str,
               job_id: Optional[int] = None
               ) -> Optional[job_lib.JobStatus]:
    handle = backend_utils.check_cluster_available(cluster_name,
                                                   'query job status')
    return _backend().get_job_status(handle, job_id)


@usage_lib.entrypoint(name='cost_report')
def cost_report() -> List[Dict[str, Any]]:
    """Per-cluster accumulated cost from usage intervals.

    Parity: core.py:280 + global_user_state.py:548.
    """
    out = []
    for record in global_state.get_cluster_history():
        resources = record['launched_resources']
        cost = None
        if resources is not None and resources.is_launchable():
            cost = resources.get_hourly_cost() * \
                (record['num_nodes'] or 1) * record['duration'] / 3600.0
        out.append({
            'name': record['name'],
            'duration': record['duration'],
            'num_nodes': record['num_nodes'],
            'resources': resources,
            'total_cost': cost,
        })
    return out


@usage_lib.entrypoint(name='local_up')
def local_up() -> List[str]:
    """Enable the zero-credential Local cloud (parity: core.py:280
    `sky local up`, which boots a kind cluster — the TPU-native test
    double is the Local cloud: processes as hosts, no Kubernetes
    needed). Returns the enabled-cloud list.

    Runs the full credential probe first so persisting a non-empty
    enabled set here can't suppress the first-use probe of every OTHER
    cloud (the cache only auto-refreshes when empty).
    """
    from skypilot_tpu import check as check_lib
    try:
        enabled = set(check_lib.check(quiet=True))
    except exceptions.NoCloudAccessError:
        enabled = set()
    enabled.add('Local')
    out = sorted(enabled)
    global_state.set_enabled_clouds(out)
    return out


@usage_lib.entrypoint(name='local_down')
def local_down() -> List[str]:
    """Tear down every Local-cloud cluster and disable the cloud
    (parity: core.py:1061 `sky local down`). Returns the torn-down
    cluster names. NOTE: the Local cloud needs no credentials, so a
    later full probe (`skytpu check` / an empty-cache refresh)
    re-enables it.
    """
    torn_down = []
    for record in global_state.get_clusters():
        handle = record['handle']
        if str(handle.launched_resources.cloud).lower() == 'local':
            # Reuse the ordinary down verb: owner-identity guard,
            # locking, and history bookkeeping stay in ONE place.
            down(record['name'], purge=True)
            torn_down.append(record['name'])
    enabled = [c for c in (global_state.get_enabled_clouds() or [])
               if c.lower() != 'local']
    global_state.set_enabled_clouds(enabled)
    return torn_down
