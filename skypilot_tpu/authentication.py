"""SSH keypair management (parity: ``sky/authentication.py:133``).

Key generation is pure-Python (``cryptography``) so it works on hosts
without OpenSSH client tools; the keys are standard RSA/OpenSSH format
consumed by the ssh/scp binaries on real control hosts.
"""
import os

from skypilot_tpu.utils import locks

DEFAULT_SSH_USER = 'skytpu'
_KEY_PATH = '~/.skytpu/sky-key'


def _generate_keypair(private_path: str, public_path: str) -> None:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    private_pem = key.private_bytes(
        encoding=serialization.Encoding.PEM,
        format=serialization.PrivateFormat.OpenSSH,
        encryption_algorithm=serialization.NoEncryption())
    public_ssh = key.public_key().public_bytes(
        encoding=serialization.Encoding.OpenSSH,
        format=serialization.PublicFormat.OpenSSH)
    with open(private_path, 'wb') as f:
        f.write(private_pem)
    os.chmod(private_path, 0o600)
    with open(public_path, 'wb') as f:
        f.write(public_ssh + b' skytpu\n')


def get_or_generate_keys() -> tuple:
    """Returns (public_key_str, private_key_path); generates once."""
    private_path = os.path.expanduser(_KEY_PATH)
    public_path = private_path + '.pub'
    os.makedirs(os.path.dirname(private_path), exist_ok=True)
    lock = locks.FileLock(private_path + '.lock', timeout=20)
    with lock:
        if not os.path.exists(private_path):
            _generate_keypair(private_path, public_path)
    with open(public_path, encoding='utf-8') as f:
        public_key = f.read().strip()
    return public_key, _KEY_PATH
