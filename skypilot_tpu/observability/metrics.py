"""Thread-safe, dependency-free metrics registry with Prometheus exposition.

The in-tree analogue of ``prometheus_client`` (which the container does
not ship): Counter / Gauge / Histogram with labels, one registry per
process by default, and text-format exposition (``generate_latest``,
content type ``text/plain; version=0.0.4``) that real Prometheus scrapes
parse.

Conventions:

* Every metric name matches ``^skytpu_[a-z0-9_]+$`` (enforced here at
  registration and by a tier-1 lint test over the source tree), so the
  whole codebase exposes one coherent, greppable namespace.
* Metric construction is get-or-create: calling :func:`counter` (or
  ``registry.counter``) twice with the same name returns the SAME metric
  object — instrumentation sites can resolve their metric at call time
  instead of holding module globals, which keeps tests free to swap the
  process registry (:func:`set_registry`).
* Label sets are fixed at first registration; re-registering with a
  different type or label names raises (silent drift between two call
  sites would corrupt the exposition).
"""
import math
import re
import threading
import time
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional, Sequence, Tuple

METRIC_NAME_PATTERN = r'^skytpu_[a-z0-9_]+$'
_NAME_RE = re.compile(METRIC_NAME_PATTERN)
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*$')

# Label-cardinality guard: these label NAMES are rejected at
# registration because their values are unbounded by construction —
# per-request ids would mint one child series per request and grow the
# registry (and every scrape) without bound. Request-scoped telemetry
# belongs in the journal (keyed by trace id) or the request-trace ring,
# not in metric labels. A tier-1 lint additionally scans call sites.
UNBOUNDED_LABEL_NAMES = frozenset({
    'request_id', 'request', 'trace_id', 'span_id',
})

# The VALUE half of the same guard: expression fragments that mark a
# label value as derived from a per-request identifier. ONE vocabulary
# shared by the runtime guard above and the static label-cardinality
# rule (skypilot_tpu/analysis/rules_observability.py) — previously the
# lint test carried its own copy, which is how denylists drift.
UNBOUNDED_LABEL_VALUE_MARKERS = ('trace_id', 'request_id', 'req.id',
                                 'request.id', 'span_id')

# Default histogram buckets: wide enough to cover sub-ms decode token
# latencies AND multi-minute provisioning spans in one scheme.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                   300.0, 600.0)

CONTENT_TYPE_LATEST = 'text/plain; version=0.0.4; charset=utf-8'


def _noop_write() -> None:
    pass


def format_float(v: float) -> str:
    """Prometheus sample-value formatting ('+Inf', integers without
    trailing '.0')."""
    v = float(v)
    if math.isinf(v):
        return '+Inf' if v > 0 else '-Inf'
    if math.isnan(v):
        return 'NaN'
    s = repr(v)
    if s.endswith('.0'):
        s = s[:-2]
    return s


def normalize_buckets(buckets: Sequence[float]) -> Tuple[float, ...]:
    """Sorted finite upper bounds (+Inf is implicit and dropped)."""
    bs = sorted(float(b) for b in buckets)
    if bs and math.isinf(bs[-1]):
        bs = bs[:-1]
    if not bs:
        raise ValueError('Histogram needs at least one finite bucket')
    return tuple(bs)


def escape_label_value(v: str) -> str:
    """Backslash, double-quote and newline escaping per the text format."""
    return str(v).replace('\\', r'\\').replace('\n', r'\n').replace(
        '"', r'\"')


def _escape_help(v: str) -> str:
    return str(v).replace('\\', r'\\').replace('\n', r'\n')


class Metric:
    """Base: a named family of label-keyed children."""

    kind = ''

    def __init__(self, name: str, help_text: str,
                 labels: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(
                f'Metric name {name!r} must match {METRIC_NAME_PATTERN}')
        for label in labels:
            if not _LABEL_RE.match(label):
                raise ValueError(f'Invalid label name {label!r}')
            if label in UNBOUNDED_LABEL_NAMES:
                raise ValueError(
                    f'Label {label!r} on {name!r} is unbounded by '
                    'construction (one series per request); key '
                    'request-scoped telemetry by trace id in the '
                    'journal / request-trace ring instead.')
        self.name = name
        self.help_text = help_text
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        # Wired to the owning registry's write stamp at registration;
        # metrics constructed outside a registry keep the no-op.
        self._on_write: 'callable' = _noop_write

    def _note_write(self) -> None:
        self._on_write()

    def _key(self, labels: Sequence[str]) -> Tuple[str, ...]:
        key = tuple(str(v) for v in labels)
        if len(key) != len(self.label_names):
            raise ValueError(
                f'{self.name}: got {len(key)} label values for '
                f'{len(self.label_names)} labels {self.label_names}')
        return key

    def remove(self, labels: Sequence[str] = ()) -> None:
        """Drop one labeled child series. For gauges whose label values
        churn over a process lifetime (fleet replica URLs): a departed
        replica's series must disappear from the exposition instead of
        exporting its last value — and leaking one series per
        ever-seen value — forever. No-op when the child never existed."""
        key = self._key(labels)
        with self._lock:
            self._children.pop(key, None)
        self._note_write()

    def _render_series(self, suffix: str, key: Tuple[str, ...], value,
                       extra_labels: Sequence[Tuple[str, str]] = ()
                       ) -> str:
        pairs = [f'{n}="{escape_label_value(v)}"'
                 for n, v in zip(self.label_names, key)]
        pairs += [f'{n}="{escape_label_value(v)}"'
                  for n, v in extra_labels]
        label_str = '{' + ','.join(pairs) + '}' if pairs else ''
        return f'{self.name}{suffix}{label_str} {format_float(value)}'

    def expose(self) -> List[str]:
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [f'# HELP {self.name} {_escape_help(self.help_text)}',
                f'# TYPE {self.name} {self.kind}']


class Counter(Metric):
    """Monotonically increasing count."""

    kind = 'counter'

    def inc(self, amount: float = 1.0, labels: Sequence[str] = ()) -> None:
        if amount < 0:
            raise ValueError('Counters can only increase')
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount
        self._note_write()

    def value(self, labels: Sequence[str] = ()) -> float:
        with self._lock:
            return float(self._children.get(self._key(labels), 0.0))

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._children.items())
        return self._header() + [self._render_series('', k, v)
                                 for k, v in items]


class Gauge(Metric):
    """A value that can go up and down."""

    kind = 'gauge'

    def set(self, value: float, labels: Sequence[str] = ()) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)
        self._note_write()

    def inc(self, amount: float = 1.0, labels: Sequence[str] = ()) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount
        self._note_write()

    def dec(self, amount: float = 1.0, labels: Sequence[str] = ()) -> None:
        self.inc(-amount, labels)

    def value(self, labels: Sequence[str] = ()) -> float:
        with self._lock:
            return float(self._children.get(self._key(labels), 0.0))

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._children.items())
        return self._header() + [self._render_series('', k, v)
                                 for k, v in items]


class _HistogramChild:
    __slots__ = ('bucket_counts', 'total', 'count')

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.total = 0.0
        self.count = 0


class Histogram(Metric):
    """Observations bucketed by upper bound, with ``_sum`` and ``_count``.

    Exposition follows the Prometheus scheme exactly: ``_bucket`` series
    are CUMULATIVE and always end with ``le="+Inf"`` equal to ``_count``.
    """

    kind = 'histogram'

    def __init__(self, name: str, help_text: str,
                 labels: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, labels)
        self.buckets = normalize_buckets(buckets)

    def observe(self, value: float, labels: Sequence[str] = ()) -> None:
        value = float(value)
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _HistogramChild(len(self.buckets) + 1)
                self._children[key] = child
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    child.bucket_counts[i] += 1
                    break
            else:
                child.bucket_counts[-1] += 1  # > largest bound → +Inf only
            child.total += value
            child.count += 1
        self._note_write()

    def count(self, labels: Sequence[str] = ()) -> int:
        with self._lock:
            child = self._children.get(self._key(labels))
            return child.count if child else 0

    def sum(self, labels: Sequence[str] = ()) -> float:
        with self._lock:
            child = self._children.get(self._key(labels))
            return child.total if child else 0.0

    def expose(self) -> List[str]:
        with self._lock:
            items = [(k, list(c.bucket_counts), c.total, c.count)
                     for k, c in sorted(self._children.items())]
        lines = self._header()
        for key, per_bucket, total, count in items:
            cumulative = 0
            for bound, n in zip(self.buckets, per_bucket):
                cumulative += n
                lines.append(self._render_series(
                    '_bucket', key, cumulative,
                    extra_labels=[('le', format_float(bound))]))
            lines.append(self._render_series(
                '_bucket', key, count, extra_labels=[('le', '+Inf')]))
            lines.append(self._render_series('_sum', key, total))
            lines.append(self._render_series('_count', key, count))
        return lines


class MetricsRegistry:
    """Name → Metric map with get-or-create registration.

    ``last_write_ts`` is stamped on every metric mutation — the
    exporter's ``/healthz`` uses it to report how stale this process's
    telemetry is (a wedged process keeps serving but stops writing).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}
        self.last_write_ts = 0.0

    def _stamp_write(self) -> None:
        # Plain float store: atomic under the GIL, and a heartbeat may
        # be a hair late without consequence — no lock on the hot path.
        self.last_write_ts = time.time()

    def _get_or_create(self, cls, name: str, help_text: str,
                       labels: Sequence[str], **kwargs) -> Metric:
        labels = tuple(labels)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f'{name!r} already registered as '
                        f'{type(existing).__name__}, not {cls.__name__}')
                if existing.label_names != labels:
                    raise ValueError(
                        f'{name!r} already registered with labels '
                        f'{existing.label_names}, not {labels}')
                return existing
            metric = cls(name, help_text, labels, **kwargs)
            metric._on_write = self._stamp_write  # pylint: disable=protected-access
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_text: str = '',
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = '',
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = '',
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        metric = self._get_or_create(Histogram, name, help_text, labels,
                                     buckets=buckets)
        # Buckets are part of the registration contract too: a second
        # call site with a different scheme would silently get the first
        # one's le= series (read-side lookups should use get()).
        if metric.buckets != normalize_buckets(buckets):
            raise ValueError(
                f'{name!r} already registered with buckets '
                f'{metric.buckets}, not {tuple(buckets)}')
        return metric

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def generate_latest(self) -> bytes:
        """Prometheus text-format exposition of every registered metric."""
        lines: List[str] = []
        for metric in self.metrics():
            lines.extend(metric.expose())
        return ('\n'.join(lines) + '\n').encode('utf-8')


# Process-global registry. Instrumentation sites resolve metrics through
# the module helpers at CALL time, so tests can swap in a fresh registry.
_registry = MetricsRegistry()
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    return _registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the previous."""
    global _registry
    with _registry_lock:
        prev = _registry
        _registry = registry
        return prev


def counter(name: str, help_text: str = '',
            labels: Sequence[str] = ()) -> Counter:
    return _registry.counter(name, help_text, labels)


def gauge(name: str, help_text: str = '',
          labels: Sequence[str] = ()) -> Gauge:
    return _registry.gauge(name, help_text, labels)


def histogram(name: str, help_text: str = '', labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _registry.histogram(name, help_text, labels, buckets=buckets)


def generate_latest(registry: Optional[MetricsRegistry] = None) -> bytes:
    return (registry or _registry).generate_latest()


class RateTracker:
    """Windowed event-rate signal whose cumulative count publishes to a
    registry Counter.

    Replaces ad-hoc private timestamp deques (the load balancer / serve
    controller QPS path): callers ``note()``/``extend()`` events, read a
    windowed rate via :meth:`qps`, and the cumulative total rides the
    registry so ``/metrics`` and the autoscaler see the SAME signal.
    """

    def __init__(self, name: str, help_text: str = '',
                 labels: Sequence[str] = (),
                 label_values: Sequence[str] = (),
                 maxlen: int = 100_000,
                 registry: Optional[MetricsRegistry] = None):
        reg = registry or get_registry()
        self._counter = reg.counter(name, help_text, labels)
        self._label_values = tuple(str(v) for v in label_values)
        self._lock = threading.Lock()
        self._timestamps: Deque[float] = deque(maxlen=maxlen)

    def note(self, ts: Optional[float] = None) -> None:
        with self._lock:
            self._timestamps.append(time.time() if ts is None else ts)
        self._counter.inc(labels=self._label_values)

    def extend(self, timestamps: Iterable[float]) -> None:
        n = 0
        with self._lock:
            for ts in timestamps:
                self._timestamps.append(float(ts))
                n += 1
        if n:
            self._counter.inc(n, labels=self._label_values)

    def timestamps(self) -> List[float]:
        with self._lock:
            return list(self._timestamps)

    def total(self) -> float:
        return self._counter.value(labels=self._label_values)

    def qps(self, window_seconds: float,
            now: Optional[float] = None) -> float:
        """Events-per-second over the trailing window (the autoscaler's
        QPS signal; same computation the private-deque path used)."""
        now = time.time() if now is None else now
        cutoff = now - window_seconds
        with self._lock:
            recent = sum(1 for t in self._timestamps if t > cutoff)
        return recent / window_seconds if window_seconds > 0 else 0.0
