"""JAX runtime telemetry: train step/MFU, decode TTFT + per-token
latency, KV-cache gauges, and on-demand profiler capture.

The TPU-pod scaling papers (PAPERS.md: "Exploring the limits of
Concurrency in ML Training on Google TPUs", MLPerf-0.6 on v3 pods) find
stragglers and input-pipeline stalls from exactly two signals — step
time and MFU — so those are first-class here, published through the
process registry where the serve/bench layers already report.

Everything in this module is host-side and cheap (perf_counter deltas +
dict updates under a lock); nothing here forces a device sync. Step
timing in a steady loop measures dispatch-to-dispatch wall time, which
converges to device step time once JAX's async dispatch queue
backpressures — the standard host-side step-time estimate.
"""
import os
import time
from typing import Optional

from skypilot_tpu.observability import metrics
from skypilot_tpu.utils import accelerator_registry

PROFILE_DIR_ENV = 'SKYTPU_PROFILE_DIR'
PROFILE_STEPS_ENV = 'SKYTPU_PROFILE_STEPS'
PEAK_FLOPS_ENV = 'SKYTPU_PEAK_FLOPS'

# Train-step times: one CPU debug step is ~10ms, a big pod step ~10s.
TRAIN_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                      0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# Decode per-token latencies sit in the 100us–100ms band on TPU.
TOKEN_LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                         0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
# The long-tail end (... 2.5/5/10/30/60 s) matters as much as the fast
# end: prefill-heavy requests on a saturated replica land there, and
# without those bounds p99 TTFT saturates into +Inf and is unreadable.
TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def peak_flops(device=None) -> float:
    """Per-chip peak bf16 FLOPs: env override (``SKYTPU_PEAK_FLOPS``),
    else detected from the device kind; 0.0 when unknown."""
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:  # pylint: disable=broad-except
            return 0.0
    return accelerator_registry.peak_bf16_flops(device)


class TrainTelemetry:
    """Per-step training telemetry → registry.

    Publishes ``skytpu_train_step_seconds`` (histogram),
    ``skytpu_train_tokens_per_second`` and ``skytpu_train_mfu`` (gauges,
    MFU 0.0 when the hardware peak is unknown, e.g. CPU dev runs), and
    ``skytpu_train_steps_total``.
    """

    def __init__(self, model_cfg=None, seq_len: Optional[int] = None,
                 device=None):
        self._model_cfg = model_cfg
        self._seq_len = seq_len
        self._peak = peak_flops(device)
        self._last_t: Optional[float] = None

    def step_start(self) -> None:
        """Optional explicit step boundary; record_step() alone also
        works (dispatch-to-dispatch timing)."""
        self._last_t = time.perf_counter()

    def record_step(self, tokens: int,
                    step_seconds: Optional[float] = None) -> None:
        """Record one completed step of ``tokens`` tokens.

        Without an explicit ``step_seconds``, the delta since the
        previous record/step_start is used (the first call is a silent
        arm when no boundary was set).
        """
        now = time.perf_counter()
        # Every completed step counts, including the compile-dominated
        # first one that only ARMS the step timer below.
        metrics.counter('skytpu_train_steps_total',
                        'Training steps completed.').inc()
        if step_seconds is None:
            if self._last_t is None:
                self._last_t = now
                return
            step_seconds = now - self._last_t
        self._last_t = now
        metrics.histogram('skytpu_train_step_seconds',
                          'Wall-clock time per training step.',
                          buckets=TRAIN_STEP_BUCKETS).observe(step_seconds)
        if step_seconds <= 0:
            return
        tps = tokens / step_seconds
        metrics.gauge('skytpu_train_tokens_per_second',
                      'Training throughput (tokens/sec, most recent '
                      'step).').set(tps)
        mfu = 0.0
        if self._peak and self._model_cfg is not None and self._seq_len:
            from skypilot_tpu.models import train
            mfu = train.tokens_per_second_to_mfu(tps, self._model_cfg,
                                                 self._seq_len, self._peak)
        metrics.gauge('skytpu_train_mfu',
                      'Model FLOPs utilization of the most recent step '
                      '(0.0 when the hardware peak is unknown).').set(mfu)


def record_decode_phase(prefill_seconds: float, decode_seconds: float,
                        batch: int, new_tokens: int,
                        kv_cache_dtype: str = 'bf16',
                        completed_tokens: Optional[int] = None) -> None:
    """Record one decode run: TTFT (prefill latency) and per-token decode
    latency histograms, plus generated-token/request counters.
    ``completed_tokens`` overrides the ``batch * new_tokens`` token count
    when the caller knows how many tokens were actually generated (EOS
    stops rows early; the padding is not generated output)."""
    metrics.histogram('skytpu_decode_ttft_seconds',
                      'Time to first token (prefill latency).',
                      labels=('kv_cache_dtype',),
                      buckets=TTFT_BUCKETS).observe(
                          prefill_seconds, labels=(kv_cache_dtype,))
    if new_tokens > 0:
        metrics.histogram('skytpu_decode_token_seconds',
                          'Per-token decode latency.',
                          labels=('kv_cache_dtype',),
                          buckets=TOKEN_LATENCY_BUCKETS).observe(
                              decode_seconds / new_tokens,
                              labels=(kv_cache_dtype,))
    metrics.counter('skytpu_decode_tokens_total',
                    'Tokens generated by decode.').inc(
                        batch * new_tokens if completed_tokens is None
                        else completed_tokens)
    # skytpu_decode_requests_total is incremented by decode.generate
    # itself (every serving call), not here — this helper only adds the
    # latency view that needs a sync boundary.


def record_kv_cache(batch: int, max_len: int, used_len: int,
                    kv_cache_dtype: str) -> None:
    """KV-cache occupancy + dtype gauges for the current decode config."""
    g = metrics.gauge('skytpu_decode_kv_cache_tokens',
                      'KV cache slots (batch * positions).',
                      labels=('kind',))
    g.set(batch * max_len, labels=('capacity',))
    g.set(batch * used_len, labels=('used',))
    dtype_g = metrics.gauge('skytpu_decode_kv_cache_dtype_info',
                            'KV cache storage dtype (1 for the active '
                            'dtype).', labels=('dtype',))
    # One process can switch cache dtypes between runs (bench.py's
    # bf16/int8 sweep): zero the inactive series so exactly one dtype
    # reports 1.
    for dtype in {'bf16', 'int8', kv_cache_dtype}:
        dtype_g.set(1 if dtype == kv_cache_dtype else 0, labels=(dtype,))


class StepProfiler:
    """On-demand ``jax.profiler`` trace capture for the train loop.

    Armed by ``SKYTPU_PROFILE_DIR``: the first ``SKYTPU_PROFILE_STEPS``
    (default 3) steps after warmup are captured to
    ``$SKYTPU_PROFILE_DIR/<tag>`` (open in XProf/TensorBoard). A no-op
    when the env is unset, so the hook can stay permanently wired into
    ``train_loop``.
    """

    def __init__(self, tag: str = 'train', skip_steps: int = 1):
        self._dir = os.environ.get(PROFILE_DIR_ENV)
        try:
            self._steps = int(os.environ.get(PROFILE_STEPS_ENV, '3'))
        except ValueError:
            self._steps = 3  # degrade, never die: bad env ≠ dead trainer
        self._skip = skip_steps  # let compile/warmup steps pass
        self._tag = tag
        self._seen = 0
        self._active = False

    def step(self) -> None:
        """Call once per loop iteration (before the step dispatch)."""
        if not self._dir:
            return
        self._seen += 1
        if not self._active and self._seen == self._skip + 1:
            import jax
            path = os.path.join(self._dir, self._tag)
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            self._active = True
            metrics.counter('skytpu_profile_captures_total',
                            'jax.profiler trace captures started.').inc()
        elif self._active and self._seen > self._skip + self._steps:
            self.stop()

    def stop(self) -> None:
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
