"""Head-side fleet aggregator: pull node windows, roll up, flag, publish.

The consumer half of the fleet telemetry plane. Each host keeps its own
``observability/timeseries`` ring buffer; this module pulls the latest
window from every host of a cluster over the ordinary command-runner
path (the same codegen-over-SSH idiom as job submit), computes
per-cluster rollups (mean / max / p95 per resource), flags **stale**
nodes (no fresh sample / dead skylet heartbeat) and **stragglers**
(utilization deviating from the slice mean by more than a configurable
threshold — the per-host-trace methodology of MLPerf-scale TPU pod
studies), publishes ``skytpu_node_*{cluster,node}`` and
``skytpu_cluster_*{cluster,stat}`` gauges through the process registry,
and journals ``node.stale`` / ``node.straggler`` events.

Consumers: ``skytpu top`` / ``skytpu status -v`` / the dashboard's
Fleet pane (via ``core.fleet_status``), the utilization-aware
``AutostopEvent`` (via :func:`local_cluster_snapshot`, running ON the
head), and optionally the serve autoscaler's utilization blend.
"""
import json
import os
import shlex
import time
from typing import Any, Dict, List, Optional, Sequence

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import journal
from skypilot_tpu.observability import metrics
from skypilot_tpu.skylet import constants
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

STRAGGLER_THRESHOLD_ENV = 'SKYTPU_STRAGGLER_THRESHOLD'
DEFAULT_STRAGGLER_THRESHOLD = 0.25  # |node − slice mean| in util points
STALE_SECONDS_ENV = 'SKYTPU_NODE_STALE_SECONDS'
DEFAULT_STALE_SECONDS = 120.0
DEFAULT_WINDOW_SECONDS = 120.0

_STATS_MARKER = '__NODE_STATS__'

# The resources rolled up per cluster and shown per node. Keys are the
# timeseries metric names; all are 0..1 utilizations.
UTIL_METRICS = ('cpu_util', 'mem_util', 'disk_util', 'accel_mem_util')


# Env-knob parsing: the shared helper (bad values degrade to defaults).
_env_float = common_utils.env_float


class FleetCodeGen:
    """Snippet run on each host to print its telemetry snapshot (the
    worker-pull "RPC", same idiom as ``job_lib.JobLibCodeGen``)."""

    _PRELUDE = (
        'import sys; '
        'sys.path.insert(0, __import__("os").path.expanduser('
        '"~/.skytpu/runtime")); '
        'from skypilot_tpu.observability import timeseries; ')

    @classmethod
    def node_snapshot(cls, window_seconds: float) -> str:
        body = (
            'import json; '
            f'snap = timeseries.node_snapshot({float(window_seconds)}); '
            f'print({_STATS_MARKER!r} + json.dumps(snap), flush=True)')
        return (f'{constants.accel_strip_shell_prefix()}'
                f'python3 -u -c {shlex.quote(cls._PRELUDE + body)}')


def parse_snapshot(output: str) -> Optional[Dict[str, Any]]:
    for line in output.splitlines():
        if line.startswith(_STATS_MARKER):
            try:
                return json.loads(line[len(_STATS_MARKER):])
            except ValueError:
                return None
    return None


def collect(runners: Sequence[Any],
            window_seconds: float = DEFAULT_WINDOW_SECONDS,
            timeout: float = 30.0) -> List[Optional[Dict[str, Any]]]:
    """Pull one snapshot per runner (parallel); unreachable hosts yield
    None — a node that cannot answer is exactly what the stale flag is
    for, so collection never raises for one bad host."""
    cmd = FleetCodeGen.node_snapshot(window_seconds)

    def _pull(runner) -> Optional[Dict[str, Any]]:
        try:
            rc, out, _ = runner.run(cmd, require_outputs=True,
                                    timeout=timeout)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'fleet pull {runner.node_id}: {e}')
            return None
        if rc != 0:
            return None
        return parse_snapshot(out)

    return list(subprocess_utils.run_in_parallel(_pull, list(runners)))


# Linear-interpolation percentile — the shared copy (the serving SLO
# surface computes its p50/p95/p99 with the same semantics).
percentile = common_utils.percentile


def aggregate(cluster_name: str,
              node_names: Sequence[str],
              snapshots: Sequence[Optional[Dict[str, Any]]],
              straggler_threshold: Optional[float] = None,
              stale_after: Optional[float] = None,
              now: Optional[float] = None) -> Dict[str, Any]:
    """Pure rollup over per-node snapshots → the fleet summary dict.

    Straggler rule: with ≥ 2 reporting nodes, a node whose window-mean
    utilization differs from the slice mean by more than the threshold
    (absolute, in utilization points) on CPU or accelerator memory is
    flagged — a TPU gang runs in lockstep, so one under- (or over-)
    utilized host is the canonical symptom of a wedged rank.
    """
    now = time.time() if now is None else now
    if straggler_threshold is None:
        straggler_threshold = _env_float(STRAGGLER_THRESHOLD_ENV,
                                         DEFAULT_STRAGGLER_THRESHOLD)
    if stale_after is None:
        stale_after = _env_float(STALE_SECONDS_ENV, DEFAULT_STALE_SECONDS)

    nodes: List[Dict[str, Any]] = []
    for name, snap in zip(node_names, snapshots):
        node: Dict[str, Any] = {'node': name, 'stale': False,
                                'straggler': False}
        if snap is None:
            node.update(unreachable=True, stale=True, sample_age=None,
                        skylet_tick_age=None)
            nodes.append(node)
            continue
        node['unreachable'] = False
        mean = snap.get('mean') or {}
        mx = snap.get('max') or {}
        last = snap.get('last') or {}
        for key in UTIL_METRICS + ('load1', 'cpu_cores_used'):
            if key in mean:
                node[key] = mean[key]
                node[key + '_max'] = mx.get(key, mean[key])
            if key in last:
                node[key + '_last'] = last[key]
        node['sample_age'] = snap.get('sample_age')
        node['skylet_tick_age'] = snap.get('skylet_tick_age')
        ages = [a for a in (node['sample_age'], node['skylet_tick_age'])
                if a is not None]
        node['stale'] = (not ages) or max(ages) > stale_after
        nodes.append(node)

    live = [n for n in nodes if not n['stale']]
    rollup: Dict[str, Dict[str, float]] = {}
    for key in UTIL_METRICS:
        vals = [n[key] for n in live if key in n]
        if not vals:
            continue
        rollup[key] = {
            'mean': sum(vals) / len(vals),
            'max': max(vals),
            'p95': percentile(vals, 95.0),
        }
    # Straggler detection against the slice mean.
    if len(live) >= 2:
        for key in ('cpu_util', 'accel_mem_util'):
            stats = rollup.get(key)
            if stats is None:
                continue
            for n in live:
                if key in n and abs(n[key] - stats['mean']) > \
                        straggler_threshold:
                    n['straggler'] = True
                    n.setdefault('straggler_reason', []).append(
                        f'{key}={n[key]:.2f} vs mean '
                        f'{stats["mean"]:.2f}')
    return {
        'cluster': cluster_name,
        'ts': now,
        'nodes': nodes,
        'rollup': rollup,
        'stale_nodes': [n['node'] for n in nodes if n['stale']],
        'stragglers': [n['node'] for n in nodes if n['straggler']],
    }


# Last journaled (stale, straggler) flags per (cluster, node): the
# journal records *transitions* into a flagged state, not every
# observation — publish() runs on every read path (`skytpu top --watch`,
# the dashboard's auto-refresh, status -v), and one persistently stale
# node journaled per refresh would evict the flight-recorder history
# the bounded journal exists to keep. Gauges carry the steady state.
_journaled_flags: Dict[Any, Any] = {}


def publish(summary: Dict[str, Any]) -> None:
    """Gauges + journal events for one fleet summary.

    Node gauges carry ``{cluster, node}``; cluster rollups carry
    ``{cluster, stat}`` with stat ∈ mean/max/p95 — both label sets are
    bounded by fleet size.
    """
    cluster = summary['cluster']
    for node in summary['nodes']:
        labels = (cluster, node['node'])
        if 'cpu_util' in node:
            metrics.gauge('skytpu_node_cpu_util',
                          'Per-node CPU utilization (window mean).',
                          labels=('cluster', 'node')).set(
                              node['cpu_util'], labels=labels)
        if 'mem_util' in node:
            metrics.gauge('skytpu_node_mem_util',
                          'Per-node memory utilization.',
                          labels=('cluster', 'node')).set(
                              node['mem_util'], labels=labels)
        if 'disk_util' in node:
            metrics.gauge('skytpu_node_disk_util',
                          'Per-node disk utilization of the skylet home '
                          'filesystem.',
                          labels=('cluster', 'node')).set(
                              node['disk_util'], labels=labels)
        if 'accel_mem_util' in node:
            metrics.gauge('skytpu_node_accel_mem_util',
                          'Per-node accelerator (HBM) memory '
                          'utilization.',
                          labels=('cluster', 'node')).set(
                              node['accel_mem_util'], labels=labels)
        if node.get('skylet_tick_age') is not None:
            metrics.gauge('skytpu_skylet_tick_age_seconds',
                          'Seconds since the node\'s skylet completed a '
                          'tick loop (heartbeat age; a dead skylet '
                          'grows without bound).',
                          labels=('cluster', 'node')).set(
                              node['skylet_tick_age'], labels=labels)
        metrics.gauge('skytpu_node_stale',
                      '1 when the node has no fresh sample or skylet '
                      'heartbeat.',
                      labels=('cluster', 'node')).set(
                          1.0 if node['stale'] else 0.0, labels=labels)
        prev_stale, prev_strag = _journaled_flags.get(
            (cluster, node['node']), (False, False))
        if node['stale'] and not prev_stale:
            journal.event(journal.EventKind.NODE_STALE,
                          f'cluster:{cluster}',
                          {'node': node['node'],
                           'sample_age': node.get('sample_age'),
                           'skylet_tick_age': node.get(
                               'skylet_tick_age'),
                           'unreachable': node.get('unreachable')})
        if node['straggler'] and not prev_strag:
            journal.event(journal.EventKind.NODE_STRAGGLER,
                          f'cluster:{cluster}',
                          {'node': node['node'],
                           'reason': '; '.join(
                               node.get('straggler_reason', []))})
        _journaled_flags[(cluster, node['node'])] = (node['stale'],
                                                    node['straggler'])
    stat_labels = ('cluster', 'stat')
    # Literal names so the tier-1 metric-name lint sees each family.
    gauges = {
        'cpu_util': metrics.gauge(
            'skytpu_cluster_cpu_util',
            'Cluster CPU utilization rollup.', labels=stat_labels),
        'mem_util': metrics.gauge(
            'skytpu_cluster_mem_util',
            'Cluster memory utilization rollup.', labels=stat_labels),
        'disk_util': metrics.gauge(
            'skytpu_cluster_disk_util',
            'Cluster disk utilization rollup.', labels=stat_labels),
        'accel_mem_util': metrics.gauge(
            'skytpu_cluster_accel_mem_util',
            'Cluster accelerator (HBM) memory rollup.',
            labels=stat_labels),
    }
    for key, stats in summary['rollup'].items():
        g = gauges.get(key)
        if g is None:
            continue
        for stat, value in stats.items():
            g.set(value, labels=(summary['cluster'], stat))


def collect_cluster(cluster_name: str, runners: Sequence[Any],
                    window_seconds: float = DEFAULT_WINDOW_SECONDS,
                    timeout: float = 30.0) -> Dict[str, Any]:
    """collect → aggregate → publish for one cluster's runners."""
    snaps = collect(runners, window_seconds=window_seconds,
                    timeout=timeout)
    names = [getattr(r, 'node_id', f'rank-{i}')
             for i, r in enumerate(runners)]
    summary = aggregate(cluster_name, names, snaps)
    publish(summary)
    return summary


# ----------------------------------------------- on-cluster (head) view


def local_cluster_snapshot(window_seconds: float = 30.0,
                           timeout: float = 15.0
                           ) -> Optional[Dict[str, Any]]:
    """Cluster utilization as seen FROM the head host (the autostop
    consumer): this node's timeseries directly, plus a best-effort pull
    of the other slice hosts from ``cluster_info.json``. Returns None
    when telemetry is unavailable (no samples yet, no cluster info) —
    callers must fall back to queue-only semantics, never block on it.
    """
    from skypilot_tpu.observability import timeseries
    info_path = constants.cluster_info_path()
    hosts: List[Dict[str, Any]] = []
    cluster_name = ''
    if os.path.exists(info_path):
        try:
            with open(info_path, encoding='utf-8') as f:
                info = json.load(f)
            hosts = info.get('hosts') or []
            cluster_name = info.get('cluster_name') or ''
        except (OSError, ValueError):
            hosts = []
    snaps: List[Optional[Dict[str, Any]]] = [
        timeseries.node_snapshot(window_seconds)]
    names = ['rank-0']
    if len(hosts) > 1:
        try:
            from skypilot_tpu.provision import provisioner
            workers = provisioner.runners_from_host_meta(hosts[1:])
            snaps.extend(collect(workers, window_seconds=window_seconds,
                                 timeout=timeout))
            names.extend(getattr(r, 'node_id', f'rank-{i + 1}')
                         for i, r in enumerate(workers))
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'fleet: worker pull failed: {e}')
    if all(s is None or not s.get('samples') for s in snaps):
        return None
    return aggregate(cluster_name or 'local', names, snaps)


def busiest_node(summary: Dict[str, Any],
                 keys: Sequence[str] = ('cpu_util_max', 'cpu_util_last',
                                        'cpu_util')
                 ) -> Optional[Dict[str, Any]]:
    """The node with the highest utilization by the first available of
    ``keys`` per node — the autostop evidence."""
    best = None
    best_val = -1.0
    for node in summary['nodes']:
        val = next((node[k] for k in keys if node.get(k) is not None),
                   None)
        if val is not None and val > best_val:
            best, best_val = node, val
    return best


# ------------------------------------------------------------- rendering


def _fmt_pct(v: Optional[float]) -> str:
    return f'{v * 100:5.1f}%' if v is not None else '-'


def _fmt_age(v: Optional[float]) -> str:
    if v is None:
        return '-'
    return f'{v:.0f}s' if v < 120 else f'{v / 60:.0f}m'


def node_flags(node: Dict[str, Any]) -> str:
    """``UNREACHABLE``/``STALE``/``STRAGGLER`` cell text for one node —
    shared by `skytpu top` and the dashboard's fleet pane."""
    flags = []
    if node.get('unreachable'):
        flags.append('UNREACHABLE')
    elif node.get('stale'):
        flags.append('STALE')
    if node.get('straggler'):
        flags.append('STRAGGLER')
    return ','.join(flags) or '-'


def format_top(summary: Dict[str, Any]) -> str:
    """The ``skytpu top`` table: one row per node plus a rollup line."""
    header = ('NODE', 'CPU', 'CPU(MAX)', 'MEM', 'DISK', 'ACCELMEM',
              'LOAD1', 'TICK', 'FLAGS')
    rows = []
    for n in summary['nodes']:
        rows.append((
            n['node'],
            _fmt_pct(n.get('cpu_util')),
            _fmt_pct(n.get('cpu_util_max')),
            _fmt_pct(n.get('mem_util')),
            _fmt_pct(n.get('disk_util')),
            _fmt_pct(n.get('accel_mem_util')),
            f"{n['load1']:.2f}" if n.get('load1') is not None else '-',
            _fmt_age(n.get('skylet_tick_age')),
            node_flags(n),
        ))
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              if rows else len(header[i]) for i in range(len(header))]
    lines = [f"== {summary['cluster']} "
             f"({len(summary['nodes'])} node(s)) =="]
    lines.append('  '.join(h.ljust(widths[i])
                           for i, h in enumerate(header)))
    for r in rows:
        lines.append('  '.join(c.ljust(widths[i])
                               for i, c in enumerate(r)))
    parts = []
    for key, label in (('cpu_util', 'cpu'), ('mem_util', 'mem'),
                       ('accel_mem_util', 'accelmem')):
        stats = summary['rollup'].get(key)
        if stats:
            parts.append(f'{label} mean={stats["mean"] * 100:.1f}% '
                         f'max={stats["max"] * 100:.1f}% '
                         f'p95={stats["p95"] * 100:.1f}%')
    if parts:
        lines.append('rollup: ' + '  '.join(parts))
    return '\n'.join(lines)


def format_status_line(summary: Dict[str, Any]) -> str:
    """One-line fleet digest for ``skytpu status -v``."""
    cpu = summary['rollup'].get('cpu_util')
    mem = summary['rollup'].get('mem_util')
    bits = [f"{len(summary['nodes'])} node(s)"]
    if cpu:
        bits.append(f'cpu {cpu["mean"] * 100:.0f}%/'
                    f'{cpu["max"] * 100:.0f}%max')
    if mem:
        bits.append(f'mem {mem["mean"] * 100:.0f}%')
    if summary['stale_nodes']:
        bits.append(f"stale: {','.join(summary['stale_nodes'])}")
    if summary['stragglers']:
        bits.append(f"stragglers: {','.join(summary['stragglers'])}")
    return '  '.join(bits)
