"""Control-plane flight recorder: an append-only structured event journal.

PR 2 gave the data plane numbers (``skytpu_*`` metrics); this is the
control plane's black box. Every orchestration step that decides a job's
fate — provision failover attempts, gang job submits, managed-job phase
transitions, recovery rounds, serve replica lifecycle — appends one
structured row here, stamped with the trace context
(``observability/trace``), so "why did my job take 40 minutes to
recover" is answerable *after the fact* from one sqlite file instead of
grepping process logs that may no longer exist.

Design rules:

* **Bounded vocabulary.** Event kinds come from :class:`EventKind` —
  an unregistered kind raises immediately (and a tier-1 lint scans call
  sites), so the journal stays greppable and dashboards don't chase
  free-text drift.
* **Best-effort writes.** A full disk or locked DB must never fail a
  launch: sqlite/OS errors are swallowed (the kind check is a
  programming error and is not).
* **Bounded size.** The table self-prunes to ``SKYTPU_JOURNAL_MAX_EVENTS``
  (default 20000) rows by rowid — O(1) per insert, no table scans on the
  control path.
* **Local by design.** Each host journals to its own
  ``~/.skytpu/journal.db``; the controller host's journal is the
  control-plane record the CLI/dashboard read. Cross-host linkage is by
  trace id, not by a shared database.
"""
import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import trace as trace_lib
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import db_utils

DISABLE_ENV = 'SKYTPU_JOURNAL_DISABLED'
# Comma-separated kind values: when set, ONLY those kinds are written
# (everything else is dropped silently). The benchmark harness uses it
# to keep slow-request breaches joinable (`skytpu trace`) while the
# measured engine passes stay free of per-tick admit/evict fsyncs.
ONLY_KINDS_ENV = 'SKYTPU_JOURNAL_ONLY_KINDS'
MAX_EVENTS_ENV = 'SKYTPU_JOURNAL_MAX_EVENTS'
DEFAULT_MAX_EVENTS = 20000
# job.phase rows are exempt from the generic prune (goodput recomputes
# from them) and capped separately, much higher — see event().
PHASE_EVENTS_CAP = 50000
# Journal file override: lets several in-process instances (the federated
# flight-recorder e2e: LB + prefill replica + decode replica) keep
# genuinely separate journals; in prod each host resolves its own
# ~/.skytpu/journal.db and the env is a deploy-time escape hatch (tmpfs,
# per-replica volumes).
DB_PATH_ENV = 'SKYTPU_JOURNAL_PATH'
# JournalBuffer bound: appends beyond this depth are dropped (and
# counted) instead of growing without bound while the writer is stalled.
QUEUE_DEPTH_ENV = 'SKYTPU_JOURNAL_QUEUE_DEPTH'
DEFAULT_QUEUE_DEPTH = 4096
# A flush slower than this journals ONE journal.stall row on recovery.
STALL_SECONDS_ENV = 'SKYTPU_JOURNAL_STALL_SECONDS'
DEFAULT_STALL_SECONDS = 1.0
# Hard cap on rows a /journal query endpoint will serve per call.
QUERY_LIMIT_ENV = 'SKYTPU_JOURNAL_QUERY_LIMIT'
DEFAULT_QUERY_LIMIT = 1000


class EventKind(enum.Enum):
    """The journal's full vocabulary. Add here FIRST; the tier-1 lint
    (test_observability.py) rejects call sites using strings that are
    not registered values."""
    # Span structure (emitted by trace.span()).
    SPAN_START = 'span.start'
    SPAN_END = 'span.end'
    # execution.py lifecycle.
    LAUNCH_START = 'launch.start'
    LAUNCH_DONE = 'launch.done'
    LAUNCH_ERROR = 'launch.error'
    # Provision failover engine (gang_backend.RetryingProvisioner).
    PROVISION_ATTEMPT = 'provision.attempt'
    PROVISION_FAILOVER = 'provision.failover'
    PROVISION_DONE = 'provision.done'
    # Provision orchestrator phases (provision/provisioner.py).
    PROVISION_WAIT_SSH = 'provision.wait_ssh'
    PROVISION_RUNTIME_SETUP = 'provision.runtime_setup'
    # Cluster backend (gang_backend.TpuGangBackend).
    BACKEND_JOB_SUBMIT = 'backend.job_submit'
    CLUSTER_TEARDOWN = 'cluster.teardown'
    # On-cluster runtime (skylet/).
    SKYLET_JOB_START = 'skylet.job_start'
    SKYLET_JOB_END = 'skylet.job_end'
    SKYLET_AUTOSTOP = 'skylet.autostop'
    SKYLET_EVENT_ERROR = 'skylet.event_error'
    # Fleet telemetry (observability/fleet.py).
    NODE_STALE = 'node.stale'
    NODE_STRAGGLER = 'node.straggler'
    # Managed jobs (jobs/).
    JOB_CREATED = 'job.created'
    JOB_PHASE = 'job.phase'
    JOB_RECOVER_START = 'job.recover_start'
    JOB_RECOVER_DONE = 'job.recover_done'
    RECOVERY_SWEEP = 'recovery.sweep'
    # Serve replica lifecycle (serve/replica_managers.py).
    REPLICA_TRANSITION = 'replica.transition'
    # Continuous-batching decode engine (models/engine.py): slot
    # admission/eviction — the scheduling decisions behind a serving
    # replica's latency, reconstructable per request id.
    ENGINE_ADMIT = 'engine.admit'
    ENGINE_EVICT = 'engine.evict'
    # Admission-control decisions: over-budget requests clamped or
    # rejected instead of crashing the serve loop.
    ENGINE_REJECT = 'engine.reject'
    # Request-telemetry plane (observability/request_trace.py): a
    # completed request that breached SKYTPU_SLOW_REQUEST_SECONDS or
    # the TTFT SLO journals its full phase timeline under the request's
    # own trace id (X-Request-Id), and an engine step that blew past
    # the stall threshold journals the step profile evidence.
    ENGINE_SLOW_REQUEST = 'engine.slow_request'
    ENGINE_STALL = 'engine.stall'
    # Speculative decoding + chunked prefill (models/engine.py):
    # journaled the first time each (bucket, chunk, spec_k) dispatch
    # shape traces, so recompile churn from new shapes is visible
    # instead of silently eating p99.
    ENGINE_COMPILE = 'engine.compile'
    # Serving-plane fault tolerance: the engine supervisor's crash →
    # fail-fast → rebuild → restart lifecycle (engine.crash carries the
    # traceback; restarts are bounded by SKYTPU_ENGINE_MAX_RESTARTS),
    # the model server's graceful-drain phases, and load-balancer
    # circuit-breaker ejections/reinstatements.
    ENGINE_CRASH = 'engine.crash'
    ENGINE_RESTART = 'engine.restart'
    SERVER_DRAIN = 'server.drain'
    LB_EJECT = 'lb.eject'
    # Fleet request tracing (serve/load_balancer.py): one event per
    # proxy hop inside the LB-side `lb.proxy` span — candidate
    # selection (with the circuit-breaker ejections traversed) and each
    # failover hop — journaled under the request's own trace id
    # (X-Request-Id), so `skytpu trace <request-id>` shows WHICH
    # replicas a request tried before it was answered.
    LB_HOP = 'lb.hop'
    # Fleet SLO rollup (observability/slo.py): a replica whose TTFT p95
    # deviates from the fleet median past the straggler threshold is
    # journaled on the flag TRANSITION (and again when it recovers),
    # with the evidence; the LB also feeds the flag to its circuit
    # breaker as a soft signal.
    REPLICA_STRAGGLER = 'replica.straggler'
    # Tensor-parallel serving (models/engine.py): journaled once at
    # engine start with the GSPMD mesh shape + device kinds, so perf
    # rounds and postmortems can attribute throughput to the topology
    # that served it.
    ENGINE_MESH = 'engine.mesh'
    # HBM accounting (models/engine.py): journaled once at engine start
    # beside engine.mesh — per-device weights vs KV-pool vs workspace
    # bytes on the serving mesh (also the skytpu_engine_hbm_bytes{kind}
    # gauges), so "what is eating this replica's HBM" is answerable
    # without a device debugger.
    ENGINE_HBM = 'engine.hbm'
    # Prefix-aware routing (serve/load_balancer.py): one event per
    # digest-keyed routing decision — the consistent-hash owner, and
    # whether the request landed on it (affinity hit) or was rehashed
    # away (excluded replica / load bound / saturated fleet) — nested
    # under the request's lb.proxy span.
    LB_ROUTE = 'lb.route'
    # Cross-replica prefix cache tier (models/engine.py): an admission
    # that radix-missed locally and consulted a peer (the LB-advertised
    # owner or SKYTPU_PREFIX_PEERS) journals the outcome — blocks
    # fetched and injected, miss, dtype/shape mismatch, or budget
    # exhaustion degrading to plain prefill.
    ENGINE_PREFIX_FETCH = 'engine.prefix_fetch'
    # Disaggregated prefill/decode (models/engine.py): a prefill-tier
    # admission streaming its KV blocks to a decode-tier peer journals
    # the handoff outcome — complete (all aligned blocks acked, slot
    # freed), degraded (push failure / peer backoff / truncated stream:
    # decode-in-place on the prefill replica), and the decode side's
    # injection result — so "who served this request's tokens" is
    # answerable per handoff.
    ENGINE_HANDOFF = 'engine.handoff'
    # Durable fleet KV cache (models/block_store.py): a cold-miss
    # admission that also missed its peers consulted the persistent
    # block store; the outcome (blocks fetched and injected, store
    # miss, mismatch rejection, store down → plain prefill) journals
    # under the request's trace id beside engine.prefix_fetch.
    ENGINE_STORE_FETCH = 'engine.store_fetch'
    # Write-behind spill (models/engine.py → block_store): an owner
    # that published a new radix run persisted it to the store (or
    # failed to, entering backoff) — so "which prefixes survive a
    # fleet restart" is answerable from the journal.
    STORE_SPILL = 'store.spill'
    # Digest-aware autoscaling (serve/autoscalers.py + controller):
    # a scale-up triggered by hot digest-family load journals the
    # family evidence, and a joining replica pre-warmed from the
    # store (POST /prewarm) journals the digests it warmed.
    AUTOSCALE_PREWARM = 'autoscale.prewarm'
    # Journal-plane self-observability (this module): a JournalBuffer
    # flush that blew past SKYTPU_JOURNAL_STALL_SECONDS journals ONE row
    # when writes recover — written via the direct (unbuffered,
    # un-chaos'd) path so a stalled journal can never recurse into
    # reporting its own stall.
    JOURNAL_STALL = 'journal.stall'


KINDS = frozenset(k.value for k in EventKind)

_TABLE = """
    PRAGMA journal_mode=WAL;
    PRAGMA synchronous=NORMAL;
    CREATE TABLE IF NOT EXISTS events (
        event_id INTEGER PRIMARY KEY AUTOINCREMENT,
        ts REAL,
        kind TEXT,
        entity TEXT,
        payload TEXT,
        trace_id TEXT,
        span_id TEXT,
        parent_span_id TEXT
    );
    CREATE INDEX IF NOT EXISTS idx_events_trace ON events(trace_id);
    CREATE INDEX IF NOT EXISTS idx_events_entity ON events(entity);
"""


def db_path() -> str:
    override = os.environ.get(DB_PATH_ENV)
    if override:
        return os.path.expanduser(override)
    return os.path.join(os.path.expanduser('~'), '.skytpu', 'journal.db')


# WAL + synchronous=NORMAL (in the schema script above): a commit appends
# to the write-ahead log instead of rewriting the main DB — on network
# filesystems this is the difference between ~200ms and sub-ms per write,
# and the durability trade (an OS crash may lose the tail of the log) is
# exactly the journal's documented best-effort contract.


_CONN = db_utils.SqliteConn('journal', db_path, _TABLE)
# Explicit-path connections (the ``db_path=`` parameter threaded through
# event/event_batch/query): one SqliteConn per resolved path, so several
# in-process instances can journal to separate files concurrently.
_conns_lock = threading.Lock()
_CONNS: Dict[str, db_utils.SqliteConn] = {}


def _db(db_path_override: Optional[str] = None) -> sqlite3.Connection:
    if not db_path_override:
        return _CONN.get()
    resolved = os.path.abspath(os.path.expanduser(db_path_override))
    with _conns_lock:
        conn = _CONNS.get(resolved)
        if conn is None:
            conn = _CONNS[resolved] = db_utils.SqliteConn(
                f'journal@{resolved}', lambda p=resolved: p, _TABLE)
    return conn.get()


def max_events() -> int:
    try:
        return int(os.environ.get(MAX_EVENTS_ENV, DEFAULT_MAX_EVENTS))
    except ValueError:
        return DEFAULT_MAX_EVENTS


def queue_depth() -> int:
    """JournalBuffer bound (re-read per call: tests shrink it to force
    the drop path without thousands of appends)."""
    try:
        return int(os.environ.get(QUEUE_DEPTH_ENV, DEFAULT_QUEUE_DEPTH))
    except ValueError:
        return DEFAULT_QUEUE_DEPTH


def stall_seconds() -> float:
    try:
        return float(os.environ.get(STALL_SECONDS_ENV,
                                    str(DEFAULT_STALL_SECONDS)))
    except ValueError:
        return DEFAULT_STALL_SECONDS


def query_limit() -> int:
    """Hard per-call row cap for the /journal query endpoints."""
    try:
        return int(os.environ.get(QUERY_LIMIT_ENV, DEFAULT_QUERY_LIMIT))
    except ValueError:
        return DEFAULT_QUERY_LIMIT


def enabled() -> bool:
    return os.environ.get(DISABLE_ENV, '0') != '1'


def kind_writable(kind_value: str) -> bool:
    """Whether this kind passes the ONLY_KINDS filter (always True when
    the env is unset). Re-read per call: the bench toggles it around
    measured passes."""
    only = os.environ.get(ONLY_KINDS_ENV, '')
    if not only:
        return True
    return kind_value in {k.strip() for k in only.split(',') if k.strip()}


def event(kind: Union[EventKind, str],
          entity: str,
          payload: Optional[Dict[str, Any]] = None,
          *,
          trace_id: Optional[str] = None,
          span_id: Optional[str] = None,
          parent_span_id: Optional[str] = None,
          ts: Optional[float] = None,
          db_path: Optional[str] = None) -> None:
    """Append one event. Trace/span default to the ambient context
    (``observability/trace``); entity is a ``type:name`` string, e.g.
    ``cluster:train-1-0``, ``job:3``, ``replica:svc/2``. ``db_path``
    targets an explicit journal file (defaults to this host's)."""
    kind_value = kind.value if isinstance(kind, EventKind) else str(kind)
    if kind_value not in KINDS:
        raise ValueError(
            f'Unregistered journal event kind {kind_value!r}; add it to '
            'observability.journal.EventKind first.')
    if not enabled() or not kind_writable(kind_value):
        return
    trace_id = trace_id or trace_lib.get_trace_id()
    span_id = span_id or trace_lib.get_span_id()
    if parent_span_id is None:
        parent_span_id = trace_lib.get_parent_span_id()
    try:
        with _db(db_path) as conn:
            cur = conn.execute(
                'INSERT INTO events (ts, kind, entity, payload, trace_id, '
                'span_id, parent_span_id) VALUES (?,?,?,?,?,?,?)',
                (time.time() if ts is None else ts, kind_value,
                 entity or '', json.dumps(payload or {}, default=str),
                 trace_id, span_id, parent_span_id))
            # Rowid-window prune: O(1) via the PK index, no ORDER BY
            # scan. job.phase rows are exempt — the goodput integral is
            # recomputed from them, and letting chatty span/provision
            # traffic evict a long-lived job's early phase events would
            # silently shrink its phase_seconds. They get their own much
            # larger cap below (they are low-volume: a handful per
            # transition, not per poll).
            cap = max_events()
            if cur.lastrowid is not None and cur.lastrowid > cap:
                conn.execute(
                    'DELETE FROM events WHERE event_id <= ? AND '
                    'kind != ?',
                    (cur.lastrowid - cap, EventKind.JOB_PHASE.value))
            if kind_value == EventKind.JOB_PHASE.value:
                conn.execute(
                    'DELETE FROM events WHERE kind = ? AND event_id '
                    'NOT IN (SELECT event_id FROM events WHERE kind = ? '
                    'ORDER BY event_id DESC LIMIT ?)',
                    (kind_value, kind_value, PHASE_EVENTS_CAP))
    except (sqlite3.Error, OSError):
        pass  # the flight recorder must never take the plane down


def event_batch(items: Sequence[tuple],
                db_path: Optional[str] = None) -> int:
    """Append many events in ONE transaction (one fsync) — the hot-path
    form. Per-event ``event()`` pays a commit per call, which is fine at
    control-plane rates; a serving engine journaling admissions and
    evictions per scheduling tick uses this instead (models/engine.py
    buffers and flushes per tick).

    Returns the number of rows committed (filtered/disabled rows are not
    counted — they were dropped by policy, not lost), or ``-1`` when the
    transaction failed (sqlite/OS error): one transaction means the
    WHOLE batch was lost, which the JournalBuffer counts as
    ``write_error`` drops.

    Each item is ``(kind, entity, payload, ts)`` — ts stamped by the
    caller at buffer time, so batching does not skew the timeline.
    Trace context is resolved once at write time (the buffering caller
    is single-threaded per engine loop, so ambient context is stable).
    An optional fifth element overrides the trace context for THAT row:
    a bare string overrides the trace id (span/parent nulled — the
    pre-fleet-tracing form), and a ``(trace_id, span_id,
    parent_span_id)`` tuple overrides all three — the engine stamps
    request-scoped events (admit/evict/slow_request) with the request's
    own trace id (the server's ``X-Request-Id``) AND the server-side
    request span, so ``skytpu trace <request-id>`` reconstructs one
    request's timeline nested under the HTTP spans that carried it.
    """
    if not items:
        return 0
    rows = []
    for item in items:
        kind, entity, payload, ts = item[:4]
        override = item[4] if len(item) > 4 else None
        kind_value = (kind.value if isinstance(kind, EventKind)
                      else str(kind))
        if kind_value not in KINDS:
            raise ValueError(
                f'Unregistered journal event kind {kind_value!r}; add it '
                'to observability.journal.EventKind first.')
        if isinstance(override, (tuple, list)):
            row_ctx = (tuple(override) + (None, None, None))[:3]
        elif override:
            row_ctx = (override, None, None)
        else:
            row_ctx = None
        if not kind_writable(kind_value):
            continue
        rows.append((ts, kind_value, entity or '',
                     json.dumps(payload or {}, default=str), row_ctx))
    if not enabled() or not rows:
        return 0
    trace_id = trace_lib.get_trace_id()
    span_id = trace_lib.get_span_id()
    parent = trace_lib.get_parent_span_id()
    try:
        with _db(db_path) as conn:
            cur = None
            for ts, kind_value, entity, payload_json, row_ctx in rows:
                cur = conn.execute(
                    'INSERT INTO events (ts, kind, entity, payload, '
                    'trace_id, span_id, parent_span_id) '
                    'VALUES (?,?,?,?,?,?,?)',
                    (ts, kind_value, entity, payload_json,
                     row_ctx[0] if row_ctx else trace_id,
                     row_ctx[1] if row_ctx else span_id,
                     row_ctx[2] if row_ctx else parent))
            cap = max_events()
            if cur is not None and cur.lastrowid is not None \
                    and cur.lastrowid > cap:
                conn.execute(
                    'DELETE FROM events WHERE event_id <= ? AND '
                    'kind != ?',
                    (cur.lastrowid - cap, EventKind.JOB_PHASE.value))
    except (sqlite3.Error, OSError):
        return -1  # the flight recorder must never take the plane down
    return len(rows)


class JournalBuffer:
    """Bounded, lock-guarded buffer of :func:`event_batch` rows for
    hot-path writers (the decode engine's tick loop, the LB's proxy
    handler): appends are lock+list-append cheap and NEVER block on the
    database — at ``SKYTPU_JOURNAL_QUEUE_DEPTH`` the row is dropped and
    counted (``skytpu_journal_dropped_total{reason="queue_full"}``)
    instead of growing without bound behind a stalled disk. One
    ``flush()`` writes the whole batch in a single transaction;
    ``flush(wait=False)`` hands the write to a short-lived background
    thread so the engine step loop never sits behind an fsync. The
    optional ``override`` per row is event_batch's fifth element (a
    trace-id string or a ``(trace, span, parent)`` tuple).

    The buffer observes itself: flush latency/batch counters feed the
    ``skytpu_journal_*`` self-metrics and :meth:`stats`, and a flush
    slower than ``SKYTPU_JOURNAL_STALL_SECONDS`` journals ONE
    ``journal.stall`` row on recovery (via the direct, unbuffered write
    path — reporting a stall must not re-enter the stalled path).
    """

    # Lock discipline (skytpu lint): appenders race the flusher; the
    # self-accounting counters ride the same lock. Metric increments and
    # the actual sqlite write happen OUTSIDE the lock — a wedged journal
    # write must never wedge appenders.
    _GUARDED_BY = {
        '_buf': '_lock',
        '_appended': '_lock',
        '_written': '_lock',
        '_dropped_queue_full': '_lock',
        '_dropped_write_error': '_lock',
        '_flushes': '_lock',
        '_flush_secs': '_lock',
        '_pending_stall': '_lock',
        '_async_inflight': '_lock',
        '_async_pending': '_lock',
    }

    # Flush-latency ring for the stats() p95 (not a full histogram —
    # the registry metric has the buckets).
    _FLUSH_RING = 256

    def __init__(self, db_path: Optional[str] = None,
                 entity: str = 'journal'):
        self._lock = threading.Lock()
        # Serializes _flush_once bodies: a flush(wait=True) must not
        # return while an async flush that already claimed rows is
        # still committing them, or "flush then read" callers miss the
        # tail of the batch. Never held while taking _lock-only paths'
        # callers (append stays lock-cheap and never touches it).
        self._write_lock = threading.Lock()
        self._buf: List[tuple] = []
        self._db_path = db_path
        self._entity = entity
        self._appended = 0
        self._written = 0
        self._dropped_queue_full = 0
        self._dropped_write_error = 0
        self._flushes = 0
        self._flush_secs: List[float] = []
        self._pending_stall: Optional[Dict[str, Any]] = None
        self._async_inflight = False
        self._async_pending = False

    @property
    def db_path(self) -> Optional[str]:
        return self._db_path

    def append(self, kind, entity: str, payload: Optional[Dict[str, Any]],
               override=None, ts: Optional[float] = None) -> bool:
        """Buffer one row. Returns False when the bounded queue was full
        and the row was dropped (counted, never blocking)."""
        row = (kind, entity, payload,
               time.time() if ts is None else ts, override)
        with self._lock:
            if len(self._buf) >= queue_depth():
                self._dropped_queue_full += 1
                dropped = True
            else:
                self._buf.append(row)
                self._appended += 1
                dropped = False
        if dropped:
            # Outside the buffer lock: the registry takes its own locks
            # and the drop path must never hold ours while doing so.
            metrics_lib.counter(
                'skytpu_journal_dropped_total',
                'Journal rows lost (bounded queue full, or a failed '
                'batch transaction).',
                labels=('reason',)).inc(labels=('queue_full',))
        return not dropped

    def flush(self, wait: bool = True) -> None:
        """Write buffered rows. ``wait=True`` (teardown, stats, tests)
        blocks until the batch is committed; ``wait=False`` (the engine
        step loop) schedules the write on a short-lived daemon thread
        and returns immediately — concurrent calls coalesce, so a flush
        stalled behind a wedged disk queues at most one follow-up."""
        if wait:
            self._flush_once()
            return
        with self._lock:
            if self._async_inflight:
                self._async_pending = True
                return
            self._async_inflight = True
        threading.Thread(target=self._async_flush,
                         name='journal-flush', daemon=True).start()

    def _async_flush(self) -> None:
        while True:
            self._flush_once()
            with self._lock:
                if not self._async_pending:
                    self._async_inflight = False
                    return
                self._async_pending = False

    def _flush_once(self) -> None:
        # Taken before rows are claimed and held through the commit:
        # once a sync flush acquires it, every row claimed by an
        # earlier (possibly async) flush is already durable.
        with self._write_lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return
        t0 = time.monotonic()
        if chaos.should_fire('journal_write_stall'):
            time.sleep(chaos.journal_stall_seconds())
        if chaos.should_fire('journal_disk_full'):
            written = -1
        else:
            written = event_batch(buf, db_path=self._db_path)
        dt = time.monotonic() - t0
        stall_note = None
        with self._lock:
            self._flushes += 1
            self._flush_secs.append(dt)
            del self._flush_secs[:-self._FLUSH_RING]
            if written < 0:
                self._dropped_write_error += len(buf)
            else:
                self._written += written
            if dt >= stall_seconds():
                note = self._pending_stall or {'stall_seconds': 0.0,
                                               'stalled_flushes': 0}
                note['stall_seconds'] = max(note['stall_seconds'], dt)
                note['stalled_flushes'] += 1
                self._pending_stall = note
            elif self._pending_stall is not None:
                # Recovery: this flush was fast again.
                stall_note = self._pending_stall
                self._pending_stall = None
                stall_note['dropped_queue_full'] = self._dropped_queue_full
                stall_note['dropped_write_error'] = \
                    self._dropped_write_error
        metrics_lib.histogram(
            'skytpu_journal_flush_seconds',
            'JournalBuffer batch-commit latency.').observe(dt)
        if written > 0:
            metrics_lib.counter(
                'skytpu_journal_events_total',
                'Journal rows committed through the buffered '
                'path.').inc(written)
        elif written < 0:
            metrics_lib.counter(
                'skytpu_journal_dropped_total',
                'Journal rows lost (bounded queue full, or a failed '
                'batch transaction).',
                labels=('reason',)).inc(len(buf),
                                        labels=('write_error',))
        if stall_note is not None:
            # Direct synchronous write, NOT through this buffer and not
            # through the chaos'd batch path — cannot recurse.
            event(EventKind.JOURNAL_STALL, self._entity, stall_note,
                  db_path=self._db_path)

    def stats(self) -> Dict[str, Any]:
        """Self-observability snapshot (the bench detail block and the
        engine's journal_stats surface)."""
        with self._lock:
            secs = sorted(self._flush_secs)
            p95 = secs[int(0.95 * (len(secs) - 1))] if secs else 0.0
            return {
                'buffered': len(self._buf),
                'appended': self._appended,
                'written': self._written,
                'dropped_queue_full': self._dropped_queue_full,
                'dropped_write_error': self._dropped_write_error,
                'dropped': (self._dropped_queue_full
                            + self._dropped_write_error),
                'flushes': self._flushes,
                'flush_p95_seconds': p95,
            }


def query(kinds: Optional[Sequence[Union[EventKind, str]]] = None,
          entity: Optional[str] = None,
          entity_prefix: Optional[str] = None,
          trace_id: Optional[str] = None,
          since_id: Optional[int] = None,
          limit: int = 200,
          ascending: bool = False,
          db_path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Read events, newest first by default (``ascending=True`` for
    timeline/trace rendering). Payloads come back as dicts."""
    clauses, args = [], []
    if kinds:
        values = [k.value if isinstance(k, EventKind) else str(k)
                  for k in kinds]
        clauses.append(
            f'kind IN ({",".join("?" * len(values))})')
        args.extend(values)
    if entity is not None:
        clauses.append('entity = ?')
        args.append(entity)
    if entity_prefix is not None:
        # Escape LIKE wildcards: entities legitimately contain '_'.
        escaped = (entity_prefix.replace('\\', '\\\\')
                   .replace('%', '\\%').replace('_', '\\_'))
        clauses.append("entity LIKE ? ESCAPE '\\'")
        args.append(escaped + '%')
    if trace_id is not None:
        clauses.append('trace_id = ?')
        args.append(trace_id)
    if since_id is not None:
        clauses.append('event_id > ?')
        args.append(since_id)
    where = f' WHERE {" AND ".join(clauses)}' if clauses else ''
    order = 'ASC' if ascending else 'DESC'
    try:
        rows = _db(db_path).execute(
            f'SELECT * FROM events{where} ORDER BY event_id {order} '
            'LIMIT ?', (*args, limit)).fetchall()
    except (sqlite3.Error, OSError):
        return []
    out = []
    for r in rows:
        d = dict(r)
        try:
            d['payload'] = json.loads(d['payload'] or '{}')
        except ValueError:
            d['payload'] = {}
        out.append(d)
    return out


def serve_query(params: Dict[str, Any],
                db_path: Optional[str] = None,
                host: str = '') -> Dict[str, Any]:
    """The /journal query endpoint, shared by the model server, the LB,
    and the API server: filter (trace id, kinds, entity/prefix,
    since-rowid cursor) + a hard ``SKYTPU_JOURNAL_QUERY_LIMIT`` row cap
    per call. Unknown kinds are filtered out and malformed values
    degrade to defaults — the journal read plane must not 500 on a
    typo'd cursor. Rows come back oldest-first within the page;
    ``next_since_id`` is the resume cursor for the federation poll
    (``skytpu events --since``)."""
    def _int(key: str) -> Optional[int]:
        try:
            return int(params[key])
        except (KeyError, TypeError, ValueError):
            return None

    kinds = params.get('kinds')
    if isinstance(kinds, str):
        kinds = [k.strip() for k in kinds.split(',') if k.strip()]
    kinds = [k for k in (kinds or []) if k in KINDS] or None
    cap = query_limit()
    limit = _int('limit')
    limit = cap if limit is None else max(1, min(limit, cap))
    since_id = _int('since_id')
    trace_id = params.get('trace_id') or params.get('trace') or None
    # A cursor pull pages oldest-first (resumable); the initial pull
    # serves the NEWEST rows (what `events` shows), re-sorted so the
    # page itself always reads oldest-first.
    ascending = since_id is not None or trace_id is not None
    rows = query(kinds=kinds,
                 entity=params.get('entity') or None,
                 entity_prefix=params.get('entity_prefix') or None,
                 trace_id=trace_id, since_id=since_id, limit=limit,
                 ascending=ascending, db_path=db_path)
    if not ascending:
        rows.reverse()
    return {
        'host': host,
        'count': len(rows),
        'events': rows,
        'next_since_id': max((r['event_id'] for r in rows),
                             default=since_id or 0),
    }


def resolve_trace_prefix(prefix: str,
                         db_path: Optional[str] = None) -> List[str]:
    """Full trace ids matching a prefix — resolved in SQL so even traces
    whose events sit deep in the journal are found (`skytpu events`
    prints 8-char prefixes)."""
    escaped = (prefix.replace('\\', '\\\\')
               .replace('%', '\\%').replace('_', '\\_'))
    try:
        rows = _db(db_path).execute(
            "SELECT DISTINCT trace_id FROM events WHERE trace_id "
            "LIKE ? ESCAPE '\\'", (escaped + '%',)).fetchall()
    except (sqlite3.Error, OSError):
        return []
    return sorted(r['trace_id'] for r in rows if r['trace_id'])


# ------------------------------------------------------------- rendering


def _fmt_ts(ts: float) -> str:
    return time.strftime('%m-%d %H:%M:%S', time.localtime(ts))


def _fmt_payload(payload: Dict[str, Any], skip: Sequence[str] = ()) -> str:
    # One line per event, always: payload values (error strings with
    # embedded stderr, multi-line reasons) must not break the table.
    parts = [f'{k}={v}'.replace('\n', '\\n').replace('\r', '')
             for k, v in payload.items()
             if k not in skip and v not in (None, '', {})]
    return ' '.join(parts)


def format_event_line(e: Dict[str, Any]) -> str:
    """One event as a stable, non-tabular line (the --follow stream —
    per-event table widths would make columns jump on every row).
    Federated rows carry a ``host`` tag (which journal served the row);
    local rows don't and the column stays out of the way."""
    host = f'  @{e["host"]}' if e.get('host') else ''
    return (f'{_fmt_ts(e["ts"])}  {e["kind"]:<24} '
            f'{(e["entity"] or "-"):<24} '
            f'{(e["trace_id"] or "")[:8] or "-":<8}  '
            f'{_fmt_payload(e["payload"]) or "-"}{host}')


def format_events(events: List[Dict[str, Any]]) -> str:
    """Flat timeline table for ``skytpu events`` (pass oldest-first).
    A HOST column appears when any row is host-tagged (federated)."""
    if not events:
        return 'No journal events.'
    with_host = any(e.get('host') for e in events)
    header = ('TIME', 'KIND', 'ENTITY', 'TRACE', 'DETAIL')
    if with_host:
        header = ('TIME', 'HOST', 'KIND', 'ENTITY', 'TRACE', 'DETAIL')
    rows = []
    for e in events:
        row = (_fmt_ts(e['ts']), e['kind'], e['entity'] or '-',
               (e['trace_id'] or '')[:8] or '-',
               _fmt_payload(e['payload']) or '-')
        if with_host:
            row = (row[0], e.get('host') or '-') + row[1:]
        rows.append(row)
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    lines = ['  '.join(h.ljust(widths[i]) for i, h in enumerate(header))]
    for r in rows:
        lines.append('  '.join(c.ljust(widths[i]) for i, c in enumerate(r)))
    return '\n'.join(lines)


class _SpanNode:
    __slots__ = ('span_id', 'name', 'entity', 'host', 'start', 'end',
                 'error', 'events', 'children', 'parent')

    def __init__(self, span_id: Optional[str]):
        self.span_id = span_id
        self.name: Optional[str] = None
        self.entity = ''
        self.host = ''
        self.start: Optional[float] = None
        self.end: Optional[float] = None
        self.error: Optional[str] = None
        self.events: List[Dict[str, Any]] = []
        self.children: List['_SpanNode'] = []
        self.parent: Optional[str] = None


def span_tree(events: List[Dict[str, Any]]) -> List[_SpanNode]:
    """Group one trace's events (oldest-first) into a span forest.

    Spans are declared by span.start/span.end pairs; events whose span id
    never got a span.start (e.g. emitted by a process that inherited the
    span over env) still show up, attached to a synthetic node.
    """
    nodes: Dict[Optional[str], _SpanNode] = {}

    def node(span_id: Optional[str]) -> _SpanNode:
        if span_id not in nodes:
            nodes[span_id] = _SpanNode(span_id)
        return nodes[span_id]

    for e in events:
        n = node(e['span_id'])
        kind = e['kind']
        if kind == EventKind.SPAN_START.value:
            n.name = e['payload'].get('name')
            n.entity = e['entity'] or n.entity
            n.host = e.get('host') or n.host
            n.start = e['ts']
            n.parent = e['parent_span_id']
        elif kind == EventKind.SPAN_END.value:
            n.end = e['ts']
            n.error = e['payload'].get('error')
        else:
            n.events.append(e)
            n.entity = n.entity or (e['entity'] or '')
            n.host = n.host or (e.get('host') or '')
            if n.start is None:
                n.start = e['ts']
            if n.parent is None:
                n.parent = e['parent_span_id']
    roots: List[_SpanNode] = []
    for n in nodes.values():
        parent = nodes.get(n.parent) if n.parent else None
        if parent is not None and parent is not n:
            parent.children.append(n)
        else:
            roots.append(n)
    for n in nodes.values():
        n.children.sort(key=lambda c: c.start or 0)
    roots.sort(key=lambda c: c.start or 0)
    return roots


def format_trace(trace_id: str,
                 events: Optional[List[Dict[str, Any]]] = None) -> str:
    """Render one trace as an indented span tree with durations."""
    if events is None:
        events = query(trace_id=trace_id, ascending=True, limit=10000)
    if not events:
        return f'No events for trace {trace_id!r}.'
    t0 = events[0]['ts']
    t_last = max(e['ts'] for e in events)
    lines = [f'trace {trace_id}  ({len(events)} events, '
             f'{t_last - t0:.1f}s, started {_fmt_ts(t0)})']

    def _dur(n: _SpanNode) -> str:
        if n.start is None:
            return ''
        end = n.end if n.end is not None else t_last
        return f'  {end - n.start:.1f}s'

    def _render(n: _SpanNode, indent: str) -> None:
        # Events journaled outside any span (e.g. the client process
        # before the controller exists) collect under '(no span)'.
        label = n.name or (f'span {n.span_id}' if n.span_id
                           else '(no span)')
        where = n.entity
        if n.host:
            where = f'{where}@{n.host}' if where else f'@{n.host}'
        suffix = f'  [{where}]' if where else ''
        err = f'  ERROR: {n.error}' if n.error else ''
        lines.append(f'{indent}{label}{suffix}{_dur(n)}{err}')
        for e in n.events:
            detail = _fmt_payload(e['payload'], skip=('name',))
            detail = f'  {detail}' if detail else ''
            host = f'  @{e["host"]}' if e.get('host') else ''
            lines.append(f'{indent}  +{e["ts"] - t0:7.1f}s '
                         f'{e["kind"]}  {e["entity"] or "-"}{detail}'
                         f'{host}')
        for c in n.children:
            _render(c, indent + '  ')

    for root in span_tree(events):
        _render(root, '  ')
    return '\n'.join(lines)
