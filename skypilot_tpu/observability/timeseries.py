"""Per-node resource time series: sampler + size-bounded sqlite ring buffer.

The machine-telemetry leg of the observability stack (events → journal,
in-process numbers → metrics registry, **machines → here**). Each host
samples itself from ``/proc`` (CPU, memory, disk, load) plus accelerator
state (JAX ``device.memory_stats()`` when a non-CPU platform is
configured; graceful omission on CPU-only nodes) into a local sqlite
ring buffer, downsampled in place (raw → 1m → 10m rollups) so retention
stays bounded like the journal. The head-side aggregator
(``observability/fleet.py``) pulls each worker's latest window over the
ordinary command-runner path.

Design rules (mirroring ``observability/journal.py``):

* **Best-effort writes** — a full disk must never kill the skylet tick
  loop; sqlite/OS errors are swallowed by the sampler event.
* **Bounded size** — every resolution is pruned to
  ``SKYTPU_TIMESERIES_MAX_ROWS`` rows by rowid window (O(1) per insert).
* **Local by design** — each host writes its own
  ``<skylet home>/.skytpu/timeseries.db``; cross-host aggregation is a
  pull, not a shared database.

CPU semantics: on a real host, utilization comes from the machine-wide
``/proc/stat`` jiffy deltas (one node == one host). On a Local-cloud
"node" (a process tree sharing the machine with its siblings —
``SKYTPU_NODE_DIR`` set), machine-wide counters would charge every node
with every other node's work, so utilization is computed from the node's
OWN process tree instead (the same ``/proc/*/environ`` membership scan
teardown uses), normalized by the machine's core count. Either way the
published number is "fraction of this node's cores in use".
"""
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.skylet import constants
from skypilot_tpu.utils import db_utils

MAX_ROWS_ENV = 'SKYTPU_TIMESERIES_MAX_ROWS'
DEFAULT_MAX_ROWS = 4096
PROC_ROOT_ENV = 'SKYTPU_PROC_ROOT'  # test override for /proc parsing

# Resolution ladder: raw samples roll into 1-minute rows, 1-minute rows
# into 10-minute rows. Completed buckets only — a bucket is aggregated
# once its window has fully elapsed. Source rows outlive their rollup by
# the retention below (the fleet pull reads trailing RAW windows), then
# are dropped; the row cap is the hard bound either way.
ROLLUPS: Tuple[Tuple[str, str, float], ...] = (
    ('raw', '1m', 60.0),
    ('1m', '10m', 600.0),
)
RESOLUTIONS = ('raw', '1m', '10m')
RETENTION_SECONDS = {'raw': 600.0, '1m': 7200.0}

_TABLE = """
    CREATE TABLE IF NOT EXISTS samples (
        sample_id INTEGER PRIMARY KEY AUTOINCREMENT,
        ts REAL,
        res TEXT,
        n INTEGER DEFAULT 1,
        metrics TEXT
    );
    CREATE INDEX IF NOT EXISTS idx_samples_res_ts ON samples(res, ts);
    CREATE TABLE IF NOT EXISTS rollup_state (
        res TEXT PRIMARY KEY,
        through_ts REAL
    );
"""


def db_path() -> str:
    return os.path.join(constants.skytpu_dir(), 'timeseries.db')


_CONN = db_utils.SqliteConn('timeseries', db_path, _TABLE)


def _db() -> sqlite3.Connection:
    return _CONN.get()


def max_rows() -> int:
    try:
        return int(os.environ.get(MAX_ROWS_ENV, DEFAULT_MAX_ROWS))
    except ValueError:
        return DEFAULT_MAX_ROWS


def _loads(blob: Optional[str]) -> Dict[str, float]:
    try:
        return json.loads(blob or '{}')
    except ValueError:
        return {}


# ----------------------------------------------------------------- writes


def record(metrics: Dict[str, float], ts: Optional[float] = None) -> None:
    """Append one raw sample (numeric metrics only) and prune."""
    ts = time.time() if ts is None else ts
    clean = {k: float(v) for k, v in metrics.items()
             if isinstance(v, (int, float))}
    with _db() as conn:
        conn.execute(
            'INSERT INTO samples (ts, res, n, metrics) VALUES (?,?,1,?)',
            (ts, 'raw', json.dumps(clean)))
        _prune(conn, 'raw')


def _prune(conn: sqlite3.Connection, res: str) -> None:
    # Per-resolution cap (journal idiom, adapted): rowids are shared
    # across resolutions, so the window is expressed as "delete this
    # resolution's oldest overflow" — indexed on (res, ts), no full
    # scans at the default cap.
    cap = max_rows()
    n = conn.execute('SELECT COUNT(*) AS c FROM samples WHERE res=?',
                     (res,)).fetchone()['c']
    if n > cap:
        conn.execute(
            'DELETE FROM samples WHERE sample_id IN ('
            'SELECT sample_id FROM samples WHERE res=? '
            'ORDER BY sample_id ASC LIMIT ?)', (res, n - cap))


def _merge_window(rows: List[Tuple[int, Dict[str, float]]]
                  ) -> Tuple[int, Dict[str, float]]:
    """n-weighted mean per metric plus ``<metric>_max`` across
    ``(n, metrics)`` pairs.

    Rolled-up rows already carry ``_max`` keys; maxes merge by max, means
    by weight, so a 10m row is exact over its 1m inputs.
    """
    total_n = 0
    sums: Dict[str, float] = {}
    maxes: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for n, m in rows:
        n = int(n or 1)
        total_n += n
        for k, v in m.items():
            if not isinstance(v, (int, float)):
                continue
            if k.endswith('_max'):
                maxes[k] = max(maxes.get(k, v), v)
            else:
                sums[k] = sums.get(k, 0.0) + v * n
                counts[k] = counts.get(k, 0) + n
                mk = k + '_max'
                maxes[mk] = max(maxes.get(mk, v), v)
    out = {k: s / counts[k] for k, s in sums.items() if counts.get(k)}
    out.update(maxes)
    return max(total_n, 1), out


def rollup(now: Optional[float] = None) -> None:
    """Aggregate completed windows one rung down the resolution ladder.

    Idempotent and cheap: each (src → dst) pair remembers the timestamp
    it has rolled through, so a tick only touches buckets that completed
    since the last call.
    """
    now = time.time() if now is None else now
    with _db() as conn:
        for src, dst, width in ROLLUPS:
            row = conn.execute(
                'SELECT through_ts FROM rollup_state WHERE res=?',
                (dst,)).fetchone()
            through = row['through_ts'] if row else None
            if through is None:
                first = conn.execute(
                    'SELECT MIN(ts) AS t FROM samples WHERE res=?',
                    (src,)).fetchone()['t']
                if first is None:
                    continue
                through = (first // width) * width
            horizon = (now // width) * width  # current bucket: incomplete
            bucket = through
            while bucket + width <= horizon:
                rows = conn.execute(
                    'SELECT n, metrics FROM samples WHERE res=? AND '
                    'ts>=? AND ts<?', (src, bucket, bucket + width)
                ).fetchall()
                if rows:
                    n, merged = _merge_window([
                        (r['n'], _loads(r['metrics'])) for r in rows])
                    conn.execute(
                        'INSERT INTO samples (ts, res, n, metrics) '
                        'VALUES (?,?,?,?)',
                        (bucket, dst, n, json.dumps(merged)))
                bucket += width
            if bucket != through:
                conn.execute(
                    'INSERT INTO rollup_state (res, through_ts) '
                    'VALUES (?,?) ON CONFLICT(res) DO UPDATE SET '
                    'through_ts=excluded.through_ts', (dst, bucket))
                _prune(conn, dst)
            # Retention: a source row is deletable once its bucket has
            # been rolled (ts < through) AND it has aged out of the
            # trailing-window reads.
            keep = RETENTION_SECONDS.get(src)
            if keep is not None:
                conn.execute(
                    'DELETE FROM samples WHERE res=? AND ts<? AND ts<?',
                    (src, bucket, now - keep))


# ------------------------------------------------------------------ reads


def query(res: str = 'raw', since: Optional[float] = None,
          limit: int = 1000) -> List[Dict[str, Any]]:
    clauses: List[str] = ['res=?']
    args: List[Any] = [res]
    if since is not None:
        clauses.append('ts>=?')
        args.append(since)
    try:
        rows = _db().execute(
            f'SELECT * FROM samples WHERE {" AND ".join(clauses)} '
            'ORDER BY ts ASC LIMIT ?', (*args, limit)).fetchall()
    except (sqlite3.Error, OSError):
        return []
    out = []
    for r in rows:
        d = dict(r)
        try:
            d['metrics'] = json.loads(d['metrics'] or '{}')
        except ValueError:
            d['metrics'] = {}
        out.append(d)
    return out


def window(seconds: float, now: Optional[float] = None
           ) -> Dict[str, Any]:
    """Aggregate the trailing raw window: per-metric mean + max, the
    newest sample verbatim, and the age of that sample."""
    now = time.time() if now is None else now
    rows = query('raw', since=now - seconds)
    if not rows:
        return {'samples': 0, 'mean': {}, 'max': {}, 'last': {},
                'last_ts': None}
    _, merged = _merge_window([(r['n'], r['metrics']) for r in rows])
    mean = {k: v for k, v in merged.items() if not k.endswith('_max')}
    mx = {k[:-4]: v for k, v in merged.items() if k.endswith('_max')}
    last = rows[-1]
    return {'samples': len(rows), 'mean': mean, 'max': mx,
            'last': last['metrics'], 'last_ts': last['ts']}


def skylet_heartbeat_path() -> str:
    return os.path.join(constants.skytpu_dir(), 'skylet.heartbeat')


def node_snapshot(window_seconds: float = 120.0,
                  now: Optional[float] = None) -> Dict[str, Any]:
    """What the fleet aggregator pulls from each host: the trailing
    window, sample freshness, and the skylet tick age (heartbeat file
    mtime — a dead skylet stops touching it, so the head can tell a
    wedged daemon from a quiet one)."""
    now = time.time() if now is None else now
    snap = window(window_seconds, now=now)
    snap['sample_age'] = (None if snap['last_ts'] is None
                          else max(0.0, now - snap['last_ts']))
    try:
        snap['skylet_tick_age'] = max(
            0.0, now - os.path.getmtime(skylet_heartbeat_path()))
    except OSError:
        snap['skylet_tick_age'] = None
    return snap


# ---------------------------------------------------------------- sampler


def _proc_root() -> str:
    return os.environ.get(PROC_ROOT_ENV, '/proc')


def _read_proc_stat_jiffies(proc: str) -> Optional[Tuple[float, float]]:
    """(busy, total) jiffies from the aggregate cpu line."""
    try:
        with open(os.path.join(proc, 'stat'), encoding='utf-8') as f:
            line = f.readline()
    except OSError:
        return None
    parts = line.split()
    if not parts or parts[0] != 'cpu':
        return None
    vals = [float(v) for v in parts[1:]]
    if len(vals) < 5:
        return None
    total = sum(vals)
    idle = vals[3] + vals[4]  # idle + iowait
    return total - idle, total


def _node_pids(proc: str, node_dir: str) -> List[int]:
    """PIDs whose env homes them in this local-cloud node (the provision
    teardown scan, reused for per-node CPU accounting)."""
    node_dir = os.path.realpath(node_dir)
    needles = tuple(
        f'{var}={node_dir}'.encode()
        for var in ('SKYTPU_SKYLET_HOME', 'HOME', 'SKYTPU_NODE_DIR'))
    pids: List[int] = []
    try:
        entries = os.listdir(proc)
    except OSError:
        return pids
    for entry in entries:
        if not entry.isdigit():
            continue
        try:
            with open(os.path.join(proc, entry, 'environ'), 'rb') as f:
                environ = f.read()
        except OSError:
            continue
        for var in environ.split(b'\0'):
            if any(var == n or var.startswith(n + b'/') for n in needles):
                pids.append(int(entry))
                break
    return pids


def _pid_jiffies(proc: str, pid: int) -> Optional[float]:
    """utime+stime of one process (fields 14/15 of /proc/pid/stat)."""
    try:
        with open(os.path.join(proc, str(pid), 'stat'),
                  encoding='utf-8') as f:
            data = f.read()
    except OSError:
        return None
    # comm may contain spaces/parens; fields are counted after the
    # closing paren.
    rparen = data.rfind(')')
    fields = data[rparen + 1:].split()
    if len(fields) < 13:
        return None
    return float(fields[11]) + float(fields[12])


class HostSampler:
    """Stateful sampler: CPU utilization needs deltas between calls, so
    one instance lives for the skylet's lifetime. The first call returns
    memory/disk/load only; CPU appears from the second call on."""

    def __init__(self):
        self._prev_host: Optional[Tuple[float, float]] = None
        self._prev_node: Dict[int, float] = {}
        self._prev_node_ts: Optional[float] = None
        try:
            self._clk_tck = os.sysconf('SC_CLK_TCK') or 100
        except (ValueError, OSError):
            self._clk_tck = 100

    def sample(self) -> Dict[str, float]:
        proc = _proc_root()
        out: Dict[str, float] = {}
        ncpu = os.cpu_count() or 1
        out['ncpu'] = float(ncpu)
        node_dir = os.environ.get('SKYTPU_NODE_DIR')
        if node_dir:
            cpu = self._sample_node_cpu(proc, node_dir, ncpu)
        else:
            cpu = self._sample_host_cpu(proc, ncpu)
        if cpu is not None:
            out['cpu_util'], out['cpu_cores_used'] = cpu
        out.update(self._sample_memory(proc))
        out.update(self._sample_disk())
        out.update(self._sample_load(proc))
        out.update(sample_accelerator())
        return out

    def _sample_host_cpu(self, proc: str, ncpu: int
                         ) -> Optional[Tuple[float, float]]:
        cur = _read_proc_stat_jiffies(proc)
        prev, self._prev_host = self._prev_host, cur
        if cur is None or prev is None:
            return None
        dbusy, dtotal = cur[0] - prev[0], cur[1] - prev[1]
        if dtotal <= 0:
            return None
        util = min(max(dbusy / dtotal, 0.0), 1.0)
        return util, util * ncpu

    def _sample_node_cpu(self, proc: str, node_dir: str, ncpu: int
                         ) -> Optional[Tuple[float, float]]:
        now = time.time()
        cur: Dict[int, float] = {}
        for pid in _node_pids(proc, node_dir):
            j = _pid_jiffies(proc, pid)
            if j is not None:
                cur[pid] = j
        prev, self._prev_node = self._prev_node, cur
        prev_ts, self._prev_node_ts = self._prev_node_ts, now
        if prev_ts is None or now <= prev_ts:
            return None
        # Only pids seen in both samples contribute (a new pid's
        # lifetime CPU would be misattributed to this interval; an
        # exited pid's usage is simply dropped — undercount, never
        # overcount).
        delta = sum(max(0.0, cur[p] - prev[p]) for p in cur if p in prev)
        cores = delta / self._clk_tck / (now - prev_ts)
        return min(cores / ncpu, 1.0), cores

    @staticmethod
    def _sample_memory(proc: str) -> Dict[str, float]:
        try:
            fields = {}
            with open(os.path.join(proc, 'meminfo'),
                      encoding='utf-8') as f:
                for line in f:
                    key, _, rest = line.partition(':')
                    vals = rest.split()
                    if vals:
                        fields[key] = float(vals[0]) * 1024  # kB
        except OSError:
            return {}
        total = fields.get('MemTotal')
        avail = fields.get('MemAvailable')
        if not total or avail is None:
            return {}
        return {'mem_total_bytes': total,
                'mem_used_bytes': total - avail,
                'mem_util': min(max(1.0 - avail / total, 0.0), 1.0)}

    @staticmethod
    def _sample_disk() -> Dict[str, float]:
        try:
            st = os.statvfs(constants.skylet_home())
        except OSError:
            return {}
        total = st.f_blocks * st.f_frsize
        if total <= 0:
            return {}
        free = st.f_bavail * st.f_frsize
        return {'disk_total_bytes': float(total),
                'disk_util': min(max(1.0 - free / total, 0.0), 1.0)}

    @staticmethod
    def _sample_load(proc: str) -> Dict[str, float]:
        try:
            with open(os.path.join(proc, 'loadavg'),
                      encoding='utf-8') as f:
                return {'load1': float(f.read().split()[0])}
        except (OSError, ValueError, IndexError):
            return {}


ACCEL_SAMPLING_ENV = 'SKYTPU_SAMPLER_ACCEL'


def sample_accelerator() -> Dict[str, float]:
    """Accelerator memory stats via JAX, or ``{}`` on CPU-only nodes.

    Gated on ``JAX_PLATFORMS`` naming a non-CPU backend before importing
    jax at all — control-plane processes run with accelerator boot
    stripped (see ``skylet/constants.ACCEL_BOOT_ENVS``) and must not pay
    a multi-second plugin import on every sampler tick just to learn
    there is no chip. The gate is deliberate on real TPU VMs too: libtpu
    is single-client, so a sampler probing the chip would SEIZE it from
    the user's job. Hosts whose runtime can share device stats opt in
    with ``SKYTPU_SAMPLER_ACCEL=1`` (``0`` forces it off; default
    ``auto`` = the ``JAX_PLATFORMS`` gate — see docs/tpu-guide.md).
    ``device.memory_stats()`` is optional per backend; any failure
    degrades to "no accelerator numbers" rather than an error.
    """
    mode = os.environ.get(ACCEL_SAMPLING_ENV, 'auto').strip().lower()
    if mode in ('0', 'off', 'false', 'disabled'):
        return {}
    if mode not in ('1', 'on', 'true', 'force'):
        platforms = os.environ.get('JAX_PLATFORMS', '')
        wanted = {p.strip().lower()
                  for p in platforms.split(',') if p.strip()}
        if not wanted or wanted <= {'cpu'}:
            return {}
    try:
        import jax
        devices = [d for d in jax.local_devices()
                   if d.platform.lower() != 'cpu']
    except Exception:  # pylint: disable=broad-except
        return {}
    in_use = limit = 0.0
    seen = 0
    for dev in devices:
        try:
            stats = dev.memory_stats() or {}
        except Exception:  # pylint: disable=broad-except
            continue
        bytes_in_use = stats.get('bytes_in_use')
        bytes_limit = stats.get('bytes_limit') or stats.get(
            'bytes_reservable_limit')
        if bytes_in_use is None or not bytes_limit:
            continue
        in_use += float(bytes_in_use)
        limit += float(bytes_limit)
        seen += 1
    if not seen or limit <= 0:
        return {}
    return {'accel_count': float(len(devices)),
            'accel_mem_used_bytes': in_use,
            'accel_mem_total_bytes': limit,
            'accel_mem_util': min(max(in_use / limit, 0.0), 1.0)}
