"""Stdlib HTTP exporter: ``/metrics`` (Prometheus text) + ``/healthz``.

Mounted by long-lived processes (serve controller, load balancer) so the
autoscaler's signals, proxy traffic counters, and runtime telemetry are
scrapeable. ``http.server.ThreadingHTTPServer`` on a daemon thread — no
third-party dependency, and a wedged scrape can never block the process
it is observing.
"""
import http.server
import os
import threading
from typing import Optional

from skypilot_tpu.observability import metrics as metrics_lib

METRICS_HOST_ENV = 'SKYTPU_METRICS_HOST'


class MetricsExporter:
    """Serve ``/metrics`` and ``/healthz`` for one registry.

    ``port=0`` binds an ephemeral port (tests); read :attr:`port` after
    :meth:`start`. Binds loopback by default — metrics name services,
    replica topology, and failure breakdowns, which must not leak from a
    public VM IP. Set ``SKYTPU_METRICS_HOST=0.0.0.0`` (or pass ``host``)
    to expose to a real scraper network deliberately.
    """

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 registry: Optional[metrics_lib.MetricsRegistry] = None):
        self._requested_port = port
        self._host = host or os.environ.get(METRICS_HOST_ENV, '127.0.0.1')
        # Resolved lazily so an exporter constructed before a test swaps
        # the global registry still serves the active one.
        self._registry = registry
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        assert self._server is not None, 'exporter not started'
        return self._server.server_port

    def url(self, path: str = '/metrics') -> str:
        host = '127.0.0.1' if self._host == '0.0.0.0' else self._host
        return f'http://{host}:{self.port}{path}'

    def start(self) -> int:
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):

            def do_GET(self):  # noqa: N802
                if self.path.split('?', 1)[0] == '/metrics':
                    registry = (outer._registry or
                                metrics_lib.get_registry())
                    payload = registry.generate_latest()
                    self._reply(200, payload,
                                metrics_lib.CONTENT_TYPE_LATEST)
                elif self.path.split('?', 1)[0] == '/healthz':
                    self._reply(200, b'ok\n', 'text/plain; charset=utf-8')
                else:
                    self.send_error(404)

            def _reply(self, code: int, payload: bytes,
                       content_type: str) -> None:
                self.send_response(code)
                self.send_header('Content-Type', content_type)
                self.send_header('Content-Length', str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass  # scrapes must not spam the observed process's logs

        self._server = http.server.ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name='skytpu-metrics-exporter')
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
